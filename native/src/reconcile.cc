// Native reconcile decision core.
//
// The per-sync decision kernel of the pod reconciler — the hot loop the
// reference runs in compiled Go (pkg/controller.v1/pytorch/pod.go:49-117
// plus the train_util exit-code table) — as pure functions over compact
// rows.  The Python controller extracts (index, phase, exit_code) per
// observed pod, calls rc_plan, then performs the I/O the plan dictates
// (pod creates/deletes, events, status tallies).  Pure decision logic:
// no allocation beyond caller buffers, no locks, trivially testable for
// equivalence against the Python fallback.

#include "tpu_operator.h"

namespace {

// Phase encoding shared with the binding layer (rc_plan docs).
constexpr int kPhaseRunning = 1;
constexpr int kPhaseSucceeded = 2;
constexpr int kPhaseFailed = 3;

}  // namespace

extern "C" {

int rc_retryable_exit_code(int exit_code, int tpu_aware) {
  // Mirror of controller/train_util.py (itself mirroring the
  // reference's train_util.go:18-53 with the TPU extension):
  // permanent: 1,2,126,127,128,139; retryable signals: 130,137,143;
  // user-defined retryable: 138; TPU transients (when tpu_aware):
  // 134 SIGABRT (libtpu chip-lock contention), 135 SIGBUS (slice
  // preemption HBM teardown).
  switch (exit_code) {
    case 1:
    case 2:
    case 126:
    case 127:
    case 128:
    case 139:
      return 0;
    case 130:
    case 137:
    case 143:
      return 1;
    case 138:
      return 1;
    case 134:
    case 135:
      return tpu_aware ? 1 : 0;
    default:
      return 0;
  }
}

int rc_plan(int replicas, int restart_policy_exit_code, int tpu_aware,
            const int* pods, int n_pods, int* create_out, int* n_create,
            int* delete_out, int* n_delete, int* warn_out, int* n_warn,
            int* counts, int* restart_out) {
  *n_create = 0;
  *n_delete = 0;
  *n_warn = 0;
  counts[0] = counts[1] = counts[2] = 0;  // active, succeeded, failed
  *restart_out = 0;
  if (replicas < 0 || n_pods < 0) return -1;

  // Slice occupancy: count pods per in-range index and remember the row
  // of the single occupant (only single-occupant slices get status
  // tallies and retry decisions — pod.go:56-92 semantics).
  // replicas is bounded by the CRD schema (small); stack VLA avoided
  // for portability — use a fixed cap with overflow guard.
  constexpr int kMaxReplicas = 4096;
  if (replicas > kMaxReplicas) return -1;
  int occupancy[kMaxReplicas];
  int sole_row[kMaxReplicas];
  for (int i = 0; i < replicas; ++i) {
    occupancy[i] = 0;
    sole_row[i] = -1;
  }
  for (int r = 0; r < n_pods; ++r) {
    int index = pods[r * 3];
    if (index < 0 || index >= replicas) continue;  // get_pod_slices drop
    if (++occupancy[index] == 1) {
      sole_row[index] = r;
    }
  }

  for (int i = 0; i < replicas; ++i) {
    if (occupancy[i] == 0) {
      create_out[(*n_create)++] = i;
    } else if (occupancy[i] > 1) {
      warn_out[(*n_warn)++] = i;
    } else {
      int r = sole_row[i];
      int phase = pods[r * 3 + 1];
      int exit_code = pods[r * 3 + 2];
      if (restart_policy_exit_code && phase == kPhaseFailed &&
          rc_retryable_exit_code(exit_code, tpu_aware)) {
        delete_out[(*n_delete)++] = r;
        *restart_out = 1;
      }
      if (phase == kPhaseRunning) {
        ++counts[0];
      } else if (phase == kPhaseSucceeded) {
        ++counts[1];
      } else if (phase == kPhaseFailed) {
        ++counts[2];
      }
    }
  }
  return 0;
}

}  // extern "C"
