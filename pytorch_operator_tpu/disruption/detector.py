"""Pure disruption predicates over node/pod wire objects.

Detection sources, in the order a GCE preemption usually surfaces them:

  1. node taints — GCE taints the node with
     ``cloud.google.com/impending-node-termination`` ahead of a
     preemptible/spot VM termination; ``node.kubernetes.io/unreachable``
     / ``not-ready`` are the node-lifecycle controller's verdicts after
     the VM is already gone;
  2. pod ``DisruptionTarget`` conditions — the eviction machinery marks
     the doomed pod directly;
  3. a TPU node whose Ready condition goes false — a dead TPU VM without
     any taint (hard crashes skip the polite notice).

All functions are side-effect free so the unit tier can table-test them.
"""

from __future__ import annotations

from typing import Optional

from ..api.v1 import constants

# Taint keys that mean "this node is going away" (detection source 1).
# Defined once in api/v1/constants.py, shared with the chaos injector
# (k8s.fake_kubelet) so injection and recognition cannot drift;
# re-exported here for the detector's public surface.
IMPENDING_NODE_TERMINATION_TAINT = constants.IMPENDING_NODE_TERMINATION_TAINT
NODE_UNREACHABLE_TAINT = constants.NODE_UNREACHABLE_TAINT
NODE_NOT_READY_TAINT = constants.NODE_NOT_READY_TAINT
NODE_OUT_OF_SERVICE_TAINT = constants.NODE_OUT_OF_SERVICE_TAINT
CLOUD_NODE_SHUTDOWN_TAINT = constants.CLOUD_NODE_SHUTDOWN_TAINT
DISRUPTION_TAINT_KEYS = constants.DISRUPTION_TAINT_KEYS


def is_tpu_node(node: dict) -> bool:
    """A node that carries google.com/tpu capacity (or the GKE TPU
    accelerator label — capacity may be momentarily absent while the
    device plugin restarts)."""
    status = node.get("status") or {}
    for field in ("capacity", "allocatable"):
        if (status.get(field) or {}).get(constants.TPU_RESOURCE):
            return True
    labels = (node.get("metadata") or {}).get("labels") or {}
    return constants.NODE_SELECTOR_TPU_ACCELERATOR in labels


def _node_ready(node: dict) -> Optional[bool]:
    """Tri-state Ready: True/False from the condition, None when the
    node reports no Ready condition at all."""
    for cond in (node.get("status") or {}).get("conditions") or []:
        if cond.get("type") == "Ready":
            return cond.get("status") == "True"
    return None


def node_disruption_reason(node: dict) -> Optional[str]:
    """The disruption taint key when the node is going away, the string
    ``"TPUNodeNotReady"`` for a TPU node that lost readiness, else None
    (healthy)."""
    taints = (node.get("spec") or {}).get("taints") or []
    for taint in taints:
        if taint.get("key") in DISRUPTION_TAINT_KEYS:
            return taint.get("key")
    if is_tpu_node(node) and _node_ready(node) is False:
        return "TPUNodeNotReady"
    return None


def node_schedulable_tpu(node: dict) -> bool:
    """A TPU node that can take new work: Ready and carrying no taints
    at all (unrelated NoSchedule taints keep it out of the pool exactly
    like the fake kubelet's binding rule).  The capacity watcher's
    definition of "capacity returned"."""
    if not is_tpu_node(node):
        return False
    if (node.get("spec") or {}).get("taints"):
        return False
    return _node_ready(node) is True


def pod_disruption_reason(pod: dict) -> Optional[str]:
    """``DisruptionTarget`` condition reason (or the condition type when
    no reason is set) for a pod the eviction machinery has marked; None
    otherwise."""
    for cond in (pod.get("status") or {}).get("conditions") or []:
        if (cond.get("type") == constants.POD_CONDITION_DISRUPTION_TARGET
                and cond.get("status") == "True"):
            return cond.get("reason") or constants.POD_CONDITION_DISRUPTION_TARGET
    return None
