"""Fake kubelet: advances pod phases like a node would.

The reference has no simulation tier between fake-control unit tests and a
real GKE cluster (SURVEY.md §4).  This fills that gap: subscribed to the
fake cluster's pod store, it walks created pods through
Pending -> Running -> Succeeded/Failed on a background thread, so the full
controller loop (informers, workqueue, status machine, GC) can be
exercised end-to-end in-process — the e2e driver
(test/e2e/v1/default/defaults.go) flow without a cluster.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from .errors import NotFoundError
from .fake import ADDED, FakeCluster


class FakeKubelet:
    def __init__(
        self,
        cluster: FakeCluster,
        run_delay: float = 0.02,
        complete_delay: float = 0.05,
        # decide(pod) -> ("Succeeded"|"Failed", exit_code), or None to
        # leave the pod Running forever.
        decide: Optional[Callable[[dict], Optional[tuple]]] = None,
        # logs(pod, phase, exit_code) -> str stored on the pod, readable
        # via the SDK's get_logs (fake.kubelet/logs annotation)
        logs: Optional[Callable[[dict, str, int], str]] = None,
    ):
        self.cluster = cluster
        self.run_delay = run_delay
        self.complete_delay = complete_delay
        self.decide = decide or (lambda pod: ("Succeeded", 0))
        self.logs = logs or (
            lambda pod, phase, code:
            f"{pod['metadata']['name']}: {phase} exit={code}\naccuracy=0.9876\n"
        )
        self._timers: Dict[str, threading.Timer] = {}
        self._lock = threading.Lock()
        self._stopped = False

    def start(self) -> None:
        self.cluster.pods.add_listener(self._on_pod_event)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            for t in self._timers.values():
                t.cancel()
            self._timers.clear()
        self.cluster.pods.remove_listener(self._on_pod_event)

    # ------------------------------------------------------------------
    def _on_pod_event(self, event_type: str, pod: dict) -> None:
        if event_type != ADDED:
            return
        meta = pod.get("metadata", {})
        ns, name = meta.get("namespace", "default"), meta.get("name", "")
        self._set_phase(ns, name, "Pending")
        self._schedule(f"{ns}/{name}/run", self.run_delay, self._run_pod, ns, name)

    def _run_pod(self, ns: str, name: str) -> None:
        self._set_phase(ns, name, "Running")
        self._schedule(
            f"{ns}/{name}/complete", self.complete_delay, self._complete_pod, ns, name
        )

    def _complete_pod(self, ns: str, name: str) -> None:
        try:
            pod = self.cluster.pods.get(ns, name)
        except NotFoundError:
            return
        decision = self.decide(pod)
        if decision is None:
            return
        phase, exit_code = decision
        status = {
            "phase": phase,
            "containerStatuses": [
                {
                    "name": "pytorch",
                    "restartCount": 0,
                    "state": {"terminated": {"exitCode": exit_code}},
                }
            ],
        }
        try:
            # logs BEFORE the terminal status: a process writes its
            # output and then exits, and follow-mode log streams close
            # on the terminal phase — writing the text first guarantees
            # a tailer sees the final lines before the stream ends
            log_text = self.logs(pod, phase, exit_code)
            if log_text:
                self.cluster.pods.patch(ns, name, {
                    "metadata": {"annotations": {"fake.kubelet/logs": log_text}}
                })
            self.cluster.pods.set_status(ns, name, status)
        except NotFoundError:
            pass

    def _set_phase(self, ns: str, name: str, phase: str) -> None:
        try:
            self.cluster.pods.set_status(ns, name, {"phase": phase})
        except NotFoundError:
            pass

    def _schedule(self, key: str, delay: float, fn, *args) -> None:
        with self._lock:
            if self._stopped:
                return
            timer = threading.Timer(delay, fn, args=args)
            timer.daemon = True
            self._timers[key] = timer
            timer.start()
