"""Active-active sharded control plane (ISSUE 7): consistent-hash job
sharding, per-shard Lease ownership with fair rebalancing, shard-filtered
informer sources, the windowed (watch-cache) relist, the per-endpoint
circuit breaker, the controller-owned fan-out executor — and the e2e
satellite: a mid-churn replica kill whose shards are re-acquired with
zero duplicate creates."""

from __future__ import annotations

import threading
import time

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.k8s.stub_server import StubApiServer
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import JobControllerConfig
from pytorch_operator_tpu.runtime.informer import Informer
from pytorch_operator_tpu.runtime.leader_election import LeaderElector
from pytorch_operator_tpu.runtime.sharding import (
    LabelFilteredSource,
    ShardManager,
    shard_of,
    shard_selector,
)


def wait_for(cond, timeout=10.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def new_job(name, workers=1, namespace="default"):
    tmpl = {"spec": {"containers": [{"name": "pytorch", "image": "img:1"}]}}
    return {
        "apiVersion": "kubeflow.org/v1", "kind": "PyTorchJob",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {"pytorchReplicaSpecs": {
            "Master": {"replicas": 1, "restartPolicy": "OnFailure",
                       "template": tmpl},
            "Worker": {"replicas": workers, "restartPolicy": "OnFailure",
                       "template": tmpl},
        }},
    }


def _condition_true(job, cond_type):
    for c in (job.get("status") or {}).get("conditions") or []:
        if c["type"] == cond_type and c["status"] == "True":
            return True
    return False


# ---------------------------------------------------------------------------
# consistent hash


class TestShardOf:
    def test_deterministic_and_bounded(self):
        for count in (1, 2, 4, 7):
            s = shard_of("ns", "uid-123", count)
            assert 0 <= s < count
            assert s == shard_of("ns", "uid-123", count)

    def test_single_shard_is_always_zero(self):
        assert shard_of("any", "thing", 1) == 0

    def test_spread_is_roughly_uniform(self):
        counts = [0] * 4
        for i in range(400):
            counts[shard_of("default", f"uid-{i}", 4)] += 1
        # blake2b over 400 keys: every shard gets a meaningful share
        assert min(counts) > 50, counts

    def test_namespace_is_part_of_the_key(self):
        hits = {shard_of(f"ns-{i}", "same-uid", 16) for i in range(32)}
        assert len(hits) > 1


# ---------------------------------------------------------------------------
# LeaderElector release / empty-holder semantics


class TestLeaseRelease:
    def test_release_writes_empty_holder_and_is_instantly_acquirable(self):
        cluster = FakeCluster()
        leases = cluster.resource("leases")
        a = LeaderElector(leases, "a", name="shard-x",
                          lease_duration=30.0)
        b = LeaderElector(leases, "b", name="shard-x",
                          lease_duration=30.0)
        assert a.try_acquire_or_renew()
        # b cannot take a live lease
        assert not b.try_acquire_or_renew()
        a.is_leader = True
        a.release()
        lease = leases.get("default", "shard-x")
        assert lease["spec"]["holderIdentity"] == ""
        # empty holder: no expiry wait
        assert b.try_acquire_or_renew()
        assert leases.get("default", "shard-x")["spec"][
            "holderIdentity"] == "b"

    def test_release_is_noop_when_someone_else_holds(self):
        cluster = FakeCluster()
        leases = cluster.resource("leases")
        a = LeaderElector(leases, "a", name="shard-y")
        b = LeaderElector(leases, "b", name="shard-y")
        assert a.try_acquire_or_renew()
        b.release()  # b never held it
        assert leases.get("default", "shard-y")["spec"][
            "holderIdentity"] == "a"

    def test_observe_tracks_expiry_without_competing(self):
        now = [0.0]
        cluster = FakeCluster()
        leases = cluster.resource("leases")
        holder = LeaderElector(leases, "h", name="shard-z",
                               lease_duration=5.0,
                               clock=lambda: now[0])
        watcher = LeaderElector(leases, "w", name="shard-z",
                                lease_duration=5.0,
                                clock=lambda: now[0])
        assert holder.try_acquire_or_renew()
        who, acquirable = watcher.observe()
        assert who == "h" and not acquirable
        # record frozen (holder dead): acquirable after a full duration
        now[0] += 4.9
        assert watcher.observe() == ("h", False)
        now[0] += 0.2
        who, acquirable = watcher.observe()
        assert who == "h" and acquirable
        # and observe() never wrote anything
        assert leases.get("default", "shard-z")["spec"][
            "holderIdentity"] == "h"


# ---------------------------------------------------------------------------
# ShardManager fairness / rebalance (fake clock, manual ticks)


class TestShardManager:
    def _manager(self, cluster, identity, clock, shards=4, events=None):
        log = events if events is not None else []

        def on_acq(s):
            log.append((identity, "acquired", s))

        def on_rel(s):
            log.append((identity, "released", s))

        return ShardManager(
            cluster.resource("leases"), identity, shards,
            lease_duration=5.0, renew_interval=1.0,
            on_acquired=on_acq, on_released=on_rel,
            clock=lambda: clock[0])

    def test_lone_replica_owns_everything(self):
        clock = [0.0]
        cluster = FakeCluster()
        m1 = self._manager(cluster, "m1", clock)
        m1.tick()
        assert m1.owned_shards() == {0, 1, 2, 3}

    def test_join_rebalances_to_fair_share(self):
        clock = [0.0]
        events = []
        cluster = FakeCluster()
        m1 = self._manager(cluster, "m1", clock, events=events)
        m2 = self._manager(cluster, "m2", clock, events=events)
        m1.tick()
        assert len(m1.owned_shards()) == 4
        # m2 joins: its heartbeat makes it a member, but every shard is
        # live-held — it acquires nothing yet
        m2.tick()
        assert m2.owned_shards() == set()
        # m1 now sees two members -> fair share 2 -> releases two
        clock[0] += 1.0
        m1.tick()
        assert len(m1.owned_shards()) == 2
        # the released (empty-holder) shards are immediately acquirable
        m2.tick()
        assert len(m2.owned_shards()) == 2
        assert m1.owned_shards() | m2.owned_shards() == {0, 1, 2, 3}
        assert m1.owned_shards().isdisjoint(m2.owned_shards())
        released = [e for e in events if e[0] == "m1" and e[1] == "released"]
        assert len(released) == 2

    def test_uneven_shard_count_still_gives_every_replica_a_share(self):
        """4 shards / 3 replicas: a ceil-for-everyone fair share would
        leave two incumbents at 2+2 and strand the joiner at zero; the
        ranked floor/remainder quota must converge to 2/1/1."""
        clock = [0.0]
        cluster = FakeCluster()
        managers = [self._manager(cluster, f"m{i}", clock) for i in range(3)]
        for _ in range(6):
            for m in managers:
                m.tick()
            clock[0] += 1.0
        counts = sorted(len(m.owned_shards()) for m in managers)
        assert counts == [1, 1, 2], counts
        union = set()
        for m in managers:
            assert union.isdisjoint(m.owned_shards())
            union |= m.owned_shards()
        assert union == {0, 1, 2, 3}

    def test_dead_replica_shards_are_taken_over_after_expiry(self):
        clock = [0.0]
        cluster = FakeCluster()
        m1 = self._manager(cluster, "m1", clock)
        m2 = self._manager(cluster, "m2", clock)
        for _ in range(3):  # converge to 2/2
            m1.tick()
            m2.tick()
            clock[0] += 1.0
        assert len(m1.owned_shards()) == 2 and len(m2.owned_shards()) == 2
        # m1 dies (stops ticking, nothing released); m2 observes the
        # frozen records, then takes over after a full lease duration
        m2.tick()
        clock[0] += 5.2
        m2.tick()
        assert m2.owned_shards() == {0, 1, 2, 3}

    def test_graceful_stop_releases_for_instant_takeover(self):
        clock = [0.0]
        cluster = FakeCluster()
        m1 = self._manager(cluster, "m1", clock)
        m1.tick()
        m1.stop()  # no thread: releases inline
        assert m1.owned_shards() == set()
        m2 = self._manager(cluster, "m2", clock)
        m2.tick()  # no expiry wait needed
        assert m2.owned_shards() == {0, 1, 2, 3}
        # the dead replica's heartbeat lease is gone too
        names = [l["metadata"]["name"]
                 for l in cluster.resource("leases").list()]
        assert not any(n.startswith("pytorch-operator-replica-m1")
                       for n in names)


# ---------------------------------------------------------------------------
# label-filtered sources


class TestLabelFilteredSource:
    def test_list_and_events_are_filtered(self):
        cluster = FakeCluster()
        src = LabelFilteredSource(cluster.pods, shard_selector(1))
        seen = []
        src.add_listener(lambda et, obj: seen.append(
            (et, (obj.get("metadata") or {}).get("name"))))
        cluster.pods.create("default", {
            "metadata": {"name": "mine",
                         "labels": {constants.LABEL_SHARD: "1"}},
            "spec": {}})
        cluster.pods.create("default", {
            "metadata": {"name": "other",
                         "labels": {constants.LABEL_SHARD: "2"}},
            "spec": {}})
        cluster.pods.create("default", {
            "metadata": {"name": "unlabeled"}, "spec": {}})
        assert [p["metadata"]["name"] for p in src.list()] == ["mine"]
        assert seen == [("ADDED", "mine")]
        # GAP passes through unfiltered (relist healing must fire)
        src._wrappers[list(src._wrappers)[0]]("GAP", {})
        assert seen[-1] == ("GAP", None)
        cluster.pods.delete("default", "mine")
        assert ("DELETED", "mine") in seen

    def test_remove_listener_unsubscribes_the_wrapper(self):
        cluster = FakeCluster()
        src = LabelFilteredSource(cluster.pods, shard_selector(0))
        seen = []
        fn = lambda et, obj: seen.append(et)
        src.add_listener(fn)
        src.remove_listener(fn)
        cluster.pods.create("default", {
            "metadata": {"name": "p",
                         "labels": {constants.LABEL_SHARD: "0"}},
            "spec": {}})
        assert seen == []


# ---------------------------------------------------------------------------
# watch-cache windowed relist


class TestWindowedRelist:
    def test_changes_since_returns_delta_including_deletes(self):
        cluster = FakeCluster()
        cluster.pods.create("default", {"metadata": {"name": "a"},
                                        "spec": {}})
        mark = cluster.current_rv()
        cluster.pods.create("default", {"metadata": {"name": "b"},
                                        "spec": {}})
        cluster.pods.patch("default", "a",
                           {"metadata": {"labels": {"x": "1"}}})
        cluster.pods.delete("default", "b")
        changed, deleted, rv = cluster.pods.changes_since(mark)
        assert [o["metadata"]["name"] for o in changed] == ["a"]
        assert [o["metadata"]["name"] for o in deleted] == ["b"]
        assert rv == cluster.current_rv()
        # nothing since the current mark: empty delta, not None
        changed, deleted, _ = cluster.pods.changes_since(rv)
        assert changed == [] and deleted == []

    def test_out_of_window_requires_full_list(self):
        cluster = FakeCluster(watch_cache_window=4)
        for i in range(8):
            cluster.pods.create("default", {"metadata": {"name": f"p{i}"},
                                            "spec": {}})
        assert cluster.pods.changes_since(1) is None
        full = cluster.pods.list_changes(1)
        assert not full.windowed and len(full.items) == 8

    def test_stub_server_serves_windowed_list(self):
        from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster

        srv = StubApiServer().start()
        rest = RestCluster(KubeConfig("127.0.0.1", srv.port))
        try:
            srv.cluster.pods.create("default", {"metadata": {"name": "a"},
                                                "spec": {}})
            mark = srv.cluster.current_rv()
            srv.cluster.pods.create("default", {"metadata": {"name": "b"},
                                                "spec": {}})
            srv.cluster.pods.delete("default", "a")
            delta = rest.pods.list_changes(mark)
            assert delta.windowed
            assert [o["metadata"]["name"] for o in delta.items] == ["b"]
            assert [o["metadata"]["name"] for o in delta.deleted] == ["a"]
            assert delta.resource_version == srv.cluster.current_rv()
            # an RV from before the dawn of the window on a tiny cache
            srv.cluster.watch_cache_window = 1
            for i in range(4):
                srv.cluster.pods.create(
                    "default", {"metadata": {"name": f"x{i}"}, "spec": {}})
            full = rest.pods.list_changes(mark)
            assert not full.windowed and full.deleted == []
        finally:
            rest.close()
            srv.stop()

    def test_informer_gap_heal_uses_delta_not_full_list(self):
        """After a GAP the informer heals through list_changes: the
        delta applies adds/mods/deletes — and the FULL list is never
        consulted (a poisoned .list proves it)."""
        cluster = FakeCluster()
        cluster.pods.create("default", {"metadata": {"name": "keep"},
                                        "spec": {}})
        cluster.pods.create("default", {"metadata": {"name": "gone"},
                                        "spec": {}})
        informer = Informer(cluster.pods)
        informer.start()
        assert informer.store.contains("default/keep")
        # watch goes deaf (the GAP scenario)
        cluster.pods.remove_listener(informer._on_watch_event)
        cluster.pods.delete("default", "gone")
        cluster.pods.create("default", {"metadata": {"name": "new"},
                                        "spec": {}})
        cluster.pods.patch("default", "keep",
                           {"metadata": {"labels": {"x": "1"}}})
        poisoned = cluster.pods.list

        def exploding_list(*a, **kw):
            raise AssertionError("full LIST used where the windowed "
                                 "delta should have served")

        cluster.pods.list = exploding_list
        try:
            informer._on_watch_event("GAP", {})
        finally:
            cluster.pods.list = poisoned
        assert not informer.store.contains("default/gone")
        assert informer.store.contains("default/new")
        assert (informer.store.get_by_key("default/keep")["metadata"]
                ["labels"]["x"]) == "1"


# ---------------------------------------------------------------------------
# per-endpoint circuit breaker


class TestEndpointBreaker:
    def test_same_endpoint_shares_one_breaker(self):
        from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster

        srv = StubApiServer().start()
        try:
            a = RestCluster(KubeConfig("127.0.0.1", srv.port))
            b = RestCluster(KubeConfig("127.0.0.1", srv.port))
            assert a.breaker is b.breaker
            a.close()
            b.close()
        finally:
            srv.stop()

    def test_different_endpoints_do_not_share(self):
        from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster

        s1 = StubApiServer().start()
        s2 = StubApiServer().start()
        try:
            a = RestCluster(KubeConfig("127.0.0.1", s1.port))
            b = RestCluster(KubeConfig("127.0.0.1", s2.port))
            assert a.breaker is not b.breaker
            # one endpoint's failures cannot trip the other's client
            for _ in range(a.breaker.threshold):
                a.breaker.on_failure()
            assert a.breaker.state == "open"
            assert b.breaker.state == "closed"
            a.close()
            b.close()
        finally:
            s1.stop()
            s2.stop()

    def test_breaker_config_is_part_of_the_key(self):
        from pytorch_operator_tpu.k8s.resilience import breaker_for_endpoint

        x = breaker_for_endpoint("host:1", 3, 1.0)
        y = breaker_for_endpoint("host:1", 3, 1.0)
        z = breaker_for_endpoint("host:1", 5, 1.0)
        assert x is y and x is not z


# ---------------------------------------------------------------------------
# controller-owned fan-out executor


class TestFanoutExecutor:
    def test_explicit_width_owns_a_private_concurrent_pool(self):
        from pytorch_operator_tpu.runtime.controls import FanoutExecutor

        ex = FanoutExecutor(width=4)
        barrier = threading.Barrier(4, timeout=5)
        results = ex.run(lambda i: barrier.wait() or i, list(range(4)))
        assert [e for _, e in results] == [None] * 4
        ex.shutdown()
        with pytest.raises(RuntimeError):
            ex.run(lambda i: i, list(range(4)))

    def test_width_one_stays_sequential_and_ordered(self):
        from pytorch_operator_tpu.runtime.controls import FanoutExecutor

        ex = FanoutExecutor(width=1)
        order = []
        ex.run(lambda i: order.append(i), list(range(5)))
        assert order == list(range(5))
        ex.shutdown()

    def test_controller_injects_its_executor_into_controls(self):
        from pytorch_operator_tpu.controller import PyTorchController

        cluster = FakeCluster()
        ctl = PyTorchController(
            cluster,
            config=JobControllerConfig(create_fanout_width=2),
            registry=Registry())
        assert ctl.pod_control._executor is ctl.fanout
        assert ctl.service_control._executor is ctl.fanout
        assert ctl.fanout.width == 2
        ctl.shutdown()
        assert ctl.fanout._shutdown


# ---------------------------------------------------------------------------
# sharded controller semantics (sim tier)


class TestShardedController:
    def _controller(self, cluster, replica_id, shards=2, registry=None):
        from pytorch_operator_tpu.controller import PyTorchController

        cfg = JobControllerConfig(
            shard_count=shards, replica_id=replica_id,
            shard_lease_duration=1.0, shard_renew_interval=0.05)
        return PyTorchController(cluster, config=cfg,
                                 registry=registry or Registry())

    def test_single_replica_mode_builds_no_shard_machinery(self):
        from pytorch_operator_tpu.controller import PyTorchController

        ctl = PyTorchController(FakeCluster(),
                                config=JobControllerConfig(),
                                registry=Registry())
        assert ctl.shard_manager is None
        assert ctl._admission_informer is None
        assert ctl._shard_runtimes == {}
        assert ctl._queue_for_key("ns/j") is ctl.work_queue
        ctl.shutdown()

    def test_jobs_and_children_get_shard_labels_and_converge(self):
        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster)
        kubelet.start()
        registry = Registry()
        ctl = self._controller(cluster, "solo", shards=2,
                               registry=registry)
        stop = threading.Event()
        ctl.run(threadiness=2, stop_event=stop)
        try:
            assert wait_for(lambda: ctl.owned_shards() == {0, 1})
            for j in range(3):
                cluster.jobs.create("default", new_job(f"sj-{j}"))
            assert wait_for(lambda: all(
                _condition_true(cluster.jobs.get("default", f"sj-{j}"),
                                "Succeeded") for j in range(3)),
                timeout=20)
            for j in range(3):
                job = cluster.jobs.get("default", f"sj-{j}")
                shard = job["metadata"]["labels"][constants.LABEL_SHARD]
                meta = job["metadata"]
                assert shard == str(shard_of(meta["namespace"],
                                             meta["uid"], 2))
            for pod in cluster.pods.list("default"):
                assert constants.LABEL_SHARD in pod["metadata"]["labels"]
            for svc in cluster.services.list("default"):
                assert constants.LABEL_SHARD in svc["metadata"]["labels"]
                # the pod selector stays shard-free (pre-stamp pods)
                assert constants.LABEL_SHARD not in svc["spec"]["selector"]
            # owned-shards gauge + per-shard job gauge exported
            text = registry.expose()
            assert "pytorch_operator_owned_shards 2" in text
            assert 'pytorch_operator_shard_jobs{shard="0"}' in text
        finally:
            stop.set()
            ctl.shutdown()
            kubelet.stop()

    def test_modified_into_selector_fires_add_handlers(self):
        """A job PATCHED into the shard selector arrives on the filtered
        watch as MODIFIED — the informer must re-type it to ADDED
        (DeltaFIFO semantics) so add_job (Created condition) runs."""
        cluster = FakeCluster()
        src = LabelFilteredSource(cluster.jobs, shard_selector(1))
        informer = Informer(src)
        adds, updates = [], []
        informer.add_event_handler(
            on_add=lambda o: adds.append(o["metadata"]["name"]),
            on_update=lambda old, new: updates.append(
                new["metadata"]["name"]))
        informer.start()
        cluster.jobs.create("default", new_job("stamped"))
        assert adds == []  # unlabeled: invisible to the filtered source
        cluster.jobs.patch("default", "stamped", {
            "metadata": {"labels": {constants.LABEL_SHARD: "1"}}})
        assert adds == ["stamped"] and updates == []
        assert informer.store.contains("default/stamped")

    def test_migrated_jobs_children_get_stamped(self):
        """Migration: a job (and its children) admitted BEFORE sharding
        was enabled carries no shard labels.  When the owning replica
        stamps the job, it must stamp the existing children too, or the
        shard-filtered pod informer never sees their transitions."""
        cluster = FakeCluster()
        job = cluster.jobs.create("default", new_job("legacy"))
        # pre-sharding children: the job's base labels, no shard label
        base = {constants.LABEL_JOB_NAME: "legacy",
                "group-name": "kubeflow.org",
                "pytorch-job-name": "legacy",
                "controller-name": "pytorch-operator"}
        cluster.pods.create("default", {
            "metadata": {"name": "legacy-master-0", "labels": dict(base)},
            "spec": {}})
        cluster.services.create("default", {
            "metadata": {"name": "legacy-master-0", "labels": dict(base)},
            "spec": {}})
        shard = shard_of("default", job["metadata"]["uid"], 2)
        ctl = self._controller(cluster, "mig", shards=2)
        # claim the job's shard directly (no run loop needed)
        ctl.shard_manager._owned.add(shard)
        ctl._admit_job(job)
        assert (cluster.jobs.get("default", "legacy")["metadata"]
                ["labels"][constants.LABEL_SHARD]) == str(shard)
        assert (cluster.pods.get("default", "legacy-master-0")["metadata"]
                ["labels"][constants.LABEL_SHARD]) == str(shard)
        assert (cluster.services.get("default", "legacy-master-0")
                ["metadata"]["labels"][constants.LABEL_SHARD]) == str(shard)
        ctl.shutdown()

    def test_foreign_disruption_notes_are_ignored_by_non_owners(self):
        """Sharded replicas all watch nodes; only the job's owner may
        note a disruption (non-owners would overcount the metric and
        park keys on their workerless global queue)."""
        cluster = FakeCluster()
        ctl = self._controller(cluster, "non-owner", shards=2)
        # fake an owned-shard runtime with an EMPTY job store: this
        # replica owns shard 0 but not the job below
        class _Rt:
            class job_informer:
                class store:
                    @staticmethod
                    def contains(_key):
                        return False
            queue = ctl.work_queue

            @staticmethod
            def stop():
                pass
        ctl._shard_runtimes[0] = _Rt
        before = ctl.preemptions_detected_counter.value
        ctl._note_disruption("default/foreign-job", "taint", "node-1",
                             uid="u1", node="node-1")
        assert ctl.preemptions_detected_counter.value == before
        assert "default/foreign-job" not in ctl._pending_disruptions
        ctl._shard_runtimes.clear()
        ctl.shutdown()

    def test_only_the_owner_stamps(self):
        cluster = FakeCluster()
        ctl = self._controller(cluster, "non-owner", shards=4)
        # no run(): owns nothing
        obj = cluster.jobs.create("default", new_job("unowned"))
        ctl._admit_job(obj)
        labels = cluster.jobs.get("default", "unowned")["metadata"].get(
            "labels") or {}
        assert constants.LABEL_SHARD not in labels
        ctl.shutdown()


# ---------------------------------------------------------------------------
# the e2e satellite: handoff under churn over HTTP, zero duplicate creates


def test_shard_handoff_under_churn_zero_duplicate_creates():
    """Two sharded replicas against one stub apiserver; replica 0 is
    hard-killed (no Lease release) mid-churn.  Its shards must be
    re-acquired after Lease expiry, every job must reach Succeeded, and
    the server-side POST 409 (duplicate-create) count must be 0."""
    from pytorch_operator_tpu.controller import PyTorchController
    from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster

    srv = StubApiServer().start()
    kubelet = FakeKubelet(srv.cluster)
    kubelet.start()
    url_cfg = lambda: KubeConfig("127.0.0.1", srv.port)
    fleet = []
    for r in range(2):
        registry = Registry()
        rest = RestCluster(url_cfg(), namespace="default",
                           registry=registry)
        cfg = JobControllerConfig(
            shard_count=2, replica_id=f"ho-r{r}",
            shard_lease_duration=0.8, shard_renew_interval=0.1)
        ctl = PyTorchController(rest, config=cfg, registry=registry)
        stop = threading.Event()
        ctl.run(threadiness=2, stop_event=stop)
        fleet.append((ctl, rest, stop))
    jobs = 6
    try:
        assert wait_for(lambda: sum(
            len(c.owned_shards()) for c, _, _ in fleet) == 2, timeout=10)
        assert all(len(c.owned_shards()) == 1 for c, _, _ in fleet)
        for j in range(jobs):
            srv.cluster.jobs.create("default", new_job(f"ho-{j}"))

        def succeeded():
            return sum(
                1 for j in range(jobs)
                if _condition_true(
                    srv.cluster.jobs.get("default", f"ho-{j}"),
                    "Succeeded"))

        # mid-churn crash of replica 0 — no release, survivors must
        # wait out the Lease
        assert wait_for(lambda: succeeded() >= 2, timeout=20)
        ctl0, rest0, stop0 = fleet[0]
        ctl0.shard_manager.kill()
        stop0.set()
        ctl0.shutdown()
        rest0.close()

        assert wait_for(lambda: succeeded() == jobs, timeout=30), (
            f"{succeeded()}/{jobs} Succeeded")
        survivor = fleet[1][0]
        assert wait_for(lambda: survivor.owned_shards() == {0, 1},
                        timeout=10)
        assert srv.counters.get("POST 409", 0) == 0
        pods = srv.cluster.pods.list("default")
        assert len(pods) == jobs * 2
    finally:
        for ctl, rest, stop in fleet[1:]:
            stop.set()
            ctl.shutdown()
            rest.close()
        kubelet.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# ISSUE 8 satellites: per-shard PodNodeIndex union, shard-labeled
# events and leases


class TestPodNodeIndexUnion:
    def test_union_merges_per_shard_buckets(self):
        from pytorch_operator_tpu.disruption.watcher import (
            PodNodeIndex,
            PodNodeIndexUnion,
        )

        union = PodNodeIndexUnion()
        clusters = [FakeCluster(), FakeCluster()]
        for shard, cluster in enumerate(clusters):
            informer = Informer(cluster.pods)
            union.add_index(shard, PodNodeIndex(informer))
            informer.start()
            cluster.pods.create("default", {
                "metadata": {"name": f"s{shard}-pod"},
                "spec": {"nodeName": "node-x"}})
        names = {p["metadata"]["name"] for p in union.pods_on("node-x")}
        assert names == {"s0-pod", "s1-pod"}
        union.remove_index(1)
        names = {p["metadata"]["name"] for p in union.pods_on("node-x")}
        assert names == {"s0-pod"}
        assert union.node_count() == 1

    def test_sharded_disruption_resolves_through_the_union(self):
        """The PR 7 tail: sharded replicas used to fall back to
        cluster-wide pod LISTs for disruption resolution (pod_index was
        None).  Now the union of per-shard indexes backs both watchers,
        and a taint still produces exactly one proactive gang restart —
        with the node's pods resolved from informer state, zero
        apiserver LISTs."""
        from pytorch_operator_tpu.controller import PyTorchController

        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster, decide=lambda pod: None)  # park
        kubelet.start()
        cfg = JobControllerConfig(
            shard_count=2, replica_id="union-repl",
            shard_lease_duration=1.0, shard_renew_interval=0.05,
            enable_disruption_handling=True)
        ctl = PyTorchController(cluster, config=cfg, registry=Registry())
        stop = threading.Event()
        ctl.run(threadiness=2, stop_event=stop)
        try:
            assert wait_for(lambda: ctl.owned_shards() == {0, 1})
            # disruption resolution rides the union (owned-shard
            # scope is exactly right there); capacity occupancy keeps
            # the authoritative cluster fallback — a node hosting
            # another shard's pods must not read as free
            assert ctl._pod_index_union is not None
            assert ctl.disruption_watcher.pod_index \
                is ctl._pod_index_union
            assert ctl.capacity_watcher.pod_index is None
            assert ctl.capacity_watcher.cluster is cluster
            # TPU-requesting template: gang semantics (tpu_auto_gang)
            # are what make the proactive restart eligible
            job = new_job("union-job", workers=1)
            for spec in job["spec"]["pytorchReplicaSpecs"].values():
                spec["template"]["spec"]["containers"][0]["resources"] = {
                    "limits": {"google.com/tpu": "4"}}
            cluster.jobs.create("default", job)
            assert wait_for(lambda: len([
                p for p in cluster.pods.list("default")
                if (p.get("status") or {}).get("phase") == "Running"])
                == 2, timeout=15)
            worker = next(p for p in cluster.pods.list("default")
                          if "worker" in p["metadata"]["name"])
            node = worker["spec"]["nodeName"]
            uids_before = {p["metadata"]["uid"]
                           for p in cluster.pods.list("default")}
            # the union resolves the node's pods from per-shard state
            assert wait_for(lambda: any(
                p["metadata"]["name"] == worker["metadata"]["name"]
                for p in ctl._pod_index_union.pods_on(node)))
            kubelet.taint_node(node)
            assert wait_for(
                lambda: ctl.preemption_gang_restarts_counter.value == 1,
                timeout=15)
            # the proactive restart recreated the WHOLE gang
            assert wait_for(lambda: (
                len(cluster.pods.list("default")) == 2
                and {p["metadata"]["uid"]
                     for p in cluster.pods.list("default")}
                .isdisjoint(uids_before)), timeout=15)
        finally:
            stop.set()
            ctl.shutdown()
            kubelet.stop()


class TestShardLabeledEventsAndLeases:
    def test_events_inherit_the_involved_jobs_shard_label(self):
        from pytorch_operator_tpu.controller import PyTorchController

        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster)
        kubelet.start()
        cfg = JobControllerConfig(
            shard_count=2, replica_id="ev-repl",
            shard_lease_duration=1.0, shard_renew_interval=0.05)
        ctl = PyTorchController(cluster, config=cfg, registry=Registry())
        stop = threading.Event()
        ctl.run(threadiness=2, stop_event=stop)
        try:
            assert wait_for(lambda: ctl.owned_shards() == {0, 1})
            cluster.jobs.create("default", new_job("ev-job"))
            assert wait_for(lambda: _condition_true(
                cluster.jobs.get("default", "ev-job"), "Succeeded"),
                timeout=20)
            job = cluster.jobs.get("default", "ev-job")
            shard = job["metadata"]["labels"][constants.LABEL_SHARD]
            events = cluster.events.list("default")
            assert events, "the lifecycle should have emitted events"
            for ev in events:
                assert (ev["metadata"].get("labels") or {}).get(
                    constants.LABEL_SHARD) == shard
            # a shard-selector list isolates exactly this shard's stream
            assert cluster.events.list(
                "default", {constants.LABEL_SHARD: shard}) == events
        finally:
            stop.set()
            ctl.shutdown()
            kubelet.stop()

    def test_shard_and_heartbeat_leases_carry_role_labels(self):
        cluster = FakeCluster()
        store = cluster.resource("leases")
        manager = ShardManager(store, "lbl-repl", 2,
                               lease_duration=5.0, renew_interval=1.0)
        manager.tick()
        try:
            shard_lease = store.get("default", "pytorch-operator-shard-0")
            labels = shard_lease["metadata"]["labels"]
            assert labels[constants.LABEL_LEASE_COMPONENT] == \
                constants.LEASE_COMPONENT_SHARD
            assert labels[constants.LABEL_SHARD] == "0"
            hb = store.get("default",
                           "pytorch-operator-replica-lbl-repl")
            assert hb["metadata"]["labels"][
                constants.LABEL_LEASE_COMPONENT] == \
                constants.LEASE_COMPONENT_HEARTBEAT
        finally:
            manager.stop()

    def test_pre_label_lease_is_stamped_on_renewal(self):
        """Upgrade path: a Lease minted by a pre-label build gains the
        role labels the first time a labeling build renews it — its
        replica must not stay selector-invisible forever."""
        store = FakeCluster().resource("leases")
        store.create("default", {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": "pytorch-operator-replica-old"},
            "spec": {"holderIdentity": "old-build",
                     "leaseDurationSeconds": 5,
                     "renewTime": "2020-01-01T00:00:00.000000Z"}})
        elector = LeaderElector(
            store, "old-build", name="pytorch-operator-replica-old",
            lease_duration=5.0,
            labels={constants.LABEL_LEASE_COMPONENT:
                    constants.LEASE_COMPONENT_HEARTBEAT})
        assert elector.try_acquire_or_renew()
        lease = store.get("default", "pytorch-operator-replica-old")
        assert lease["metadata"]["labels"][
            constants.LABEL_LEASE_COMPONENT] == \
            constants.LEASE_COMPONENT_HEARTBEAT

    def test_live_members_scans_only_labeled_heartbeats(self):
        """Membership LISTs with the heartbeat selector: shard leases,
        third-party leases and pre-label heartbeats no longer travel
        (nor count).  Safety is unaffected — shard ownership stays
        CAS-guarded by the per-shard Leases themselves."""
        cluster = FakeCluster()
        store = cluster.resource("leases")
        # a third-party lease and an UNLABELED old-build heartbeat
        for name in ("some-other-controller",
                     "pytorch-operator-replica-ghost"):
            store.create("default", {
                "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": {"name": name},
                "spec": {"holderIdentity": "ghost",
                         "leaseDurationSeconds": 3600,
                         "renewTime": "2099-01-01T00:00:00.000000Z"}})
        m1 = ShardManager(store, "m1", 4, lease_duration=5.0,
                          renew_interval=1.0)
        m2 = ShardManager(store, "m2", 4, lease_duration=5.0,
                          renew_interval=1.0)
        m1.tick()
        m2.tick()
        try:
            assert m1.live_members() == {"m1", "m2"}
            assert m2.live_members() == {"m1", "m2"}
        finally:
            m1.stop()
            m2.stop()


# ---------------------------------------------------------------------------
# ISSUE 12 satellite: SIGTERM on a real operator PROCESS releases its
# shard Leases before exit


def test_sigterm_releases_shard_leases_before_exit():
    """A true `cmd/operator.py` subprocess owning shards must, on
    SIGTERM, write empty-holder releases (ShardManager.stop()) before
    exiting — successors acquire instantly instead of waiting out the
    Lease.  The 30s lease duration makes the distinction observable:
    empty holders right after exit can only mean release, not expiry."""
    import os
    import signal
    import subprocess
    import sys as _sys

    srv = StubApiServer().start()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    proc = subprocess.Popen(
        [_sys.executable, "-m", "pytorch_operator_tpu.cmd.operator",
         "--master", f"http://127.0.0.1:{srv.port}",
         "--namespace", "default", "--shard-count", "2",
         "--replica-id", "term-r0",
         "--shard-lease-duration", "30s",
         "--shard-renew-interval", "0.2s",
         "--threadiness", "1", "--monitoring-port", "0"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        text=True)

    def shard_leases():
        return [lease for lease in srv.cluster.resource("leases").list(
            namespace="default",
            label_selector={constants.LABEL_LEASE_COMPONENT:
                            constants.LEASE_COMPONENT_SHARD})]

    try:
        assert wait_for(lambda: sum(
            1 for lease in shard_leases()
            if (lease.get("spec") or {}).get("holderIdentity")
            == "term-r0") == 2, timeout=60), (
            "operator subprocess never acquired its shards; stderr: "
            + (proc.stderr.read() if proc.poll() is not None else "?"))
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=30) == 0
        holders = [(lease.get("spec") or {}).get("holderIdentity")
                   for lease in shard_leases()]
        assert holders == ["", ""], holders
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
        srv.stop()
