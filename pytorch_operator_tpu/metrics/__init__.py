from .prometheus import (
    OPENMETRICS_CONTENT_TYPE,
    TEXT_CONTENT_TYPE,
    Counter,
    CounterVec,
    Gauge,
    GaugeVec,
    Histogram,
    HistogramVec,
    Registry,
    default_registry,
)

__all__ = [
    "Counter",
    "CounterVec",
    "Gauge",
    "GaugeVec",
    "Histogram",
    "HistogramVec",
    "OPENMETRICS_CONTENT_TYPE",
    "Registry",
    "TEXT_CONTENT_TYPE",
    "default_registry",
]
