"""Cluster-scale simulator: deterministic virtual-time kubemark tier.

The stub apiserver + fake kubelet were always a miniature kubemark;
this package grows them into a deliberate one (ROADMAP direction 2):

  * :class:`~pytorch_operator_tpu.sim.clock.VirtualClock` — the one
    injectable time source (generalizing the fake clocks the
    resilience/sharding test tiers grew ad hoc) honored by the
    workqueue's delayed adds, lease renew/expiry, retry backoff, drain
    deadlines and the fake kubelet's phase timers, so a ten-minute
    convergence scenario runs in seconds of real time and is fully
    deterministic — seeded, single-threaded, no wall-clock races;
  * :class:`~pytorch_operator_tpu.sim.fleet.NodeFleet` — thousands of
    virtual TPU nodes with per-node kubelet latency profiles drawn from
    a seeded distribution (plus configurable stragglers), replacing the
    fake kubelet's lazily-minted-node behavior at scale;
  * :mod:`~pytorch_operator_tpu.sim.scale` — the discrete-event driver
    that pumps the controller's workqueue and the virtual clock from
    ONE thread, and the 10k-job / 50k-pod churn scenario behind
    ``bench_control_plane.py --scale``.
"""

from .clock import VirtualClock, VirtualTimer
from .fleet import NodeFleet, NodeProfile
from .scale import (
    ScaleConfig,
    TenancyConfig,
    run_scale,
    run_scenario,
    run_tenancy,
    run_tenancy_scenario,
)

__all__ = [
    "NodeFleet",
    "NodeProfile",
    "ScaleConfig",
    "TenancyConfig",
    "VirtualClock",
    "VirtualTimer",
    "run_scale",
    "run_scenario",
    "run_tenancy",
    "run_tenancy_scenario",
]
