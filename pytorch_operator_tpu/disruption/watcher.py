"""Node-disruption watcher: informer events -> affected gang jobs.

Consumes the node informer (runtime.Informer over the cluster's Nodes)
and, when a node transitions into a disrupted state
(:func:`detector.node_disruption_reason`), resolves the pods bound to it
(``spec.nodeName``) back to their owning jobs through the controller
owner reference and fires
``on_job_disruption(job_key, reason, node, uid=owner_uid)`` once per
(node, reason) transition.  The per-node flag clears when the
node turns healthy again, so a node that is preempted, replaced and
re-tainted later fires again — while taint-update churn on an
already-flagged node stays silent.

The concrete controller (disruption.handler) owns the policy; this class
owns only detection fan-in.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Set

from ..analysis.witness import make_lock
from ..runtime.informer import meta_namespace_key
from .detector import node_disruption_reason, node_schedulable_tpu

_log = logging.getLogger(__name__)


class PodNodeIndex:
    """``spec.nodeName`` -> pod-key index over the pod informer store.

    The watcher used to LIST pods cluster-wide per disrupted node (fine
    at sim scale, O(pods) per node event at fleet scale — the ROADMAP
    scalability item).  This index rides the pod informer's event
    stream instead: adds/updates move the pod between per-node buckets
    (binding arrives as a MODIFIED patch after the ADDED, so moves are
    the common path), deletes drop it, and lookup resolves keys back
    through the informer store — one dict hit per disrupted node
    instead of a cluster-wide scan, and no extra apiserver traffic.
    """

    def __init__(self, informer):
        self._store = informer.store
        self._lock = make_lock("disruption.pod-index")
        self._keys_by_node: Dict[str, Set[str]] = {}
        self._node_of_key: Dict[str, str] = {}
        informer.add_event_handler(
            on_add=self._upsert,
            on_update=lambda _old, new: self._upsert(new),
            on_delete=self._remove,
        )

    def _upsert(self, pod: dict) -> None:
        # the informer store's OWN key function — divergent key logic
        # here would silently fail every pods_on() store lookup
        key = meta_namespace_key(pod)
        node = (pod.get("spec") or {}).get("nodeName") or None
        with self._lock:
            prev = self._node_of_key.get(key)
            if prev == node:
                return
            if prev is not None:
                bucket = self._keys_by_node.get(prev)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self._keys_by_node[prev]
            if node is None:
                self._node_of_key.pop(key, None)
            else:
                self._node_of_key[key] = node
                self._keys_by_node.setdefault(node, set()).add(key)

    def _remove(self, pod: dict) -> None:
        key = meta_namespace_key(pod)
        with self._lock:
            node = self._node_of_key.pop(key, None)
            if node is not None:
                bucket = self._keys_by_node.get(node)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self._keys_by_node[node]

    def pods_on(self, node_name: str) -> List[dict]:
        """Pods currently bound to the node (resolved live from the
        informer store, so callers see fresh objects, not index-time
        snapshots)."""
        with self._lock:
            keys = list(self._keys_by_node.get(node_name, ()))
        pods = []
        for key in keys:
            obj = self._store.get_by_key(key)
            if obj is not None:
                pods.append(obj)
        return pods

    def node_count(self) -> int:
        with self._lock:
            return len(self._keys_by_node)

    def node_names(self) -> Set[str]:
        """Nodes currently hosting at least one indexed pod."""
        with self._lock:
            return set(self._keys_by_node)


class PodNodeIndexUnion:
    """Union view over per-shard :class:`PodNodeIndex` instances.

    A sharded replica never starts the global pod informer (each owned
    shard runs its own shard-filtered one), so a single PodNodeIndex
    would be permanently empty and disruption handling used to fall
    back to cluster-wide LISTs (the PR 7 tail).  Instead, the
    controller registers one index per ACQUIRED shard's pod informer
    here and drops it on release; ``pods_on`` unions the per-shard
    buckets — which is exactly the right scope, because a replica only
    restarts gangs it owns, and every owned job's pods live in an owned
    shard's informer.

    The union covers OWNED shards only: other replicas' pods are
    invisible (their disruptions resolve on their owners).  That scope
    makes it wrong for capacity OCCUPANCY — a node hosting another
    shard's pods is not free — so sharded ``CapacityWatcher``s keep the
    cluster-LIST fallback instead of this view.
    """

    def __init__(self):
        self._lock = make_lock("disruption.sharded-index")
        self._indexes: Dict[int, PodNodeIndex] = {}

    def add_index(self, shard: int, index: PodNodeIndex) -> None:
        with self._lock:
            self._indexes[shard] = index

    def remove_index(self, shard: int) -> None:
        with self._lock:
            self._indexes.pop(shard, None)

    def _snapshot(self) -> List[PodNodeIndex]:
        with self._lock:
            return list(self._indexes.values())

    def pods_on(self, node_name: str) -> List[dict]:
        pods: List[dict] = []
        seen: Set[str] = set()
        for index in self._snapshot():
            for pod in index.pods_on(node_name):
                key = meta_namespace_key(pod)
                if key not in seen:
                    seen.add(key)
                    pods.append(pod)
        return pods

    def node_count(self) -> int:
        nodes: Set[str] = set()
        for index in self._snapshot():
            nodes.update(index.node_names())
        return len(nodes)


class CapacityWatcher:
    """Node informer -> "schedulable TPU capacity returned" events.

    The inverse of :class:`DisruptionWatcher`: it tracks each node's
    schedulable-TPU state (:func:`detector.node_schedulable_tpu`) and
    fires ``on_capacity(node_name)`` once per transition INTO that state
    — a tainted node restored, a NotReady node recovering, or a fresh
    node joining after the initial sync.  The elastic-gang handler uses
    the signal to wake shrunken jobs so they can grow back toward their
    configured replica count.

    ``free_capacity()`` answers the grow precondition: how many
    schedulable TPU nodes currently host no pods (resolved through the
    shared :class:`PodNodeIndex` when available, a cluster-wide LIST
    otherwise).
    """

    def __init__(
        self,
        informer,
        on_capacity: Callable[[str], None],
        pod_index: Optional[PodNodeIndex] = None,
        cluster=None,
    ):
        self.informer = informer
        self.on_capacity = on_capacity
        self.pod_index = pod_index
        self.cluster = cluster
        self._lock = make_lock("disruption.capacity")
        self._schedulable: Dict[str, bool] = {}
        informer.add_event_handler(
            on_add=self._evaluate,
            on_update=lambda _old, new: self._evaluate(new),
            on_delete=self._node_deleted,
        )

    def _evaluate(self, node: dict) -> None:
        name = (node.get("metadata") or {}).get("name", "")
        if not name:
            return
        ok = node_schedulable_tpu(node)
        with self._lock:
            prev = self._schedulable.get(name)
            self._schedulable[name] = ok
        if not ok or prev is True:
            return
        # First sight during the initial LIST is existing capacity, not
        # returning capacity; a node first seen after sync is a genuine
        # join (scale-up) and does fire.
        if prev is None and not self.informer.has_synced():
            return
        _log.info("schedulable TPU capacity returned on node %s", name)
        self.on_capacity(name)

    def _node_deleted(self, node: dict) -> None:
        name = (node.get("metadata") or {}).get("name", "")
        with self._lock:
            self._schedulable.pop(name, None)

    def free_capacity(self) -> int:
        """Schedulable TPU nodes with no pods bound — the slots a
        growing gang can actually land on."""
        occupied_nodes = None
        if self.pod_index is None and self.cluster is not None:
            # no index: build the occupied set ONCE (O(pods)) instead
            # of re-listing every pod per node (O(nodes x pods))
            occupied_nodes = {(p.get("spec") or {}).get("nodeName")
                              for p in self.cluster.pods.list()}
        free = 0
        for node in self.informer.store.list():
            if not node_schedulable_tpu(node):
                continue
            name = (node.get("metadata") or {}).get("name", "")
            if self.pod_index is not None:
                occupied = bool(self.pod_index.pods_on(name))
            elif occupied_nodes is not None:
                occupied = name in occupied_nodes
            else:
                occupied = False
            if not occupied:
                free += 1
        return free


class DisruptionWatcher:
    def __init__(
        self,
        cluster,
        informer,
        on_job_disruption: Callable[..., None],
        kind: str = "PyTorchJob",
        pod_index: Optional[PodNodeIndex] = None,
        journal=None,
    ):
        """``informer`` is a runtime.Informer over ``cluster.nodes``;
        the watcher registers its handlers but leaves start/stop to the
        controller's informer lifecycle.  ``pod_index`` (a PodNodeIndex
        over the pod informer) resolves a disrupted node's pods in one
        dict hit; without it the watcher falls back to the original
        cluster-wide pod LIST per node event."""
        self.cluster = cluster
        self.informer = informer
        self.on_job_disruption = on_job_disruption
        self.kind = kind
        self.pod_index = pod_index
        # flight recorder (runtime.journal.EventJournal): one
        # ``disruption_detected`` event per node transition that flags
        # at least one job
        self.journal = journal
        self._lock = make_lock("disruption.watcher")
        self._flagged: Dict[str, str] = {}  # node name -> last fired reason
        informer.add_event_handler(
            on_add=self._node_added, on_update=self._node_updated,
            on_delete=self._node_deleted,
        )

    # -- informer handlers -------------------------------------------------
    def _node_added(self, node: dict) -> None:
        self._evaluate(node)

    def _node_updated(self, old: dict, new: dict) -> None:
        self._evaluate(new)

    def _node_deleted(self, node: dict) -> None:
        # A deleted node is indistinguishable from a hard preemption with
        # no notice: treat it as unreachable if anything still runs there.
        name = (node.get("metadata") or {}).get("name", "")
        with self._lock:
            already = name in self._flagged
            self._flagged.pop(name, None)
        if not already:
            self._fire(name, "NodeDeleted")

    # -- core --------------------------------------------------------------
    def _evaluate(self, node: dict) -> None:
        name = (node.get("metadata") or {}).get("name", "")
        if not name:
            return
        reason = node_disruption_reason(node)
        with self._lock:
            if reason is None:
                # healthy again: re-arm so the next disruption fires
                self._flagged.pop(name, None)
                return
            if self._flagged.get(name) == reason:
                return  # already fired for this transition
            self._flagged[name] = reason
        self._fire(name, reason)

    def replay_flagged(self) -> None:
        """Re-fire the callback for every node still flagged disrupted.

        Sharded handoff path: a disruption that struck while a shard had
        no owner was dropped by every replica's ownership gate (the
        node watcher fires once per transition, so nobody re-sees it).
        The replica ACQUIRING a shard replays current node state so
        those jobs get their proactive restart after all.  Safe against
        double-restarts: affected jobs are resolved LIVE, so a gang the
        previous owner already restarted has no pods left on the
        disrupted node and simply does not match."""
        with self._lock:
            flagged = dict(self._flagged)
        for name, reason in flagged.items():
            self._fire(name, reason)

    def _fire(self, node_name: str, reason: str) -> None:
        fired = 0
        for job_key, uid in self._affected_jobs(node_name):
            try:
                self.on_job_disruption(job_key, reason, node_name, uid=uid)
                fired += 1
            except Exception:
                _log.exception("disruption callback failed for %s", job_key)
        if fired:
            if self.journal is not None:
                self.journal.record("disruption_detected",
                                    node=node_name, reason=reason,
                                    jobs=fired)
            _log.info("node %s disrupted (%s): flagged %d job(s)",
                      node_name, reason, fired)

    def _affected_jobs(self, node_name: str):
        """(job_key, owner uid) pairs for jobs with a pod bound to the
        node, via controller owner refs.  The uid fences the consumer's
        note against a delete-recreate of the same key."""
        pairs = []
        seen = set()
        if self.pod_index is not None:
            candidates = self.pod_index.pods_on(node_name)
        else:
            candidates = [p for p in self.cluster.pods.list()
                          if (p.get("spec") or {}).get("nodeName")
                          == node_name]
        for pod in candidates:
            meta = pod.get("metadata") or {}
            ref = self._controller_ref(meta)
            if ref is None:
                continue
            key = f'{meta.get("namespace", "default")}/{ref.get("name", "")}'
            if key not in seen:
                seen.add(key)
                pairs.append((key, ref.get("uid") or None))
        return pairs

    def _controller_ref(self, meta: dict) -> Optional[dict]:
        for ref in meta.get("ownerReferences") or []:
            if ref.get("controller") and ref.get("kind") == self.kind:
                return ref
        return None
