"""Llama-family decoder-only transformer, TPU-first.

Flagship model of the framework (the reference's headline scale config is
Llama-2-7B FSDP on v5p-128 — BASELINE.json config 5; the reference itself
contains no model code beyond examples, see SURVEY.md §0).

Design choices for the MXU/XLA:
  * layers are *stacked* (leading n_layers axis) and iterated with
    `lax.scan` — one compiled layer body regardless of depth;
  * all matmuls are einsums over bf16 weights, f32 accumulation left to
    XLA's default dot algorithm;
  * optional `jax.checkpoint` rematerialisation per layer (cfg.remat)
    trades FLOPs for HBM;
  * GQA (n_kv_heads <= n_heads), RoPE, RMSNorm, SwiGLU — standard Llama;
  * every parameter has a PartitionSpec in `param_specs()` so the same
    code runs single-chip or sharded dp/fsdp/tp without edits.

Sharding convention (axes from parallel.mesh):
  dim (model width)   -> fsdp    (ZeRO-3 style weight sharding)
  heads / ffn hidden  -> tp      (tensor parallelism)
  batch               -> dp+fsdp
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_operator_tpu.parallel.mesh import AXIS_DP, AXIS_FSDP, AXIS_TP

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # name of a jax.checkpoint_policies policy (e.g. "dots_saveable",
    # "dots_with_no_batch_dims_saveable") — None reproduces full remat
    # (save nothing, recompute the whole layer in backward).  The
    # special value "save_attn" keeps only the flash kernel's (out,
    # lse) pair per layer (ops.flash_attention.FLASH_SAVE_NAMES): the
    # remat backward then recomputes norms/projections/MLP but never
    # the O(T^2) attention forward — the right trade at 16k/32k where
    # dots policies blow the compile-memory ceiling and full remat pays
    # a ~2x attention tax (BENCH_DETAIL §1b).  Requires use_flash.
    #
    # Round 5: "save_attn+<group>[+<group>...]" additionally saves named
    # per-layer intermediates so the remat backward skips their
    # recompute — groups from LAYER_SAVE_GROUPS ("qkv": post-RoPE
    # projections, "gateup": the SwiGLU branches, "normed": the RMSNorm
    # outputs).  Each group trades HBM for recompute FLOPs;
    # auto_remat_policy picks the richest tier that fits the chip.
    remat_policy: Any = None
    use_flash: bool = False       # pallas flash-attention kernel (ops/)
    use_fused_norm: bool = False  # pallas fused RMSNorm kernel (ops/)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def __post_init__(self):
        if self.dim % self.n_heads:
            raise ValueError("dim must be divisible by n_heads")
        if self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must be divisible by n_kv_heads")


def llama2_7b(**kw) -> LlamaConfig:
    """The BASELINE.json config-5 model (Llama-2-7B)."""
    defaults = dict(
        vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
        n_kv_heads=32, ffn_dim=11008, max_seq_len=4096,
    )
    defaults.update(kw)  # callers may override any default (max_seq_len!)
    return LlamaConfig(**defaults)


def tiny(**kw) -> LlamaConfig:
    """Small config for tests / compile checks / virtual-device dryruns."""
    defaults = dict(
        vocab_size=512, dim=128, n_layers=2, n_heads=8, n_kv_heads=4,
        ffn_dim=256, max_seq_len=256, dtype=jnp.float32,
    )
    defaults.update(kw)
    return LlamaConfig(**defaults)


# ---------------------------------------------------------------------------
# Parameters


def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    """Initialise a parameter pytree; layer params stacked on axis 0."""
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    k_embed, k_layers = jax.random.split(key)

    def dense(key, shape, fan_in):
        scale = fan_in ** -0.5
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(cfg.dtype)

    def layer_stack(key, shape, fan_in):
        # one independent draw per layer, stacked
        keys = jax.random.split(key, cfg.n_layers)
        return jnp.stack([dense(k, shape, fan_in) for k in keys])

    ks = jax.random.split(k_layers, 7)
    L, D, F = cfg.n_layers, cfg.dim, cfg.ffn_dim
    return {
        "embed": dense(k_embed, (cfg.vocab_size, D), D),
        "layers": {
            "attn_norm": jnp.ones((L, D), cfg.dtype),
            "wq": layer_stack(ks[0], (D, nh * hd), D),
            "wk": layer_stack(ks[1], (D, nkv * hd), D),
            "wv": layer_stack(ks[2], (D, nkv * hd), D),
            "wo": layer_stack(ks[3], (nh * hd, D), nh * hd),
            "mlp_norm": jnp.ones((L, D), cfg.dtype),
            "w_gate": layer_stack(ks[4], (D, F), D),
            "w_up": layer_stack(ks[5], (D, F), D),
            "w_down": layer_stack(ks[6], (F, D), F),
        },
        "final_norm": jnp.ones((D,), cfg.dtype),
    }


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpec tree matching init_params output.

    2-D weights shard (dim -> fsdp, heads/ffn -> tp); stacked layer
    weights carry a leading unsharded layer axis; norms replicate.
    """
    del cfg
    fsdp, tp = AXIS_FSDP, AXIS_TP
    return {
        "embed": P(tp, fsdp),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, fsdp, tp),
            "wk": P(None, fsdp, tp),
            "wv": P(None, fsdp, tp),
            "wo": P(None, tp, fsdp),
            "mlp_norm": P(None, None),
            "w_gate": P(None, fsdp, tp),
            "w_up": P(None, fsdp, tp),
            "w_down": P(None, tp, fsdp),
        },
        "final_norm": P(None),
    }


# ---------------------------------------------------------------------------
# Forward


def rms_norm(
    x: jax.Array, weight: jax.Array, eps: float, fused: bool = False
) -> jax.Array:
    if fused:
        from pytorch_operator_tpu.ops import rms_norm as fused_rms_norm

        return fused_rms_norm(x, weight, eps)
    dtype = x.dtype
    x = x.astype(jnp.float32)
    x = x * lax.rsqrt(jnp.mean(jnp.square(x), axis=-1, keepdims=True) + eps)
    return (x * weight.astype(jnp.float32)).astype(dtype)


def rope_table(cfg: LlamaConfig, seq_len: int) -> tuple[jax.Array, jax.Array]:
    half = cfg.head_dim // 2
    freqs = cfg.rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = jnp.arange(seq_len, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles), jnp.sin(angles)  # each (T, head_dim//2)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    # x: (B, T, H, Dh); rotate pairs (x1, x2) in the last dim.
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    c = cos[None, :, None, :]
    s = sin[None, :, None, :]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(x.dtype)


def _attention(q, k, v, cfg: LlamaConfig):
    """Causal attention (B,T,H,Dh)x(B,T,KV,Dh) with GQA broadcast.

    cfg.use_flash routes through the Pallas flash kernel (ops/); the
    dense path materialises the (T, T) scores and lets XLA fuse.
    """
    B, T, H, Dh = q.shape
    groups = cfg.n_heads // cfg.n_kv_heads
    if cfg.use_flash:
        from pytorch_operator_tpu.ops import flash_attention

        # GQA-native kernel: shared K/V streamed per group, never
        # materialised at H heads (ops/flash_attention.py)
        return flash_attention(q, k, v, causal=True)
    if groups > 1:
        k = jnp.repeat(k, groups, axis=2)
        v = jnp.repeat(v, groups, axis=2)
    scores = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32)
    scores = scores * (Dh ** -0.5)
    mask = jnp.tril(jnp.ones((T, T), bool))
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhts,bshd->bthd", probs, v)


# Named per-layer intermediates the composite "save_attn+..." remat
# policies may keep (checkpoint_name tags in _layer).  Saving a group
# removes its recompute from the remat backward:
#   qkv    post-RoPE q/k/v — the flash backward's inputs; saving them
#          skips re-running attn-norm -> 3 projections -> RoPE
#   gateup the SwiGLU branches (post-silu gate, up) — skips re-running
#          mlp-norm -> 2 D x ffn_dim matmuls
#   normed the two RMSNorm outputs — skips only the (bandwidth-bound)
#          norm recompute; they remain the d/dW inputs of the
#          projections either way
LAYER_SAVE_GROUPS = {
    "qkv": ("llama_proj_q", "llama_proj_k", "llama_proj_v"),
    "gateup": ("llama_mlp_gate", "llama_mlp_up"),
    "normed": ("llama_norm_attn", "llama_norm_mlp"),
}


def _layer(h, lp, cfg: LlamaConfig, cos, sin, attn=None):
    from jax.ad_checkpoint import checkpoint_name

    B, T, D = h.shape
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads

    x = rms_norm(h, lp["attn_norm"], cfg.norm_eps, cfg.use_fused_norm)
    x = checkpoint_name(x, "llama_norm_attn")
    q = jnp.einsum("btd,dk->btk", x, lp["wq"]).reshape(B, T, nh, hd)
    k = jnp.einsum("btd,dk->btk", x, lp["wk"]).reshape(B, T, nkv, hd)
    v = jnp.einsum("btd,dk->btk", x, lp["wv"]).reshape(B, T, nkv, hd)
    q = checkpoint_name(apply_rope(q, cos, sin), "llama_proj_q")
    k = checkpoint_name(apply_rope(k, cos, sin), "llama_proj_k")
    v = checkpoint_name(v, "llama_proj_v")
    attn = (attn or _attention)(q, k, v, cfg).reshape(B, T, nh * hd)
    h = h + jnp.einsum("btk,kd->btd", attn, lp["wo"])

    x = rms_norm(h, lp["mlp_norm"], cfg.norm_eps, cfg.use_fused_norm)
    x = checkpoint_name(x, "llama_norm_mlp")
    gate = checkpoint_name(
        jax.nn.silu(jnp.einsum("btd,df->btf", x, lp["w_gate"])),
        "llama_mlp_gate")
    up = checkpoint_name(jnp.einsum("btd,df->btf", x, lp["w_up"]),
                         "llama_mlp_up")
    h = h + jnp.einsum("btf,fd->btd", gate * up, lp["w_down"])
    return h


def make_layer_body(cfg: LlamaConfig, cos, sin, attn=None):
    """The per-layer function (h, layer_params) -> h, wrapped in the
    config's rematerialisation policy.  Shared by every stack driver:
    the lax.scan forwards, the GPipe ring, and the 1F1B stages — so the
    remat semantics (incl. save_attn's flash-residual names) cannot
    diverge between the parallel strategies."""
    body = partial(_layer, cfg=cfg, cos=cos, sin=sin, attn=attn)
    if cfg.remat:
        policy = cfg.remat_policy
        if policy == "auto":
            raise ValueError(
                "remat_policy='auto' is a selection request, not a "
                "policy: resolve it with llama.auto_remat_policy(cfg, "
                "batch, seq_len, ...) and set the returned tier on the "
                "config (the example CLI does this for --remat-policy "
                "auto)")
        if isinstance(policy, str) and (policy == "save_attn"
                                        or policy.startswith("save_attn+")):
            from pytorch_operator_tpu.ops.flash_attention import (
                FLASH_SAVE_NAMES,
            )

            if not cfg.use_flash:
                raise ValueError(
                    "remat_policy='save_attn...' saves the flash kernel's "
                    "(out, lse) residuals and requires use_flash=True")
            names = list(FLASH_SAVE_NAMES)
            for group in policy.split("+")[1:]:
                if group not in LAYER_SAVE_GROUPS:
                    raise ValueError(
                        f"unknown save group {group!r} in remat_policy "
                        f"{policy!r}; known: "
                        f"{sorted(LAYER_SAVE_GROUPS)}")
                names.extend(LAYER_SAVE_GROUPS[group])
            body = jax.checkpoint(
                body, policy=jax.checkpoint_policies.save_only_these_names(
                    *names))
        elif policy:
            body = jax.checkpoint(
                body, policy=getattr(jax.checkpoint_policies, policy))
        else:
            body = jax.checkpoint(body)
    return body


def _forward_with(params: Params, tokens: jax.Array, cfg: LlamaConfig,
                  apply_stack, attn=None, return_hidden: bool = False,
                  positions: jax.Array | None = None,
                  inv_positions: jax.Array | None = None) -> jax.Array:
    """Shared prologue/epilogue around the decoder stack: embed + RoPE
    tables in, final norm + weight-tied head out.  ``apply_stack(layers,
    h, body)`` decides how the stacked blocks run (lax.scan vs the GPipe
    ring); ``attn`` overrides the per-layer attention (the SP forward
    routes it through ring/all-to-all shard_map strategies).
    ``return_hidden`` skips the output head and returns the final-normed
    (B, T, D) hidden states — long-context losses apply the tied head
    per sequence chunk instead (parallel.train.chunked_tied_ce), so the
    (T, vocab) f32 logits never exist as one buffer."""
    T = tokens.shape[1]
    h = jnp.take(params["embed"], tokens, axis=0)
    cos, sin = rope_table(cfg, T)
    if positions is not None:
        # rows arrive in a permuted order (e.g. the zigzag sequence-
        # parallel layout): row j carries global position positions[j],
        # so RoPE must rotate by the true positions, not the row index
        cos, sin = cos[positions], sin[positions]

    body = make_layer_body(cfg, cos, sin, attn=attn)
    h = apply_stack(params["layers"], h, body)
    h = rms_norm(h, params["final_norm"], cfg.norm_eps, cfg.use_fused_norm)
    if inv_positions is not None:
        # restore natural row order on the D-wide hidden states BEFORE
        # the vocab-wide head: un-permuting logits instead would gather
        # vocab/dim times more data and materialise a second full
        # logits buffer (the allocation class that OOMs 32k configs)
        h = h[:, inv_positions]
    if return_hidden:
        return h
    # weight-tied output head
    return jnp.einsum("btd,vd->btv", h, params["embed"]).astype(jnp.float32)


def forward(params: Params, tokens: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """tokens (B, T) int32 -> logits (B, T, vocab) float32."""

    def apply_stack(layers, h, body):
        return lax.scan(lambda h, lp: (body(h, lp), None), h, layers)[0]

    return _forward_with(params, tokens, cfg, apply_stack)


def forward_hidden(params: Params, tokens: jax.Array,
                   cfg: LlamaConfig) -> jax.Array:
    """tokens (B, T) int32 -> final-normed hidden states (B, T, dim).

    The output head is deliberately NOT applied; pair with
    parallel.train.chunked_tied_ce for long sequences, where the
    (T, vocab) f32 logits (and the two same-sized scatter-add buffers
    their CE backward needs) dominate HBM — 3.9 GB each at T=32k/V=32k,
    the allocation that OOMs the 32k single-chip config if the head
    runs unchunked."""

    def apply_stack(layers, h, body):
        return lax.scan(lambda h, lp: (body(h, lp), None), h, layers)[0]

    return _forward_with(params, tokens, cfg, apply_stack,
                         return_hidden=True)


def activation_spec() -> P:
    """Spec for (B, T, D) activations under the (dp, fsdp, tp) mesh."""
    return P((AXIS_DP, AXIS_FSDP), None, AXIS_TP)


def forward_pipelined(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh,
    *,
    n_microbatches: int,
    axis_name: str = "pp",
    return_hidden: bool = False,
) -> jax.Array:
    """Pipeline-parallel forward: the decoder stack runs as GPipe stages.

    The layer stack (leading n_layers axis) is sharded over ``axis_name``
    — each stage holds n_layers/S consecutive decoder blocks — and
    microbatches march through parallel.pipeline.pipeline_apply's
    ppermute ring.  Embedding, final norm and the tied output head run
    replicated outside the pipeline.  Differentiable end to end (reverse
    mode flows back through the ppermutes), so the same path trains —
    see parallel.train.make_pp_train_step.
    """
    from pytorch_operator_tpu.parallel.pipeline import pipeline_apply

    def apply_stack(layers, h, body):
        def stage_fn(layers_local, h):
            return lax.scan(lambda h, lp: (body(h, lp), None),
                            h, layers_local)[0]

        return pipeline_apply(
            layers, h, stage_fn, mesh,
            n_microbatches=n_microbatches, axis_name=axis_name,
            # remat-wrapped bodies are rejected by the vma checker outright
            check_vma=not cfg.remat,
        )

    return _forward_with(params, tokens, cfg, apply_stack,
                         return_hidden=return_hidden)


def forward_sp(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh,
    *,
    axis_name: str = "sp",
    impl: str = "ulysses",
    return_hidden: bool = False,
) -> jax.Array:
    """Sequence-parallel forward for long-context training.

    Activations stay sequence-sharded — (B, T/n, D) per device — through
    every pointwise/matmul op (GSPMD propagates the layout from the
    sharded tokens); only attention, the one op that mixes positions,
    runs a sequence-parallel strategy via shard_map:

      impl="ulysses"      all-to-all re-shard to head parallelism
                          (parallel/ulysses.py; needs n_heads % n == 0)
      impl="ring"         K/V rotation with online softmax
                          (parallel/ring_attention.py; any head count)
      impl="ring_zigzag"  the ring with the zigzag chunk layout —
                          balanced causal load across ranks (each
                          device holds global chunks (i, 2S-1-i)).
                          The permutation happens ONCE per forward:
                          tokens are permuted into zigzag order, the
                          whole stack runs in zigzag space (RoPE
                          rotates by true positions via the
                          ``positions`` gather; norms/MLP/residuals
                          are position-independent; attention uses
                          layout="zigzag_pre"), and the output is
                          un-permuted at the end — two token/output
                          gathers per forward instead of four
                          sequence reshards per LAYER

    Composes with FSDP and pure DP: when the mesh also carries dp/fsdp
    axes (parallel.mesh.make_sp_mesh(..., fsdp=n)), the batch dim of
    every activation shards over them (parallel.mesh.data_axes decides
    which divide B) and the attention shard_maps carry the same batch
    sharding through their in/out specs.  Pair with
    ``sp_fsdp_param_specs`` to additionally shard params + optimizer
    state over fsdp — the Llama-2-7B v5p-128 north-star layout
    (BASELINE.md config 5): weights ZeRO-3-sharded over fsdp, sequence
    over sp, batch over dp×fsdp.

    GQA-native: the ring always rotates UNREPEATED K/V chunks (ICI
    traffic / group), and ulysses shards the kv heads through its
    all-to-all when n_kv_heads divides the sp axis; when it doesn't,
    K/V repeats only to lcm(n_kv_heads, sp) heads — the minimum the
    all-to-all can shard — not to the full H (e.g. H=16/kv=2/sp=8
    moves 8 kv heads over ICI, not 16).  Params replicate
    (``sp_param_specs``) — sequence parallelism shards activations, not
    weights.  Reference scope: the reference scales only DP replica
    count (SURVEY §2.4); long-context is a TPU-build extension (§5).
    """
    from pytorch_operator_tpu.parallel.mesh import data_axes
    from pytorch_operator_tpu.parallel.ring_attention import ring_attention
    from pytorch_operator_tpu.parallel.ulysses import ulysses_attention

    if impl not in ("ulysses", "ring", "ring_zigzag"):
        raise ValueError(f"unknown sp impl {impl!r}")

    batch_axes = data_axes(mesh, tokens.shape[0])
    # SP×TP: a tp axis on the mesh head-shards the attention (each tp
    # shard runs the ring/all-to-all over its own head slice) — pair
    # with llama.param_specs, whose fsdp×tp weight layout produces
    # head-sharded q/k/v at the projections
    from pytorch_operator_tpu.parallel.mesh import head_shard_degree

    head_axes: tuple = (AXIS_TP,) if mesh.shape.get(AXIS_TP, 1) > 1 else ()
    tp_deg = head_shard_degree(mesh, head_axes, cfg.n_heads,
                               cfg.n_kv_heads)

    def attn(q, k, v, cfg):
        # Both SP strategies are GQA-native: the ring rotates unrepeated
        # K/V chunks (ICI traffic / group), and ulysses shards kv heads
        # through the all-to-all when they divide the axis.  When they
        # don't, repeat only to the MINIMAL head count that does —
        # lcm(kv, sp) when it divides H — instead of the full H: e.g.
        # H=16/kv=2/sp=8 moves 8 kv heads over ICI, not 16.  Correct
        # for any repeat factor r with H % (r*kv) == 0: contiguous
        # repeat keeps the query-group -> kv-head mapping, since
        # (h // (H/kv_new)) // r == h // (H/kv).
        sp_deg = mesh.shape[axis_name]
        kv_local = cfg.n_kv_heads // tp_deg  # per-tp-shard kv heads
        if impl == "ulysses" and kv_local % sp_deg:
            # lcm(kv, sp) always divides H for configs ulysses accepts
            # (it requires sp | H, and kv | H by construction), so the
            # minimal repeat is always valid; under SP×TP the counts
            # that must divide are the per-tp-shard ones
            r = math.lcm(kv_local, sp_deg) // kv_local
            k = jnp.repeat(k, r, axis=2)
            v = jnp.repeat(v, r, axis=2)
        if impl == "ulysses":
            return ulysses_attention(q, k, v, mesh, axis_name=axis_name,
                                     use_flash=cfg.use_flash,
                                     batch_axes=batch_axes,
                                     head_axes=head_axes)
        return ring_attention(
            q, k, v, mesh, axis_name=axis_name, batch_axes=batch_axes,
            head_axes=head_axes,
            # the stack already runs in zigzag space for ring_zigzag
            # (tokens permuted once below), so attention takes the
            # pre-permuted fast path — no per-layer gathers
            layout="zigzag_pre" if impl == "ring_zigzag"
            else "contiguous",
        ).astype(q.dtype)

    def apply_stack(layers, h, body):
        # pin the (B, T, D) activations to the sequence-sharded layout
        # (batch over the dp/fsdp data axes, sequence over sp); GSPMD
        # propagates it through every pointwise/matmul op, so the
        # memory-heavy tensors live B/(dp·fsdp) × T/sp per device (the
        # token ints stay replicated — negligible and T+1 is ragged)
        h = lax.with_sharding_constraint(
            h, NamedSharding(mesh, P(batch_axes or None, axis_name, None)))
        return lax.scan(lambda h, lp: (body(h, lp), None), h, layers)[0]

    if impl == "ring_zigzag":
        # permute ONCE into zigzag order and run the whole stack there;
        # everything except attention and RoPE is position-independent,
        # attention takes the zigzag_pre fast path, RoPE rotates by the
        # true positions (the permutation itself), and the natural
        # order is restored on the D-wide hidden states before the head
        from pytorch_operator_tpu.parallel.ring_attention import (
            zigzag_layout,
        )

        perm, inv = zigzag_layout(tokens.shape[1], mesh.shape[axis_name],
                                  axis_name)
        return _forward_with(params, tokens[:, perm], cfg, apply_stack,
                             attn=attn, return_hidden=return_hidden,
                             positions=jnp.asarray(perm),
                             inv_positions=jnp.asarray(inv))
    return _forward_with(params, tokens, cfg, apply_stack, attn=attn,
                         return_hidden=return_hidden)


def sp_param_specs(cfg: LlamaConfig) -> Params:
    """Fully replicated parameter specs for the sequence-parallel layout
    (SP shards activations over the sp axis, never the weights).  For
    configs whose params + optimizer state exceed one chip's HBM, use
    ``sp_fsdp_param_specs`` on a (dp, fsdp, sp) mesh instead."""
    return jax.tree.map(lambda _: P(), param_specs(cfg),
                        is_leaf=lambda x: isinstance(x, P))


def sp_fsdp_param_specs(cfg: LlamaConfig) -> Params:
    """ZeRO-3 parameter specs for the SP×FSDP composition: every weight
    shards its model-dim axis over fsdp; norms replicate (negligible).

    This is the layout that makes BASELINE.md config 5 (Llama-2-7B on a
    v5p-128 slice) expressible: 7B params × ~14 bytes of param+AdamW
    state (~98 GB) do not fit one chip, so the weights and optimizer
    state live 1/fsdp per chip (XLA all-gathers each layer's weights on
    use, reduce-scatters its grads) while the long sequence shards over
    sp (llama.forward_sp) and the batch over dp×fsdp.  Pair with
    parallel.mesh.make_sp_mesh(dp, sp, fsdp=n) and
    parallel.train.make_sp_train_step; init via
    sharded_init(..., specs=llama.sp_fsdp_param_specs(cfg)).
    """
    del cfg
    fsdp = AXIS_FSDP
    return {
        "embed": P(None, fsdp),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, fsdp, None),
            "wk": P(None, fsdp, None),
            "wv": P(None, fsdp, None),
            "wo": P(None, None, fsdp),
            "mlp_norm": P(None, None),
            "w_gate": P(None, fsdp, None),
            "w_up": P(None, fsdp, None),
            "w_down": P(None, None, fsdp),
        },
        "final_norm": P(None),
    }


def n_params(cfg: LlamaConfig) -> int:
    """Parameter count (embed + stacked layers + final norm)."""
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    D, F, L = cfg.dim, cfg.ffn_dim, cfg.n_layers
    per_layer = (D * nh * hd + 2 * D * nkv * hd + nh * hd * D
                 + 3 * D * F + 2 * D)
    return cfg.vocab_size * D + L * per_layer + D


def auto_remat_policy(
    cfg: LlamaConfig,
    batch: int,
    seq_len: int,
    *,
    hbm_gb: float = 16.0,
    reserve_gb: float = 2.5,
    state_shards: int = 1,
    token_shards: int = 1,
) -> str:
    """Pick the richest save_attn tier whose residuals fit the chip.

    Batch-adaptive HBM-headroom math (round-5 verdict item 2): the
    budget is ``hbm_gb`` minus params + optimizer state (AdamW mu/nu in
    the param dtype) minus a transient ``reserve_gb`` (grad buffers,
    chunked-CE scratch, XLA workspace); each candidate tier's per-layer
    saved residuals are priced per token and the richest fitting tier
    wins.

    Sharding divides the two budgets DIFFERENTLY: ``state_shards`` is
    the weight-sharding degree (fsdp only — sp/dp never shard params or
    optimizer state, see sp_param_specs), while ``token_shards`` is the
    activation-sharding degree (dp × fsdp over batch, × sp over
    sequence).  Tiers are ordered by recompute FLOPs removed per saved
    byte — the SwiGLU branches and the post-RoPE q/k/v carry ~equal
    FLOPs/byte, the norm outputs only skip a bandwidth-bound recompute,
    so they come last.
    """
    dsize = jnp.dtype(cfg.dtype).itemsize
    state_bytes = n_params(cfg) * (dsize + 2 * dsize)  # params + mu + nu
    budget = (hbm_gb - reserve_gb) * 2 ** 30 - state_bytes / state_shards
    tokens = batch * seq_len / token_shards
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads

    # bytes/token of saved residuals per layer, by component
    base = dsize * cfg.dim          # the layer-input residual h
    flash = dsize * nh * hd + 4 * nh   # flash out (dtype) + lse (f32)
    per_group = {
        "qkv": dsize * hd * (nh + 2 * nkv),
        "gateup": dsize * 2 * cfg.ffn_dim,
        "normed": dsize * 2 * cfg.dim,
    }
    for tier in ("save_attn+qkv+gateup+normed", "save_attn+qkv+gateup",
                 "save_attn+gateup", "save_attn+qkv",
                 "save_attn+normed", "save_attn"):
        per_token = base + flash + sum(
            per_group[g] for g in tier.split("+")[1:])
        if cfg.n_layers * tokens * per_token <= budget:
            return tier
    return "save_attn"


def pp_param_specs(cfg: LlamaConfig, axis_name: str = "pp") -> Params:
    """PartitionSpec tree for the pipeline layout: the layer stack is
    sharded over the pp axis (stage = contiguous layer slice); embedding
    and final norm replicate."""
    del cfg
    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(axis_name, None),
            "wq": P(axis_name, None, None),
            "wk": P(axis_name, None, None),
            "wv": P(axis_name, None, None),
            "wo": P(axis_name, None, None),
            "mlp_norm": P(axis_name, None),
            "w_gate": P(axis_name, None, None),
            "w_up": P(axis_name, None, None),
            "w_down": P(axis_name, None, None),
        },
        "final_norm": P(None),
    }
