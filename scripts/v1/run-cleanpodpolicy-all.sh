#!/usr/bin/env bash
# e2e CleanPodPolicy=All flow (reference:
# scripts/v1/run-cleanpodpolicy-all.sh:44-50, driving
# test/e2e/v1/cleanpolicy/cleanpolicy_all.go:122-123): create a job with
# cleanPodPolicy All, wait for Succeeded, assert every pod AND service is
# deleted on completion, then delete the job and verify GC.  Uses the
# stub API server + simulation tier unless MASTER points at a real API
# server with the operator deployed.
set -euo pipefail
cd "$(dirname "$0")/../.."

MASTER="${MASTER:-}"
if [ -z "$MASTER" ]; then
  python -m pytorch_operator_tpu.k8s.stub_server --port 18002 &
  STUB_PID=$!
  trap 'kill $STUB_PID 2>/dev/null || true' EXIT
  sleep 1
  MASTER="http://127.0.0.1:18002"
  # the simulation tier bundles controller + fake kubelet + the
  # cleanpolicy assertions (tests/test_e2e_sim.py::test_clean_pod_policy_all_e2e)
  python -m pytest "tests/test_e2e_sim.py::test_clean_pod_policy_all_e2e" -q
else
  python - <<EOF
from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster
cluster = RestCluster(KubeConfig.from_url("$MASTER"))
assert cluster.check_crd_exists(), "PyTorchJob CRD not installed"
print("CRD present on $MASTER; submit a job with cleanPodPolicy: All "
      "(e.g. examples/smoke-dist/pytorch_job_sendrecv.yaml) to run the "
      "full flow")
EOF
fi
echo "run-cleanpodpolicy-all passed"
