"""Data-plane telemetry (ISSUE 4 tentpole): StepProfiler timing /
throughput / MFU, push ingestion with the series budget, the HTTP push
endpoint, and the sim-e2e acceptance loop — a job's pushed step metrics
appear job-labeled on the operator's /metrics within budget, and an
OpenMetrics scrape of the reconcile histogram carries an exemplar that
resolves in /debug/traces."""

from __future__ import annotations

import json
import re
import threading
import time
import urllib.error
import urllib.request

import pytest

from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.metrics.server import start_metrics_server
from pytorch_operator_tpu.telemetry import (
    PushClient,
    PushGateway,
    StepProfiler,
    peak_flops_per_chip,
    read_step_log,
    train_step_flops,
)
from pytorch_operator_tpu.telemetry.push import (
    MFU,
    STEP_DURATION,
    STEPS_TOTAL,
    TOKENS_PER_SEC,
    derive_push_token,
    step_record_samples,
)


# ---------------------------------------------------------------------------
# StepProfiler
# ---------------------------------------------------------------------------


class TestStepProfiler:
    def test_compile_vs_steady_split(self):
        prof = StepProfiler(job="default/j", batch=4, seq_len=256,
                            n_params=1000, peak_flops=1e12)
        first = prof.observe(3.0)   # trace+compile+execute
        assert first.compile and prof.compile_time_s == 3.0
        assert first.tokens_per_sec is None  # compile never pollutes stats
        prof.observe(0.5)
        prof.observe(0.5)
        assert prof.mean_step_time() == pytest.approx(0.5)
        assert prof.compile_time_s == 3.0  # steady steps don't touch it

    def test_tokens_per_sec_and_mfu_math(self):
        # 4x256 = 1024 tokens in 0.5s -> 2048 tok/s; FLOPs/step =
        # 6*1e9*1024, achieved = that/0.5, peak = 1e12 * 2 chips
        prof = StepProfiler(batch=4, seq_len=256, n_params=int(1e9),
                            n_chips=2, peak_flops=1e12)
        prof.observe(1.0)  # compile
        rec = prof.observe(0.5)
        assert rec.tokens_per_sec == pytest.approx(2048.0)
        expected_mfu = (6 * 1e9 * 1024 / 0.5) / (1e12 * 2)
        assert rec.mfu == pytest.approx(expected_mfu, rel=1e-4)
        assert prof.tokens_per_sec() == pytest.approx(2048.0)
        assert prof.mfu() == pytest.approx(expected_mfu, rel=1e-4)

    def test_no_model_shape_means_no_throughput(self):
        prof = StepProfiler()
        prof.observe(1.0)
        rec = prof.observe(0.1)
        assert rec.tokens_per_sec is None and rec.mfu is None

    def test_rolling_window_bounds_memory_of_the_mean(self):
        prof = StepProfiler(batch=1, seq_len=1, window=2)
        prof.observe(9.0)  # compile
        for t in (1.0, 2.0, 4.0):
            prof.observe(t)
        # window=2: the 1.0 step has rolled out
        assert prof.mean_step_time() == pytest.approx(3.0)

    def test_jsonl_log_and_read_back(self, tmp_path):
        path = str(tmp_path / "steps.jsonl")
        prof = StepProfiler(job="default/j", batch=2, seq_len=8,
                            n_params=100, peak_flops=1e12,
                            jsonl_path=path)
        prof.observe(1.0, loss=2.5)
        prof.observe(0.01, loss=2.0)
        prof.observe(0.01, loss=1.5)
        prof.close()
        lines = [json.loads(l) for l in open(path)]
        assert lines[0]["compile"] is True
        assert lines[1]["compile"] is False
        assert lines[1]["loss"] == 2.0
        assert lines[1]["job"] == "default/j"
        parsed = read_step_log(path)
        assert parsed["unit"] == "tok/s"
        assert parsed["steps"] == 2
        assert parsed["value"] == pytest.approx(1600.0)  # 16 tokens / 0.01
        assert parsed["mean_step_time_s"] == pytest.approx(0.01)

    def test_read_step_log_without_throughput_is_skipped(self, tmp_path):
        path = tmp_path / "steps.jsonl"
        path.write_text(
            '{"compile": true, "step": 1, "step_time_s": 1.0}\n'
            '{"compile": false, "step": 2, "step_time_s": 0.5}\n')
        parsed = read_step_log(str(path))
        assert parsed["skipped"] is True
        assert "tokens/sec" in parsed["reason"]

    def test_read_step_log_compile_only_is_skipped(self, tmp_path):
        path = tmp_path / "steps.jsonl"
        path.write_text('{"compile": true, "step": 1, "step_time_s": 9}\n')
        assert read_step_log(str(path))["skipped"] is True

    def test_wrap_times_a_jitted_step_and_extracts_loss(self, tmp_path):
        import jax
        import jax.numpy as jnp

        prof = StepProfiler(batch=2, seq_len=8, n_params=100,
                            peak_flops=1e12)

        @jax.jit
        def step(state, batch):
            return state + batch.sum(), {"loss": jnp.float32(1.25)}

        wrapped = prof.wrap(step)
        assert wrapped.profiler is prof
        state = jnp.zeros(())
        for _ in range(3):
            state, metrics = wrapped(state, jnp.ones((2, 8)))
        assert prof.step_count == 3
        assert prof.compile_time_s is not None
        assert prof.records[-1].loss == pytest.approx(1.25)
        assert prof.mean_step_time() > 0

    def test_on_record_exceptions_never_escape(self):
        def boom(record):
            raise RuntimeError("push failed")

        prof = StepProfiler(on_record=boom)
        prof.observe(1.0)  # must not raise

    def test_peak_flops_prefix_lookup(self):
        assert peak_flops_per_chip("TPU v5p chip") == 459e12
        assert peak_flops_per_chip("TPU v5 lite") == 197e12
        assert peak_flops_per_chip("TPU v4") == 275e12
        # unknown kinds fall back instead of crashing the loop
        assert peak_flops_per_chip("Radeon") == peak_flops_per_chip("cpu")

    def test_train_step_flops_is_6nbt(self):
        assert train_step_flops(10, 2, 3) == 6 * 10 * 2 * 3

    def test_with_step_profiler_on_real_train_step(self):
        import jax
        import optax

        from pytorch_operator_tpu.models import llama
        from pytorch_operator_tpu.parallel import train
        from pytorch_operator_tpu.parallel.mesh import make_mesh

        cfg = llama.tiny()
        mesh = make_mesh(dp=1, fsdp=1, tp=1, devices=jax.devices()[:1])
        opt = optax.sgd(1e-3)
        state = train.sharded_init(cfg, mesh, opt)
        step = train.make_train_step(cfg, mesh, opt)
        B, T = 2, 16
        profiled, prof = train.with_step_profiler(
            step, cfg, mesh, batch=B, seq_len=T, job="default/train")
        key = jax.random.key(0)
        batch = jax.random.randint(key, (B, T + 1), 0, cfg.vocab_size)
        for _ in range(3):
            state, metrics = profiled(state, batch)
        summary = prof.summary()
        assert summary["steps"] == 3
        assert summary["compile_time_s"] > summary["mean_step_time_s"]
        assert summary["tokens_per_sec"] > 0
        assert summary["mfu"] is not None and summary["mfu"] > 0
        assert prof.n_params == llama.n_params(cfg)
        assert prof.records[-1].loss is not None  # loss rode along


# ---------------------------------------------------------------------------
# PushGateway (ingestion + budget)
# ---------------------------------------------------------------------------


class TestPushGateway:
    def test_ingest_applies_known_families(self):
        registry = Registry()
        gw = PushGateway(registry)
        out = gw.ingest({"job": "default/j1", "samples": [
            {"name": STEP_DURATION, "op": "observe", "value": 0.02},
            {"name": TOKENS_PER_SEC, "op": "set", "value": 1500.5},
            {"name": STEPS_TOTAL, "op": "inc", "value": 2},
            {"name": MFU, "op": "set", "value": 0.41},
        ]})
        assert out == {"accepted": 4, "rejected": 0, "dropped": 0}
        text = registry.expose()
        assert ('pytorch_operator_job_step_duration_seconds_count'
                '{job="default/j1"} 1') in text
        assert ('pytorch_operator_job_tokens_per_second'
                '{job="default/j1"} 1500.5') in text
        assert 'pytorch_operator_job_steps_total{job="default/j1"} 2' in text
        assert 'pytorch_operator_job_mfu{job="default/j1"} 0.41' in text
        assert 'pytorch_operator_push_samples_total 4' in text

    def test_rejections_counted_not_raised(self):
        registry = Registry()
        gw = PushGateway(registry)
        out = gw.ingest({"job": "default/j1", "samples": [
            {"name": "made_up_family", "op": "set", "value": 1},
            {"name": TOKENS_PER_SEC, "op": "observe", "value": 1},  # op swap
            {"name": TOKENS_PER_SEC, "op": "set", "value": "NaN-ish"},
            {"name": STEPS_TOTAL, "op": "inc", "value": -5},  # down-counter
            "not-even-a-dict",
        ]})
        assert out["accepted"] == 0 and out["rejected"] == 5
        text = registry.expose()
        # rejected counter is labeled by reason (the unknown_job reason
        # rides the same family; see TestPushJobValidation)
        assert ('pytorch_operator_push_rejected_total'
                '{reason="unknown_family"} 1') in text
        assert ('pytorch_operator_push_rejected_total'
                '{reason="op_mismatch"} 1') in text
        assert ('pytorch_operator_push_rejected_total'
                '{reason="bad_value"} 3') in text
        # a rejected sample must not have minted a series for its job
        # (it would burn a budget slot and export a zero-valued series)
        assert 'job="default/j1"' not in text
        assert out["dropped"] == 0

    def test_unknown_job_rejected_when_validator_set(self):
        """ROADMAP push-hardening item: with a job validator wired (the
        operator passes the job informer store), a payload whose job
        does not name a live PyTorchJob is rejected wholesale under
        reason="unknown_job" and mints nothing."""
        registry = Registry()
        live = {"default/real-job"}
        gw = PushGateway(registry, job_validator=lambda j: j in live)
        out = gw.ingest({"job": "default/ghost", "samples": [
            {"name": STEP_DURATION, "op": "observe", "value": 0.02},
            {"name": TOKENS_PER_SEC, "op": "set", "value": 1500.5},
        ]})
        assert out == {"accepted": 0, "rejected": 2, "dropped": 0}
        text = registry.expose()
        assert ('pytorch_operator_push_rejected_total'
                '{reason="unknown_job"} 2') in text
        assert 'job="default/ghost"' not in text
        # a live job's samples pass through the same gateway untouched
        out = gw.ingest({"job": "default/real-job", "samples": [
            {"name": TOKENS_PER_SEC, "op": "set", "value": 99.0}]})
        assert out["accepted"] == 1
        assert ('pytorch_operator_job_tokens_per_second'
                '{job="default/real-job"} 99') in registry.expose()

    def test_push_token_checked_when_resolver_set(self):
        """ISSUE 15 identity satellite: with a token resolver wired,
        knowing a live job's NAME is no longer enough — the payload
        must carry the per-job token the operator injected into the
        pod env at build time.  Mismatches are rejected wholesale
        under reason="bad_token" and mint nothing."""
        registry = Registry()
        secret = "bench-secret"
        uids = {"default/j": "uid-1"}

        def resolver(job):
            uid = uids.get(job)
            return None if uid is None else derive_push_token(
                job, uid, secret)

        gw = PushGateway(registry, token_resolver=resolver)
        good = derive_push_token("default/j", "uid-1", secret)

        out = gw.ingest({"job": "default/j", "token": good, "samples": [
            {"name": TOKENS_PER_SEC, "op": "set", "value": 10.0}]})
        assert out["accepted"] == 1 and out["rejected"] == 0

        for bad in ("wrong", derive_push_token("default/j", "uid-2",
                                               secret), None):
            payload = {"job": "default/j", "samples": [
                {"name": TOKENS_PER_SEC, "op": "set", "value": 11.0},
                {"name": MFU, "op": "set", "value": 0.5}]}
            if bad is not None:
                payload["token"] = bad
            out = gw.ingest(payload)
            assert out["accepted"] == 0 and out["rejected"] == 2, bad
        text = registry.expose()
        assert ('pytorch_operator_push_rejected_total'
                '{reason="bad_token"} 6') in text
        # the accepted push minted the series; the rejected ones kept
        # their values out
        assert ('pytorch_operator_job_tokens_per_second'
                '{job="default/j"} 10') in text

    def test_push_token_fails_closed_when_job_unresolvable(self):
        """A resolver that cannot vouch for the job (informer lag, job
        gone) rejects rather than letting an attacker race deletion."""
        gw = PushGateway(registry := Registry(),
                         token_resolver=lambda job: None)
        out = gw.ingest({"job": "default/ghost", "token": "anything",
                         "samples": [{"name": MFU, "op": "set",
                                      "value": 0.5}]})
        assert out["accepted"] == 0 and out["rejected"] == 1
        assert ('pytorch_operator_push_rejected_total'
                '{reason="bad_token"} 1') in registry.expose()

    def test_derive_push_token_keyed_and_job_bound(self):
        t = derive_push_token("default/j", "u1", "s")
        assert t == derive_push_token("default/j", "u1", "s")
        assert t != derive_push_token("default/j", "u2", "s")
        assert t != derive_push_token("default/k", "u1", "s")
        assert t != derive_push_token("default/j", "u1", "other")
        # the job/uid boundary is unambiguous (no concat collision)
        assert (derive_push_token("a/bc", "d", "s")
                != derive_push_token("a/b", "cd", "s"))

    def test_build_new_pod_injects_matching_push_token(self):
        """The build-time half of the identity loop: the pod env the
        operator renders carries exactly the token the gateway's
        resolver derives for that job."""
        from pytorch_operator_tpu.api.v1.constants import ENV_PUSH_TOKEN
        from pytorch_operator_tpu.controller import PyTorchController
        from pytorch_operator_tpu.k8s.fake import FakeCluster
        from pytorch_operator_tpu.runtime import JobControllerConfig
        from testutil import new_job, wait_for

        cluster = FakeCluster()
        ctl = PyTorchController(
            cluster, config=JobControllerConfig(
                push_token_secret="e2e-secret"),
            registry=Registry())
        stop = threading.Event()
        ctl.run(threadiness=1, stop_event=stop)
        try:
            job = new_job(workers=1, name="tok-job").to_dict()
            cluster.jobs.create("default", job)
            assert wait_for(
                lambda: len(cluster.pods.list("default")) == 2,
                timeout=10)
            uid = cluster.jobs.get("default", "tok-job")["metadata"]["uid"]
            want = derive_push_token("default/tok-job", uid, "e2e-secret")
            for pod in cluster.pods.list("default"):
                env = {e.get("name"): e.get("value")
                       for c in pod["spec"]["containers"]
                       for e in c.get("env") or []}
                assert env.get(ENV_PUSH_TOKEN) == want, pod["metadata"]
        finally:
            stop.set()
            ctl.work_queue.shutdown()

    def test_malformed_payload_raises_for_http_400(self):
        gw = PushGateway(Registry())
        for bad in (None, [], {"samples": []}, {"job": ""},
                    {"job": "j", "samples": "x"}):
            with pytest.raises(ValueError):
                gw.ingest(bad)

    def test_series_budget_bounds_job_label_cardinality(self):
        registry = Registry()
        gw = PushGateway(registry, series_budget=2)
        for i in range(5):
            out = gw.ingest({"job": f"default/job-{i}", "samples": [
                {"name": TOKENS_PER_SEC, "op": "set", "value": float(i)}]})
        # jobs 0 and 1 minted series; 2..4 were dropped, and the LAST
        # request reported its drop in the response
        assert out["dropped"] == 1 and out["accepted"] == 1
        text = registry.expose()
        for i in (0, 1):
            assert f'{{job="default/job-{i}"}}' in text
        for i in (2, 3, 4):
            assert f'job-{i}' not in text, "over-budget series exported"
        m = re.search(
            r'pytorch_operator_metrics_dropped_series_total (\d+)', text)
        assert m and int(m.group(1)) == 3
        # existing series keep accepting samples at full budget
        out = gw.ingest({"job": "default/job-0", "samples": [
            {"name": TOKENS_PER_SEC, "op": "set", "value": 9.5}]})
        assert out == {"accepted": 1, "rejected": 0, "dropped": 0}
        assert ('pytorch_operator_job_tokens_per_second'
                '{job="default/job-0"} 9.5') in registry.expose()

    def test_step_record_samples_vocabulary(self):
        from pytorch_operator_tpu.telemetry.step_timer import StepRecord

        compile_rec = StepRecord(job="j", step=1, step_time_s=3.0,
                                 compile=True, tokens_per_sec=None, mfu=None)
        names = {s["name"] for s in step_record_samples(compile_rec)}
        assert names == {"pytorch_operator_job_compile_time_seconds"}
        steady = StepRecord(job="j", step=2, step_time_s=0.5, compile=False,
                            tokens_per_sec=2048.0, mfu=0.4, loss=1.5)
        samples = step_record_samples(steady)
        gw = PushGateway(registry := Registry())
        out = gw.ingest({"job": "default/j", "samples": samples})
        assert out["rejected"] == 0 and out["accepted"] == len(samples)
        text = registry.expose()
        assert 'pytorch_operator_job_loss{job="default/j"} 1.5' in text


# ---------------------------------------------------------------------------
# HTTP: POST /push/v1/metrics + content negotiation
# ---------------------------------------------------------------------------


def _post(port: int, body: bytes, path: str = "/push/v1/metrics"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method="POST",
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=5)


class TestPushExemplars:
    """ISSUE 6 satellite: per-job step exemplars on the push path — a
    slow step bucket resolves to the pushing job the way reconcile
    exemplars resolve to traces, and plain scrapes stay byte-identical."""

    def test_pushed_step_carries_job_exemplar_openmetrics_only(self):
        registry = Registry()
        gw = PushGateway(registry)
        gw.ingest({"job": "default/slow-job", "samples": [
            {"name": STEP_DURATION, "op": "observe", "value": 0.7}]})
        om = registry.expose(openmetrics=True)
        assert ('pytorch_operator_job_step_duration_seconds_bucket'
                '{job="default/slow-job",le="1"} 1 '
                '# {job="default/slow-job"} 0.7') in om
        # plain text-0.0.4 scrape carries no exemplar syntax at all
        assert "# {" not in registry.expose()

    def test_plain_scrape_byte_identical_to_exemplar_free_family(self):
        registry = Registry()
        gw = PushGateway(registry)
        for value in (0.02, 0.7, 40.0):
            gw.ingest({"job": "default/j1", "samples": [
                {"name": STEP_DURATION, "op": "observe", "value": value}]})
        # the same observations on a bare vec with no exemplars attached
        from pytorch_operator_tpu.telemetry.push import _STEP_BUCKETS

        bare_registry = Registry()
        bare = bare_registry.histogram_vec(
            STEP_DURATION,
            "Distribution of one training step's wall time, pushed per "
            "step by the job",
            ("job",), buckets=_STEP_BUCKETS)
        for value in (0.02, 0.7, 40.0):
            bare.labels(job="default/j1").observe(value)
        pushed_text = gw._vecs[STEP_DURATION].expose()
        assert pushed_text == bare.expose()

    def test_push_endpoint_content_negotiation(self):
        """The PR 4 negotiation contract extended over the push path:
        plain scrape = text 0.0.4 (no exemplars), OpenMetrics Accept =
        job exemplars + # EOF + the OM content type."""
        registry = Registry()
        gw = PushGateway(registry)
        server = start_metrics_server(registry, 0, host="127.0.0.1",
                                      push_gateway=gw)
        port = server.server_address[1]
        try:
            body = json.dumps({"job": "default/j9", "samples": [
                {"name": STEP_DURATION, "op": "observe", "value": 0.3}]})
            assert _post(port, body.encode()).status == 200
            plain = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=5).read().decode()
            assert "# {" not in plain and "# EOF" not in plain
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/metrics",
                headers={"Accept": "application/openmetrics-text; "
                                   "version=1.0.0"})
            resp = urllib.request.urlopen(req, timeout=5)
            om = resp.read().decode()
            assert resp.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            assert '# {job="default/j9"} 0.3' in om
            assert om.rstrip().endswith("# EOF")
        finally:
            server.shutdown()


class TestPushEndpoint:
    def test_post_roundtrip_and_reexport(self):
        registry = Registry()
        gw = PushGateway(registry)
        server = start_metrics_server(registry, 0, host="127.0.0.1",
                                      push_gateway=gw)
        port = server.server_address[1]
        try:
            body = json.dumps({"job": "default/j1", "samples": [
                {"name": STEP_DURATION, "op": "observe", "value": 0.2}]})
            resp = _post(port, body.encode())
            assert resp.status == 200
            assert json.loads(resp.read())["accepted"] == 1
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
            assert ('pytorch_operator_job_step_duration_seconds_count'
                    '{job="default/j1"} 1') in text
        finally:
            server.shutdown()

    def test_post_error_statuses(self):
        registry = Registry()
        server = start_metrics_server(registry, 0, host="127.0.0.1",
                                      push_gateway=PushGateway(registry))
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(port, b"{not json")
            assert exc.value.code == 400
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(port, json.dumps({"samples": []}).encode())
            assert exc.value.code == 400  # missing job
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(port, b"{}", path="/some/other/path")
            assert exc.value.code == 404
        finally:
            server.shutdown()

    def test_post_404_without_gateway(self):
        server = start_metrics_server(Registry(), 0, host="127.0.0.1")
        port = server.server_address[1]
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(port, b"{}")
            assert exc.value.code == 404
        finally:
            server.shutdown()

    def test_push_client_swallows_dead_operator(self):
        client = PushClient("http://127.0.0.1:1", "default/j", timeout=0.2)
        assert client.push_samples(
            [{"name": STEP_DURATION, "op": "observe", "value": 1}]) is None
        assert client.errors == 1  # counted, not raised

    def test_push_client_feeds_profiler_records(self):
        registry = Registry()
        gw = PushGateway(registry)
        server = start_metrics_server(registry, 0, host="127.0.0.1",
                                      push_gateway=gw)
        port = server.server_address[1]
        try:
            client = PushClient(f"http://127.0.0.1:{port}", "default/j1")
            prof = StepProfiler(job="default/j1", batch=2, seq_len=8,
                                n_params=100, peak_flops=1e12,
                                on_record=client.on_record)
            prof.observe(1.0)   # compile -> compile_time gauge
            prof.observe(0.01)  # steady -> duration/steps/tps/mfu
            text = registry.expose()
            assert ('pytorch_operator_job_compile_time_seconds'
                    '{job="default/j1"} 1') in text
            assert ('pytorch_operator_job_step_duration_seconds_count'
                    '{job="default/j1"} 1') in text
            assert ('pytorch_operator_job_steps_total'
                    '{job="default/j1"} 1') in text
        finally:
            server.shutdown()

    def test_operator_flags(self):
        from pytorch_operator_tpu.cmd.operator import build_parser

        args = build_parser().parse_args(["--push-series-budget", "7"])
        assert args.push_series_budget == 7
        assert args.enable_push_ingestion is True
        args = build_parser().parse_args(["--enable-push-ingestion=false"])
        assert args.enable_push_ingestion is False


# ---------------------------------------------------------------------------
# Sim e2e: the acceptance loop
# ---------------------------------------------------------------------------


@pytest.fixture
def telemetry_world(e2e_artifacts):
    from pytorch_operator_tpu.controller import PyTorchController
    from pytorch_operator_tpu.k8s.fake import FakeCluster
    from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
    from pytorch_operator_tpu.runtime import JobControllerConfig
    from pytorch_operator_tpu.runtime.tracing import Tracer

    cluster = FakeCluster()
    registry = Registry()
    tracer = Tracer(buffer_size=128)
    ctl = PyTorchController(cluster, config=JobControllerConfig(),
                            registry=registry, tracer=tracer)
    kubelet = FakeKubelet(cluster)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    gateway = PushGateway(registry, series_budget=2)
    server = start_metrics_server(registry, 0, host="127.0.0.1",
                                  tracer=tracer, push_gateway=gateway)
    port = server.server_address[1]
    # the fake kubelet plays the trainer side: each completing pod
    # pushes step samples for its owning job to this operator
    kubelet.telemetry_url = f"http://127.0.0.1:{port}"
    e2e_artifacts["port"] = port
    yield cluster, registry, gateway, kubelet, port
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()
    server.shutdown()


def _job_succeeded(cluster, name: str) -> bool:
    job = cluster.jobs.get("default", name)
    return any(c.get("type") == "Succeeded" and c.get("status") == "True"
               for c in (job.get("status") or {}).get("conditions") or [])


def test_sim_e2e_pushed_step_metrics_within_budget_and_exemplar_resolves(
        telemetry_world):
    from testutil import new_job, wait_for

    cluster, registry, gateway, kubelet, port = telemetry_world
    # budget is 2: two jobs mint series, the third must be dropped
    for name in ("tele-a", "tele-b", "tele-c"):
        cluster.jobs.create("default", new_job(workers=1, name=name)
                            .to_dict())
    for name in ("tele-a", "tele-b", "tele-c"):
        assert wait_for(lambda n=name: _job_succeeded(cluster, n),
                        timeout=30), name
    # pushes happen as pods complete; wait until the budget counter
    # proves the third job's samples were refused
    dropped = registry.dropped_series_counter()
    assert wait_for(lambda: dropped.value > 0, timeout=10), \
        "over-budget pushes never hit the dropped-series counter"

    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    # pushed step series are exported job-labeled...
    job_series = re.findall(
        r'pytorch_operator_job_step_duration_seconds_count'
        r'\{job="default/(tele-[abc])"\} (\d+)', text)
    assert job_series, "no pushed step series on /metrics"
    for _job, count in job_series:
        assert int(count) >= 1
    # ...and stay within the configured budget: at most 2 of the 3
    # jobs minted series, none past the budget leaked into exposition
    assert len(job_series) == 2
    tps_jobs = re.findall(
        r'pytorch_operator_job_tokens_per_second\{job="default/(tele-'
        r'[abc])"\}', text)
    assert len(tps_jobs) == 2  # throughput gauges rode along, same cap

    # OpenMetrics scrape: the reconcile histogram carries an exemplar
    # whose trace id resolves in /debug/traces
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/metrics",
        headers={"Accept": "application/openmetrics-text"})
    om = urllib.request.urlopen(req, timeout=5).read().decode()
    assert om.rstrip().endswith("# EOF")
    exemplars = re.findall(
        r'pytorch_operator_reconcile_duration_seconds_bucket\{[^}]*\} '
        r'\d+ # \{trace_id="([0-9a-f]+)"\}', om)
    assert exemplars, "no exemplar on the reconcile histogram"
    traces = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/debug/traces", timeout=5).read())["traces"]
    trace_ids = {t["span_id"] for t in traces}
    assert set(exemplars) & trace_ids, (
        f"no exemplar trace id {exemplars} resolves in /debug/traces")
    # the plain scrape never leaks exemplar syntax
    assert "# {trace_id=" not in text


def test_artifact_capture_fixture_scrapes_on_failure(tmp_path, monkeypatch):
    """The conftest flight recorder end to end: a failing test whose
    world registered a port leaves /metrics + /debug/traces files in
    $E2E_ARTIFACTS_DIR."""
    import subprocess
    import sys as _sys
    import os as _os
    import textwrap
    import uuid

    repo = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    # the probe must live under tests/ so the inner pytest loads this
    # suite's conftest (fixture + capture hook); unique name, removed
    # in finally so the outer suite never collects it
    test_file = _os.path.join(
        repo, "tests", f"_artifact_probe_{uuid.uuid4().hex[:8]}.py")
    probe_src = textwrap.dedent("""
        from pytorch_operator_tpu.metrics.prometheus import Registry
        from pytorch_operator_tpu.metrics.server import start_metrics_server
        from pytorch_operator_tpu.runtime.tracing import Tracer

        def test_fails(e2e_artifacts):
            tracer = Tracer()
            with tracer.trace("reconcile", key="default/x"):
                pass
            # NOT shut down before the assert: capture runs from the
            # makereport hook right after the test body, while fixture
            # teardown (where a real world stops its server) has not
            # started; the daemon server dies with the interpreter
            server = start_metrics_server(Registry(), 0, host="127.0.0.1",
                                          tracer=tracer)
            e2e_artifacts["port"] = server.server_address[1]
            e2e_artifacts["extra"]["state.txt"] = "world state dump"
            assert False, "deliberate failure"
    """)
    artifacts = tmp_path / "artifacts"
    try:
        with open(test_file, "w") as f:
            f.write(probe_src)
        proc = subprocess.run(
            [_sys.executable, "-m", "pytest", "-q", "-p",
             "no:cacheprovider", test_file],
            cwd=_os.path.join(repo, "tests"),
            env={**_os.environ, "E2E_ARTIFACTS_DIR": str(artifacts),
                 "JAX_PLATFORMS": "cpu"},
            capture_output=True, text=True, timeout=120)
    finally:
        if _os.path.exists(test_file):
            _os.unlink(test_file)
    assert proc.returncode != 0  # the inner test fails by design
    assert artifacts.is_dir(), (proc.stdout, proc.stderr)
    names = sorted(p.name for p in artifacts.iterdir())

    def find(suffix):
        # file base is the sanitized nodeid (module__test), so two
        # same-named tests in different modules can't clobber each other
        matches = [n for n in names if n.endswith(f"test_fails.{suffix}")]
        assert matches, (suffix, names, proc.stdout)
        return artifacts / matches[0]

    traces = json.loads(find("traces.json").read_text())
    assert traces["traces"][0]["name"] == "reconcile"
    assert "scrape_errors_total" in find("metrics.txt").read_text()
    assert find("state.txt").read_text() == "world state dump"
