"""Rule scoping for the repo's own tree.

Rules carry different blast radii: wall-clock usage is only a bug in
modules whose time source is injectable (the simulator drives them on a
VirtualClock), while builtin ``hash()`` and unseeded ``random`` are
wrong anywhere in the operator package.  The scopes below are
path-prefix matches against POSIX-style paths relative to the repo
root; tests construct their own :class:`AnalysisConfig` to exercise
rules on fixture snippets without caring where the tmpdir lives.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence, Tuple

#: Modules that accept an injected clock (``clock=`` / ``sleep=`` /
#: ``VirtualClock.timer``) somewhere in their construction chain — a raw
#: wall-clock call here either bypasses the injection (breaking the
#: simulator's same-seed determinism) or marks a path the injection has
#: not reached yet.  The sim driver's deliberate real-wall-time reads
#: (it reports the simulator's leverage, virtual vs real seconds) carry
#: reasoned waivers rather than a scope exclusion, so any NEW wall
#: reads in sim/ must justify themselves too.
CLOCK_INJECTABLE: Tuple[str, ...] = (
    "pytorch_operator_tpu/runtime/",
    "pytorch_operator_tpu/controller/",
    "pytorch_operator_tpu/disruption/",
    "pytorch_operator_tpu/telemetry/",
    "pytorch_operator_tpu/k8s/resilience.py",
    "pytorch_operator_tpu/k8s/fake_kubelet.py",
    "pytorch_operator_tpu/native/__init__.py",
    "pytorch_operator_tpu/sim/fleet.py",
    "pytorch_operator_tpu/sim/scale.py",
)

#: Modules on the reconcile path, where a silently swallowed exception
#: turns a failed sync into a wedged job (no requeue, no event, no log
#: line to find it by).
RECONCILE_PATHS: Tuple[str, ...] = (
    "pytorch_operator_tpu/controller/",
    "pytorch_operator_tpu/runtime/",
    "pytorch_operator_tpu/disruption/",
)

#: Modules that consume shared-cache objects — informer store reads,
#: event-handler payloads, ``FakeCluster``/``RestCluster`` watch
#: deliveries.  The ``cache-mutation`` rule tracks cache-sourced
#: variables here and flags in-place writes that lack an ownership
#: transfer (``copy.deepcopy`` / ``_copy_obj`` / serde parse /
#: ``analysis.owned``).
CACHE_CONSUMER_PATHS: Tuple[str, ...] = (
    "pytorch_operator_tpu/controller/",
    "pytorch_operator_tpu/runtime/",
    "pytorch_operator_tpu/disruption/",
    "pytorch_operator_tpu/sim/",
    "pytorch_operator_tpu/k8s/fake_kubelet.py",
)

#: Default scan roots for the tree-wide run (scripts/lint.py with no
#: arguments and the test suite's cleanliness assertion).
DEFAULT_SCAN_ROOTS: Tuple[str, ...] = (
    "pytorch_operator_tpu",
    "scripts",
)


@dataclass
class AnalysisConfig:
    """Which paths each scoped rule applies to.

    ``clock_injectable`` / ``reconcile_paths`` / ``cache_consumer_paths``:
    path-prefix lists; a file matches when its repo-relative POSIX path
    starts with any entry.  An empty tuple disables the scoped rule
    everywhere; tests use ``("",)`` (matches everything) to run a
    scoped rule on fixture files.
    """

    clock_injectable: Sequence[str] = field(default=CLOCK_INJECTABLE)
    reconcile_paths: Sequence[str] = field(default=RECONCILE_PATHS)
    cache_consumer_paths: Sequence[str] = field(default=CACHE_CONSUMER_PATHS)

    @staticmethod
    def _matches(rel_path: str, prefixes: Sequence[str]) -> bool:
        posix = rel_path.replace("\\", "/")
        return any(posix.startswith(p) for p in prefixes)

    def is_clock_injectable(self, rel_path: str) -> bool:
        return self._matches(rel_path, self.clock_injectable)

    def is_reconcile_path(self, rel_path: str) -> bool:
        return self._matches(rel_path, self.reconcile_paths)

    def is_cache_consumer(self, rel_path: str) -> bool:
        return self._matches(rel_path, self.cache_consumer_paths)


#: Shared default — what scripts/lint.py and test_analysis.py use.
DEFAULT_CONFIG = AnalysisConfig()
