"""Disruption subsystem: detection, policy, delete fan-out, and the
sim-tier chaos scenario.

The acceptance scenario (ISSUE 2): with disruption handling enabled, a
tainted-node preemption of 1 of 8 workers produces exactly ONE proactive
gang restart — a single batched delete, a ``Restarting`` condition with
reason ``TPUPreempted``, no per-pod backoff cycles, no expectation
leaks — and the job still reaches ``Succeeded``; with handling disabled
the legacy per-pod failure path is unchanged.
"""

from __future__ import annotations

import threading
import time

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.api.v1.defaults import set_defaults
from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.disruption import (
    DisruptionWatcher,
    node_disruption_reason,
    pod_disruption_reason,
)
from pytorch_operator_tpu.disruption.detector import (
    CLOUD_NODE_SHUTDOWN_TAINT,
    DISRUPTION_TAINT_KEYS,
    IMPENDING_NODE_TERMINATION_TAINT,
    NODE_OUT_OF_SERVICE_TAINT,
    NODE_UNREACHABLE_TAINT,
)
from pytorch_operator_tpu.k8s.errors import ApiError
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet, new_tpu_node
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import (
    FakePodControl,
    FakeServiceControl,
    Informer,
    JobControllerConfig,
)
from pytorch_operator_tpu.runtime.expectations import (
    ControllerExpectations,
    expectation_pods_key,
    expectation_services_key,
)

from testutil import job_condition, new_job, wait_for


def _mk_node(name="n1", taints=None, ready="True", tpu=True):
    node = new_tpu_node(name) if tpu else {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": name},
        "spec": {},
        "status": {"conditions": [{"type": "Ready", "status": ready}]},
    }
    if taints:
        node["spec"]["taints"] = taints
    if tpu:
        node["status"]["conditions"] = [{"type": "Ready", "status": ready}]
    return node


class TestDetector:
    def test_healthy_tpu_node_is_not_disrupted(self):
        assert node_disruption_reason(_mk_node()) is None

    @pytest.mark.parametrize("key", [
        IMPENDING_NODE_TERMINATION_TAINT,
        NODE_UNREACHABLE_TAINT,
        "node.kubernetes.io/not-ready",
        # graceful-node-shutdown spellings (ISSUE 6 satellite): the
        # out-of-service taint an operator applies to a shut-down node,
        # and the cloud provider's VM-powering-down taint
        NODE_OUT_OF_SERVICE_TAINT,
        CLOUD_NODE_SHUTDOWN_TAINT,
    ])
    def test_disruption_taints_detected(self, key):
        node = _mk_node(taints=[{"key": key, "effect": "NoSchedule"}])
        assert node_disruption_reason(node) == key

    def test_unrelated_taint_ignored(self):
        node = _mk_node(taints=[{"key": "example.com/dedicated",
                                 "effect": "NoSchedule"}])
        assert node_disruption_reason(node) is None

    def test_not_ready_tpu_node_is_disrupted(self):
        assert node_disruption_reason(
            _mk_node(ready="False")) == "TPUNodeNotReady"

    def test_not_ready_cpu_node_is_not_tpu_disruption(self):
        # only TPU nodes escalate bare NotReady (a flaky CPU node is the
        # node-lifecycle controller's problem, not a slice preemption)
        assert node_disruption_reason(
            _mk_node(ready="False", tpu=False)) is None

    def test_pod_disruption_target_condition(self):
        pod = {"status": {"conditions": [
            {"type": "DisruptionTarget", "status": "True",
             "reason": "PreemptionByScheduler"}]}}
        assert pod_disruption_reason(pod) == "PreemptionByScheduler"
        assert pod_disruption_reason({"status": {}}) is None
        assert pod_disruption_reason({"status": {"conditions": [
            {"type": "DisruptionTarget", "status": "False"}]}}) is None


def _bound_pod(name, job_name, node, rtype="worker", index="0",
               uid="job-uid"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": name, "namespace": "default",
            "labels": {constants.LABEL_REPLICA_TYPE: rtype,
                       constants.LABEL_REPLICA_INDEX: index},
            "ownerReferences": [{
                "apiVersion": constants.API_VERSION, "kind": constants.KIND,
                "name": job_name, "uid": uid, "controller": True}],
        },
        "spec": {"nodeName": node,
                 "containers": [{"name": "pytorch", "image": "i"}]},
    }


class TestWatcher:
    def test_fires_once_per_node_transition(self):
        cluster = FakeCluster()
        cluster.nodes.create("default", _mk_node("n1"))
        cluster.pods.create("default", _bound_pod("j-worker-0", "j", "n1"))
        fired = []
        informer = Informer(cluster.nodes)
        DisruptionWatcher(cluster, informer,
                          lambda key, reason, node, uid=None: fired.append(
                              (key, reason, node)))
        informer.start()
        assert fired == []  # healthy at start
        taint = [{"key": IMPENDING_NODE_TERMINATION_TAINT,
                  "effect": "NoSchedule"}]
        cluster.nodes.patch("default", "n1", {"spec": {"taints": taint}})
        assert fired == [("default/j", IMPENDING_NODE_TERMINATION_TAINT,
                          "n1")]
        # churn on an already-flagged node stays silent
        cluster.nodes.patch("default", "n1",
                            {"metadata": {"labels": {"x": "y"}}})
        assert len(fired) == 1
        # healthy again re-arms; the next taint fires again
        cluster.nodes.patch("default", "n1", {"spec": {"taints": None}})
        cluster.nodes.patch("default", "n1", {"spec": {"taints": taint}})
        assert len(fired) == 2

    @pytest.mark.parametrize("key", DISRUPTION_TAINT_KEYS)
    def test_fires_exactly_once_per_taint_variant_in_sim(self, key):
        """ISSUE 6 satellite: every recognized taint spelling —
        graceful-node-shutdown variants included — fires the watcher
        exactly once per node transition, injected through the fake
        kubelet the way a sim scenario would."""
        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster, decide=lambda pod: None)
        cluster.nodes.create("default", _mk_node("n1"))
        cluster.pods.create("default", _bound_pod("j-worker-0", "j", "n1"))
        fired = []
        informer = Informer(cluster.nodes)
        DisruptionWatcher(cluster, informer,
                          lambda jk, reason, node, uid=None: fired.append(
                              (jk, reason)))
        informer.start()
        kubelet.taint_node("n1", key=key)
        assert fired == [("default/j", key)]
        # taint churn on the already-flagged node stays silent
        kubelet.taint_node("n1", key=key)  # idempotent re-apply
        cluster.nodes.patch("default", "n1",
                            {"metadata": {"labels": {"x": "y"}}})
        assert len(fired) == 1

    def test_resolves_only_jobs_on_the_node(self):
        cluster = FakeCluster()
        cluster.nodes.create("default", _mk_node("n1"))
        cluster.nodes.create("default", _mk_node("n2"))
        cluster.pods.create("default",
                            _bound_pod("a-worker-0", "a", "n1", uid="ua"))
        cluster.pods.create("default",
                            _bound_pod("b-worker-0", "b", "n2", uid="ub"))
        fired = []
        informer = Informer(cluster.nodes)
        DisruptionWatcher(cluster, informer,
                          lambda key, reason, node, uid=None:
                          fired.append(key))
        informer.start()
        cluster.nodes.patch("default", "n2", {"spec": {"taints": [
            {"key": NODE_UNREACHABLE_TAINT, "effect": "NoExecute"}]}})
        assert fired == ["default/b"]


def _policy_controller(max_restarts=3, enabled=True):
    cluster = FakeCluster()
    registry = Registry()
    ctl = PyTorchController(
        cluster,
        config=JobControllerConfig(enable_disruption_handling=enabled,
                                   max_preemption_restarts=max_restarts),
        registry=registry)
    ctl.pod_control = FakePodControl()
    ctl.service_control = FakeServiceControl()
    return cluster, ctl


def _gang_job(name="test-pytorchjob", workers=2):
    job = new_job(workers=workers, name=name, tpu_chips=4)
    set_defaults(job)
    return job


def _pods_for(job, node="n1"):
    pods = [_bound_pod(f"{job.metadata.name}-master-0", job.metadata.name,
                       node, rtype="master", uid=job.metadata.uid)]
    workers = int(job.spec.pytorch_replica_specs["Worker"].replicas or 0)
    for i in range(workers):
        pods.append(_bound_pod(f"{job.metadata.name}-worker-{i}",
                               job.metadata.name, node, rtype="worker",
                               index=str(i), uid=job.metadata.uid))
    return pods


class TestHandlerPolicy:
    def test_gang_restart_batches_all_replicas(self):
        cluster, ctl = _policy_controller()
        job = _gang_job()
        pods = _pods_for(job)
        ctl._note_disruption(job.key, "taint", "node/n1")
        assert ctl.preemptions_detected_counter.value == 1
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        assert sorted(ctl.pod_control.delete_pod_names) == sorted(
            p["metadata"]["name"] for p in pods)
        # deletion expectations raised per replica type, none observed yet
        assert ctl.expectations.get(
            expectation_pods_key(job.key, "master")).dels == 1
        assert ctl.expectations.get(
            expectation_pods_key(job.key, "worker")).dels == 2
        # budget consumed + condition carries TPUPreempted
        assert job.status.preemption_restarts == 1
        conds = {c.type: c for c in job.status.conditions}
        assert conds[constants.JOB_RESTARTING].reason == \
            constants.TPU_PREEMPTED_REASON
        assert ctl.preemption_gang_restarts_counter.value == 1
        assert ctl.preemption_restart_latency.count == 1
        # the note was consumed: a second sync does nothing
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is False

    def test_max_restarts_cutoff(self):
        cluster, ctl = _policy_controller(max_restarts=2)
        job = _gang_job()
        job.status.preemption_restarts = 2
        ctl._note_disruption(job.key, "taint", "node/n1")
        assert ctl.maybe_handle_disruption(
            job, job.to_dict(), _pods_for(job)) is False
        assert ctl.pod_control.delete_pod_names == []
        assert ctl.preemption_restarts_suppressed_counter.value == 1
        reasons = {e["reason"] for e in cluster.events.list()}
        assert constants.PREEMPTION_RESTARTS_EXHAUSTED_REASON in reasons

    def test_annotation_budget_override(self):
        cluster, ctl = _policy_controller(max_restarts=1)
        job = _gang_job()
        job.metadata.annotations[
            constants.ANNOTATION_MAX_PREEMPTION_RESTARTS] = "5"
        job.status.preemption_restarts = 3
        ctl._note_disruption(job.key, "taint", "node/n1")
        assert ctl.maybe_handle_disruption(
            job, job.to_dict(), _pods_for(job)) is True
        assert job.status.preemption_restarts == 4

    def test_per_job_opt_out(self):
        cluster, ctl = _policy_controller()
        job = _gang_job()
        job.metadata.annotations[constants.ANNOTATION_DISRUPTION_HANDLING] = \
            constants.DISRUPTION_HANDLING_DISABLED
        ctl._note_disruption(job.key, "taint", "node/n1")
        assert ctl.maybe_handle_disruption(
            job, job.to_dict(), _pods_for(job)) is False
        assert ctl.pod_control.delete_pod_names == []

    def test_non_gang_job_not_gang_restarted(self):
        cluster, ctl = _policy_controller()
        job = new_job(workers=2, name="plain-job")  # no TPU request
        set_defaults(job)
        ctl._note_disruption(job.key, "taint", "node/n1")
        assert ctl.maybe_handle_disruption(
            job, job.to_dict(), _pods_for(job)) is False
        assert ctl.pod_control.delete_pod_names == []
        assert ctl.preemption_restarts_suppressed_counter.value == 1

    def test_failed_gang_delete_reinserts_note_for_retry(self):
        """A partial delete failure must not lose the disruption: the
        note goes back (the watcher's node flag won't re-fire), the
        budget stays unspent, and the requeued sync retries."""
        cluster, ctl = _policy_controller()
        job = _gang_job()
        pods = _pods_for(job)
        ctl.pod_control.delete_errors[
            pods[1]["metadata"]["name"]] = ApiError("transient 500")
        ctl._note_disruption(job.key, "taint", "node/n1")
        with pytest.raises(ApiError):
            ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        assert not job.status.preemption_restarts
        assert ctl.preemption_gang_restarts_counter.value == 0
        # the requeued sync finds the note again and succeeds
        ctl.pod_control.delete_errors.clear()
        assert ctl.maybe_handle_disruption(
            job, job.to_dict(), pods) is True
        assert job.status.preemption_restarts == 1

    def test_pod_signal_suppressed_while_gang_delete_in_flight(self):
        """A DisruptionTarget update racing the gang restart's own
        deletes must not re-note the job (one preemption, one budget
        unit)."""
        cluster, ctl = _policy_controller()
        job = _gang_job()
        job_dict = job.to_dict()
        ctl.job_informer.store.add(job_dict)
        pod = _bound_pod(f"{job.metadata.name}-worker-0",
                         job.metadata.name, "n1", uid=job.metadata.uid)
        pod["status"] = {"phase": "Running", "conditions": [
            {"type": "DisruptionTarget", "status": "True",
             "reason": "PreemptionByScheduler"}]}
        ctl.expectations.expect_deletions(
            expectation_pods_key(job.key, "worker"), 2)
        ctl.note_pod_disruption(pod)
        assert ctl.maybe_handle_disruption(
            job, job_dict, _pods_for(job)) is False  # no note recorded
        # once the deletes drained, the same signal counts again
        ctl.expectations.delete_expectations(
            expectation_pods_key(job.key, "worker"))
        ctl.note_pod_disruption(pod)
        assert ctl.maybe_handle_disruption(
            job, job_dict, _pods_for(job)) is True

    def test_duplicate_signals_coalesce_to_one_note(self):
        cluster, ctl = _policy_controller()
        job = _gang_job()
        ctl._note_disruption(job.key, "taint", "node/n1")
        ctl._note_disruption(job.key, "DisruptionTarget", "pod/p0")
        assert ctl.preemptions_detected_counter.value == 1
        assert ctl.maybe_handle_disruption(
            job, job.to_dict(), _pods_for(job)) is True
        assert ctl.maybe_handle_disruption(
            job, job.to_dict(), _pods_for(job)) is False


class TestDeleteFanout:
    def test_pod_control_delete_many_overlaps_requests(self, monkeypatch):
        """The delete batch must overlap its API calls exactly like the
        create fan-out: a barrier only opens when all four deletes are
        in flight at once."""
        monkeypatch.setenv("PYTORCH_OPERATOR_CREATE_FANOUT", "8")
        from pytorch_operator_tpu.runtime.controls import PodControl
        from pytorch_operator_tpu.runtime.recorder import FakeRecorder

        barrier = threading.Barrier(4, timeout=5)

        class SlowPods:
            def delete(self, namespace, name):
                barrier.wait()

        control = PodControl(SlowPods(), FakeRecorder())
        results = control.delete_many(
            "ns", [f"p-{i}" for i in range(4)], {})
        assert [err for _, err in results] == [None] * 4
        assert [name for name, _ in results] == [f"p-{i}" for i in range(4)]

    def test_submit_deletes_decrements_per_failure(self):
        from pytorch_operator_tpu.runtime.controls import (
            submit_deletes_with_expectations,
        )

        e = ControllerExpectations()
        key = expectation_pods_key("ns/job", "worker")
        control = FakePodControl()
        control.delete_errors["p-1"] = ApiError("boom")
        with pytest.raises(ApiError):
            submit_deletes_with_expectations(
                e, key, control.delete_many, "ns",
                ["p-0", "p-1", "p-2"], {})
        # 3 raised up-front, 1 rolled back on the failure; the informer
        # observes the 2 real deletes
        assert e.get(key).dels == 2
        assert control.delete_pod_names == ["p-0", "p-2"]

    def test_submit_deletes_rolls_back_all_on_batch_failure(self):
        from pytorch_operator_tpu.runtime.controls import (
            submit_deletes_with_expectations,
        )

        e = ControllerExpectations()
        key = expectation_pods_key("ns/job", "worker")

        def exploding(namespace, names, controller_obj):
            raise RuntimeError("pool torn down mid-batch")

        with pytest.raises(RuntimeError):
            submit_deletes_with_expectations(
                e, key, exploding, "ns", ["p-0", "p-1"], {})
        assert e.satisfied(key)

    def test_clean_pod_policy_all_batches_deletes(self):
        """delete_pods_and_services rides delete_many: one batch per
        replica type, deletion expectations raised."""
        cluster, ctl = _policy_controller(enabled=False)
        job = _gang_job(name="clean-batch")
        job.spec.clean_pod_policy = constants.CLEAN_POD_POLICY_ALL
        pods = _pods_for(job)
        services = [dict(p) for p in pods]  # same labels/names shape
        ctl.delete_pods_and_services(job, job.to_dict(), pods, services)
        assert sorted(ctl.pod_control.delete_pod_names) == sorted(
            p["metadata"]["name"] for p in pods)
        assert sorted(ctl.service_control.delete_service_names) == sorted(
            s["metadata"]["name"] for s in services)
        assert ctl.expectations.get(
            expectation_pods_key(job.key, "worker")).dels == 2
        assert ctl.expectations.get(
            expectation_services_key(job.key, "master")).dels == 1

    def test_clean_pod_policy_running_skips_finished_pods(self):
        cluster, ctl = _policy_controller(enabled=False)
        job = _gang_job(name="clean-running")
        job.spec.clean_pod_policy = constants.CLEAN_POD_POLICY_RUNNING
        pods = _pods_for(job)
        pods[0]["status"] = {"phase": "Succeeded"}
        pods[1]["status"] = {"phase": "Running"}
        pods[2]["status"] = {"phase": "Failed"}
        ctl.delete_pods_and_services(job, job.to_dict(), pods, [])
        assert ctl.pod_control.delete_pod_names == [
            pods[1]["metadata"]["name"]]


class TestHistogram:
    def test_exposition_format(self):
        registry = Registry()
        h = registry.histogram("x_seconds", "help", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = h.expose()
        assert '# TYPE x_seconds histogram' in text
        assert 'x_seconds_bucket{le="0.1"} 1' in text
        assert 'x_seconds_bucket{le="1"} 2' in text
        assert 'x_seconds_bucket{le="+Inf"} 3' in text
        assert 'x_seconds_count 3' in text
        assert h.count == 3 and h.sum == pytest.approx(5.55)
        # rides the registry exposition beside counters/gauges
        assert 'x_seconds_sum' in registry.expose()


# ---------------------------------------------------------------------------
# Sim tier: the acceptance chaos scenario.
# ---------------------------------------------------------------------------


@pytest.fixture
def chaos_world():
    cluster = FakeCluster()
    registry = Registry()
    ctl = PyTorchController(
        cluster,
        config=JobControllerConfig(enable_disruption_handling=True),
        registry=registry)
    # pods run forever until the test flips the decision
    kubelet = FakeKubelet(cluster, decide=lambda pod: None)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    yield cluster, ctl, registry, kubelet
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()


def _running_pods(cluster):
    return [p for p in cluster.pods.list()
            if (p.get("status") or {}).get("phase") == "Running"]


def _finish(cluster, kubelet):
    """Flip the kubelet to success and nudge pods already parked
    Running (their one-shot completion timer fired while decide said
    'keep running')."""
    kubelet.decide = lambda pod: ("Succeeded", 0)
    for pod in _running_pods(cluster):
        kubelet.complete_pod_now("default",
                                 pod["metadata"]["name"])


def test_chaos_one_preempted_worker_one_gang_restart(chaos_world):
    """ISSUE 2 acceptance: taint one of 8 workers' nodes mid-run ->
    exactly one proactive gang restart (single batched delete, a
    TPUPreempted Restarting condition, no expectation leaks) -> the job
    still reaches Succeeded."""
    cluster, ctl, registry, kubelet = chaos_world
    job = new_job(workers=8, name="chaos-job", tpu_chips=4)
    cluster.jobs.create("default", job.to_dict())
    assert wait_for(lambda: len(_running_pods(cluster)) == 9), \
        [p["status"] for p in cluster.pods.list()]
    gen1 = {p["metadata"]["uid"] for p in cluster.pods.list()}

    # record every job-status write so the transient Restarting
    # condition is observable no matter how fast recovery is
    seen_conditions = []
    cluster.jobs.add_listener(
        lambda et, obj: seen_conditions.extend(
            (obj.get("status") or {}).get("conditions") or []))

    victim = cluster.pods.get("default", "chaos-job-worker-3")
    node = victim["spec"]["nodeName"]
    assert node, "fake kubelet did not bind the pod to a node"
    kubelet.inject_preemption(node, grace=0.5)

    # exactly one proactive gang restart fires
    assert wait_for(
        lambda: ctl.preemption_gang_restarts_counter.value == 1)
    # the whole gang is replaced: 9 fresh pods, all Running again
    assert wait_for(lambda: (
        len(_running_pods(cluster)) == 9
        and not gen1 & {p["metadata"]["uid"] for p in cluster.pods.list()}
    )), [p["metadata"]["name"] for p in cluster.pods.list()]

    # restart budget consumed and persisted through the status machine
    assert wait_for(lambda: cluster.jobs.get("default", "chaos-job")
                    ["status"].get("preemptionRestarts") == 1)
    # the Restarting condition carried the TPUPreempted reason
    assert any(c.get("type") == constants.JOB_RESTARTING
               and c.get("reason") == constants.TPU_PREEMPTED_REASON
               for c in seen_conditions)

    _finish(cluster, kubelet)
    assert wait_for(lambda: job_condition(
        cluster, "default", "chaos-job", constants.JOB_SUCCEEDED)), \
        cluster.jobs.get("default", "chaos-job")["status"]

    events = cluster.events.list()
    # one disruption -> one TPUPreempted event, no failure/backoff cycle
    assert len([e for e in events
                if e["reason"] == constants.TPU_PREEMPTED_REASON]) == 1
    assert not [e for e in events if e["reason"] == "PyTorchJobFailed"]
    # single batched delete: exactly the 9 gang pods, nothing else
    deletes = [e for e in events if e["reason"] == "SuccessfulDeletePod"]
    assert len(deletes) == 9
    # metric: detections attributed once, restart latency recorded
    assert ctl.preemptions_detected_counter.value == 1
    assert ctl.preemption_restart_latency.count == 1
    # no expectation leaks
    for rtype in ("master", "worker"):
        assert ctl.expectations.satisfied(
            expectation_pods_key("default/chaos-job", rtype))
        assert ctl.expectations.satisfied(
            expectation_services_key("default/chaos-job", rtype))


@pytest.fixture
def legacy_world():
    cluster = FakeCluster()
    registry = Registry()
    ctl = PyTorchController(cluster, config=JobControllerConfig(),
                            registry=registry)
    kubelet = FakeKubelet(cluster, decide=lambda pod: None)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    yield cluster, ctl, registry, kubelet
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()


def test_chaos_disabled_legacy_per_pod_path_unchanged(legacy_world):
    """With --enable-disruption-handling off, a taint changes nothing
    and a SIGTERM'd worker rides the legacy ExitCode retry: exactly one
    pod deleted/recreated, no TPUPreempted anywhere, job Succeeds."""
    cluster, ctl, registry, kubelet = legacy_world
    assert ctl.node_informer is None and ctl.disruption_watcher is None
    job = new_job(workers=2, name="legacy-job", tpu_chips=4)
    job.spec.pytorch_replica_specs["Worker"].restart_policy = \
        constants.RESTART_POLICY_EXIT_CODE
    cluster.jobs.create("default", job.to_dict())
    assert wait_for(lambda: len(_running_pods(cluster)) == 3)

    victim = cluster.pods.get("default", "legacy-job-worker-1")
    gen1_uid = victim["metadata"]["uid"]
    kubelet.taint_node(victim["spec"]["nodeName"])
    time.sleep(0.3)  # nothing watches nodes: no proactive restart
    assert ctl.preemption_gang_restarts_counter.value == 0
    assert len(_running_pods(cluster)) == 3

    # the preemption lands the old way: worker dies with SIGTERM (143)
    kubelet.fail_pod("default", "legacy-job-worker-1", 143)
    # legacy ExitCode path: that one pod is deleted and recreated
    assert wait_for(lambda: (
        len(_running_pods(cluster)) == 3
        and cluster.pods.get("default", "legacy-job-worker-1")
        ["metadata"]["uid"] != gen1_uid))
    _finish(cluster, kubelet)
    assert wait_for(lambda: job_condition(
        cluster, "default", "legacy-job", constants.JOB_SUCCEEDED))

    events = cluster.events.list()
    assert not [e for e in events
                if e["reason"] == constants.TPU_PREEMPTED_REASON]
    deletes = [e for e in events if e["reason"] == "SuccessfulDeletePod"]
    assert [True for _ in deletes] == [True]  # exactly the one victim
    status = cluster.jobs.get("default", "legacy-job")["status"]
    assert not status.get("preemptionRestarts")
