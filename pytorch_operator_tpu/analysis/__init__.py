"""Concurrency & determinism analysis layer.

The control plane is a heavily threaded, clock-injected system whose
correctness rests on invariants no test tier can reliably surface:

  * "no wall clock in a clock-injectable path" — one raw
    ``time.monotonic()`` silently breaks the simulator's same-seed
    determinism guarantee;
  * "never builtin ``hash()`` for shard placement or cache keys" —
    PYTHONHASHSEED would reshard the fleet per restart;
  * "no blocking I/O while holding a lock" — a sleep or socket call
    inside a ``with lock:`` body convoys every other thread;
  * "one consistent lock order across controller/disruption/sharding" —
    an inverted pair is a latent deadlock that strikes only under
    production interleavings.

This package is the checking machinery itself:

  * :mod:`.rules` + :mod:`.engine` — an AST rule engine with per-line
    pragma waivers (``# lint: wall-clock-ok <reason>``) run by
    ``scripts/lint.py`` and the ``tests/test_analysis.py`` tree-wide
    cleanliness assertion;
  * :mod:`.witness` — a runtime lock-order witness: instrumented
    Lock/RLock factories the runtime's locks are built through, which
    (when enabled) record the per-thread lock-acquisition graph and
    report any cycle with the two offending acquisition stacks;
  * :mod:`.ownership` — the shared-cache read-only contract: the
    blessed ``owned()`` deep-copy helper the ``cache-mutation`` rule
    recognizes as an ownership transfer, plus a client-go-style
    ``CacheMutationDetector`` that fingerprints sampled cached objects
    and reports any in-place mutation with key, field diff, and the
    handler that last received the object.
"""

from .engine import Finding, scan_file, scan_paths, scan_tree  # noqa: F401
from .witness import (  # noqa: F401
    make_lock,
    make_rlock,
    witness_active,
    enable_witness,
    disable_witness,
)
from .ownership import (  # noqa: F401
    owned,
    CacheMutationDetector,
    MutationRecord,
    enable_cache_mutation_detector,
    disable_cache_mutation_detector,
    cache_mutation_detector_active,
)
