"""Lease-based leader election.

The reference elects a leader with a deprecated Endpoints lock named
``pytorch-operator`` (15s lease / 5s renew / 3s retry,
cmd/pytorch-operator.v1/app/server.go:55-57,146-171); this is the same
state machine over the modern Lease object.  Only the elected replica
runs the controller workers; the ``pytorch_operator_is_leader`` gauge
(server.go:58-61) flips with leadership.

Works against any store with get/create/update (the fake cluster's
``resource("leases")`` or a real REST client).
"""

from __future__ import annotations

import threading
import time
from datetime import datetime, timezone
from typing import Callable, Dict, Optional

from pytorch_operator_tpu.k8s.errors import (
    AlreadyExistsError,
    ApiError,
    ConflictError,
    NotFoundError,
)

LEASE_DURATION = 15.0
RENEW_INTERVAL = 5.0
RETRY_INTERVAL = 3.0


def _micro_time_now() -> str:
    """RFC3339 MicroTime string, the wire format the Lease schema requires.

    Kubernetes ``v1.MicroTime`` is RFC3339 with microsecond precision
    (e.g. ``2026-07-29T12:00:00.000000Z``).  A real API server rejects a
    bare float with 422.  These wall-clock timestamps are informational on
    the wire; election expiry is always judged by the *local* observation
    time of record changes (see ``_observed_at``), never by comparing a
    remote clock with ours.
    """
    # lint: wall-clock-ok renewTime is cosmetic wire metadata; election liveness is judged by LOCAL observation of record changes, never by parsing this timestamp
    return datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%S.%fZ")


class LeaderElector:
    def __init__(
        self,
        lease_store,
        identity: str,
        *,
        name: str = "pytorch-operator",
        namespace: str = "default",
        lease_duration: float = LEASE_DURATION,
        renew_interval: float = RENEW_INTERVAL,
        retry_interval: float = RETRY_INTERVAL,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
        labels: Optional[Dict[str, str]] = None,
        annotations: Optional[Callable[[], Dict[str, str]]] = None,
        create_gate: Optional[Callable[[], bool]] = None,
        journal=None,
    ):
        self.lease_store = lease_store
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.retry_interval = retry_interval
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock
        # stamped onto the Lease at creation (shard/heartbeat role
        # labels): lets membership scans LIST with a selector instead
        # of deserializing every Lease in the namespace
        self.labels = dict(labels) if labels else None
        # annotations PROVIDER (not a static dict): resolved at every
        # creation/renewal so the lease can carry live payload — the
        # shard manager's heartbeat publishes per-shard workqueue depth
        # through this.  A failing provider never blocks the renewal
        # (liveness beats telemetry).
        self.annotations = annotations
        # mint fence: when set, a missing Lease is POSTed only while the
        # gate returns True — every other caller keeps GETting 404 and
        # CASes the record once the fenced minter has created it.  Used
        # for leases ALL replicas target at once (migration fence),
        # where unfenced create-on-404 is a guaranteed 409 race.
        self.create_gate = create_gate
        # flight recorder (runtime.journal.EventJournal): lease
        # TRANSITIONS only — acquire (create/takeover), voluntary
        # release, and the first local observation that a foreign
        # holder's record has gone stale.  Steady-state renewals never
        # journal; the ring stays quiet unless ownership moves.
        self.journal = journal
        self.is_leader = False
        self._stop = threading.Event()
        self._active_stop = self._stop
        self._thread: Optional[threading.Thread] = None
        # client-go semantics: expiry is judged against the *local*
        # observation time of the last lease change, never by comparing
        # another process's timestamps with our clock (clocks across nodes
        # are not comparable; monotonic clocks especially so).
        self._observed_record: Optional[tuple] = None
        self._observed_at: float = 0.0
        # Last *successful* renew (local clock): on transient API errors a
        # sitting leader retains leadership until the lease it last wrote
        # has actually expired (client-go renewDeadline semantics) instead
        # of stepping down — and with --leader-elect, shutting the whole
        # operator down — on a single 500.
        self._last_renew: float = 0.0
        # last record tuple whose expiry we journaled: observe() runs
        # every tick, but one dead holder is ONE expiry event
        self._expiry_journaled: Optional[tuple] = None

    def _journal(self, kind: str, **attrs) -> None:
        if self.journal is not None:
            self.journal.record(kind, lease=self.name, **attrs)

    # -- lease record helpers ---------------------------------------------

    def _provided_annotations(self) -> Dict[str, str]:
        if self.annotations is None:
            return {}
        try:
            return dict(self.annotations() or {})
        except Exception:
            return {}

    def _lease_obj(self) -> dict:
        ts = _micro_time_now()
        meta: dict = {"name": self.name, "namespace": self.namespace}
        if self.labels:
            meta["labels"] = dict(self.labels)
        annotations = self._provided_annotations()
        if annotations:
            meta["annotations"] = annotations
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": meta,
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": max(1, int(round(self.lease_duration))),
                "acquireTime": ts,
                "renewTime": ts,
                "leaseTransitions": 0,
            },
        }

    def try_acquire_or_renew(self) -> bool:
        """One CAS round: returns True if we hold the lease afterwards.

        Any API error other than the expected CAS races (AlreadyExists /
        Conflict) degrades gracefully instead of killing the thread on
        e.g. a 422/InvalidError: a non-leader treats it as "not leader
        this round"; a sitting leader retains leadership until the lease
        duration has elapsed since its last successful renew.
        """
        now = self.clock()

        def _degraded() -> bool:
            return (self.is_leader
                    and now - self._last_renew < self.lease_duration)

        try:
            lease = self.lease_store.get(self.namespace, self.name)
        except NotFoundError:
            if self.create_gate is not None:
                try:
                    if not self.create_gate():
                        return False  # not the designated minter
                except Exception:
                    return False
            try:
                self.lease_store.create(self.namespace, self._lease_obj())
                self._last_renew = now
                self._journal("lease_acquired", via="created",
                              holder=self.identity)
                return True
            except AlreadyExistsError:
                return False
            except ApiError:
                return _degraded()
        except ApiError:
            return _degraded()
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        duration = float(spec.get("leaseDurationSeconds") or self.lease_duration)
        record = (holder, spec.get("renewTime"))
        if record != self._observed_record:
            self._observed_record = record
            self._observed_at = now
        # An EMPTY holder is a released lease (client-go's ReleaseOnCancel
        # writes holderIdentity "" on the way out): immediately acquirable,
        # no expiry wait — shard handoff between cooperating replicas
        # rides this.
        if holder and holder != self.identity \
                and now - self._observed_at < duration:
            return False  # holder's record changed within leaseDuration (locally observed)
        ts = _micro_time_now()
        taking_over = holder != self.identity
        if self.labels:
            # stamp the role labels on renewal/takeover too, not only
            # at creation: a Lease minted by a pre-label build must
            # become selector-visible the moment a labeling build
            # renews it, or membership scans exclude its replica
            # forever rather than for one upgrade window
            meta = lease.setdefault("metadata", {})
            labels = dict(meta.get("labels") or {})
            if any(labels.get(k) != v for k, v in self.labels.items()):
                labels.update(self.labels)
                meta["labels"] = labels
        annotations = self._provided_annotations()
        if annotations:
            # refresh the provider's annotations on every renewal (the
            # heartbeat's load payload changes per tick); keys the
            # provider stops emitting keep their last value — staleness
            # is bounded by the lease expiry consumers already apply
            meta = lease.setdefault("metadata", {})
            merged = dict(meta.get("annotations") or {})
            merged.update(annotations)
            meta["annotations"] = merged
        lease["spec"] = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": max(1, int(round(self.lease_duration))),
            "acquireTime": ts if taking_over else (spec.get("acquireTime") or ts),
            "renewTime": ts,
            "leaseTransitions": int(spec.get("leaseTransitions") or 0)
            + (1 if taking_over else 0),
        }
        try:
            updated = self.lease_store.update(lease)
            spec = updated.get("spec") or {}
            self._observed_record = (spec.get("holderIdentity"), spec.get("renewTime"))
            self._observed_at = now
            self._last_renew = now
            if taking_over:
                self._journal("lease_acquired", via="takeover",
                              holder=self.identity,
                              prev_holder=holder or "")
            return True
        except (ConflictError, NotFoundError):
            return False
        except ApiError:
            return _degraded()

    def observe(self) -> tuple:
        """Track the lease record WITHOUT competing for it: one GET that
        advances the local change-observation clock (the same rule
        try_acquire_or_renew applies), returning ``(holder, acquirable)``
        — acquirable when the lease is absent, released (empty holder),
        already ours, or its holder's record has not changed for a full
        leaseDuration of local observation.  The shard manager calls
        this every tick for shards it does not own, so a dead holder's
        expiry clock starts at death, not at the first acquisition
        attempt."""
        now = self.clock()
        try:
            lease = self.lease_store.get(self.namespace, self.name)
        except NotFoundError:
            return None, True
        except ApiError:
            return None, False
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        duration = float(spec.get("leaseDurationSeconds")
                         or self.lease_duration)
        record = (holder, spec.get("renewTime"))
        if record != self._observed_record:
            self._observed_record = record
            self._observed_at = now
        if not holder or holder == self.identity:
            return holder, True
        stale_s = now - self._observed_at
        if stale_s < duration:
            return holder, False
        # First local observation that this holder's record went a full
        # leaseDuration without changing: the flight-recorder anchor for
        # the DETECTION stage of a handoff.  ``stale_s`` lets a journal
        # consumer back the vacancy start out of the event timestamp
        # (wall - stale_s = the holder's last observed renewal).
        if self._expiry_journaled != record:
            self._expiry_journaled = record
            self._journal("lease_expiry_observed", holder=holder,
                          stale_s=stale_s)
        return holder, True

    def release(self) -> None:
        """Voluntarily hand the lease back (client-go ReleaseOnCancel):
        write an empty holderIdentity so the next contender acquires
        immediately instead of waiting out the lease duration.
        Best-effort — on any API error the lease simply expires."""
        self.is_leader = False
        try:
            lease = self.lease_store.get(self.namespace, self.name)
        except ApiError:
            return
        spec = lease.get("spec") or {}
        if spec.get("holderIdentity") != self.identity:
            return  # someone else took over; nothing to release
        lease["spec"] = dict(spec, holderIdentity="",
                             renewTime=_micro_time_now())
        try:
            self.lease_store.update(lease)
            self._journal("lease_released", holder=self.identity)
        except ApiError:
            pass

    # -- run loop ----------------------------------------------------------

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """Block until stopped; invokes callbacks on leadership changes."""
        stop = stop_event or self._stop
        self._active_stop = stop
        while not stop.is_set():
            if self.try_acquire_or_renew():
                if not self.is_leader:
                    self.is_leader = True
                    if self.on_started_leading:
                        self.on_started_leading()
                interval = self.renew_interval
            else:
                if self.is_leader:
                    self.is_leader = False
                    if self.on_stopped_leading:
                        self.on_stopped_leading()
                interval = self.retry_interval
            stop.wait(interval)
        if self.is_leader:
            self.is_leader = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def start(self, stop_event: Optional[threading.Event] = None) -> threading.Thread:
        self._thread = threading.Thread(
            target=self.run, args=(stop_event,), daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        self._active_stop.set()
        if self._thread:
            self._thread.join(timeout=5)
