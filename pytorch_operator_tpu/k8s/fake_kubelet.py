"""Fake kubelet: advances pod phases like a node would.

The reference has no simulation tier between fake-control unit tests and a
real GKE cluster (SURVEY.md §4).  This fills that gap: subscribed to the
fake cluster's pod store, it walks created pods through
Pending -> Running -> Succeeded/Failed on a background thread, so the full
controller loop (informers, workqueue, status machine, GC) can be
exercised end-to-end in-process — the e2e driver
(test/e2e/v1/default/defaults.go) flow without a cluster.

It also plays the node side of the cluster: every pod is bound to a Node
object (``spec.nodeName``), lazily provisioning fake TPU nodes the way a
GKE node pool would, and exposes a chaos-injection API
(:meth:`FakeKubelet.inject_preemption`) that scripts the GCE preemption
sequence — taint the node with the impending-termination taint, then
SIGTERM (exit 143) every pod on it after a grace window — so sim/e2e
tests can drive the disruption subsystem through realistic storms.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..analysis.witness import make_lock
from .errors import ApiError, NotFoundError
from .fake import ADDED, FakeCluster

# GCE/GKE disruption vocabulary — shared with disruption.detector via
# api/v1/constants so injection and recognition cannot drift.
from ..api.v1 import constants as _api_constants

IMPENDING_PREEMPTION_TAINT = _api_constants.IMPENDING_NODE_TERMINATION_TAINT
TPU_RESOURCE = _api_constants.TPU_RESOURCE
TPU_ACCELERATOR_LABEL = _api_constants.NODE_SELECTOR_TPU_ACCELERATOR

# SIGTERM exit code a preempted container reports.
SIGTERM_EXIT_CODE = 143


def _now_iso(now: Optional[float] = None) -> str:
    """RFC3339 timestamp; ``now`` (epoch seconds, e.g. a VirtualClock's
    ``now``) overrides the real wall clock."""
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now))


def new_tpu_node(name: str, tpu_chips: int = 4,
                 accelerator: str = "tpu-v4-podslice") -> dict:
    """A Ready TPU node in wire format (what a GKE TPU node pool adds)."""
    chips = str(tpu_chips)
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {
            "name": name,
            "labels": {TPU_ACCELERATOR_LABEL: accelerator},
        },
        "spec": {},
        "status": {
            "conditions": [{"type": "Ready", "status": "True",
                            "lastTransitionTime": _now_iso()}],
            "capacity": {TPU_RESOURCE: chips},
            "allocatable": {TPU_RESOURCE: chips},
        },
    }


class FakeKubelet:
    def __init__(
        self,
        cluster: FakeCluster,
        run_delay: float = 0.02,
        complete_delay: float = 0.05,
        # decide(pod) -> ("Succeeded"|"Failed", exit_code), or None to
        # leave the pod Running forever.
        decide: Optional[Callable[[dict], Optional[tuple]]] = None,
        # logs(pod, phase, exit_code) -> str stored on the pod, readable
        # via the SDK's get_logs (fake.kubelet/logs annotation)
        logs: Optional[Callable[[dict, str, int], str]] = None,
        # Node-pool shape: None (default) provisions a fresh node per pod
        # — one worker per TPU VM, the slice topology the disruption
        # tests rely on (tainting one node hits exactly one replica).
        # An int N round-robins pods over at most N healthy nodes.
        max_nodes: Optional[int] = None,
        # Base URL of the operator's metrics server (http://host:port).
        # When set, each completing pod plays the trainer's telemetry
        # side: it POSTs `push_steps` per-step samples for its owning
        # job to /push/v1/metrics (telemetry/push.py), so the sim tier
        # exercises the full job-pushes -> operator-exports loop.
        # Assignable after construction (the operator wires it once the
        # server has a port).
        telemetry_url: Optional[str] = None,
        push_steps: int = 3,
        # Elastic drain protocol: a pod annotated checkpoint-requested
        # answers with the checkpointed ack after this delay (the sim's
        # stand-in for SIGTERM -> orbax save -> exit readiness).  None
        # never acks, so drains run to their deadline.
        checkpoint_delay: Optional[float] = 0.02,
        # Cluster-scale simulator hooks (pytorch_operator_tpu.sim): a
        # NodeFleet replaces the lazily-minted-node behavior — pods
        # bind round-robin onto the fleet's fixed node population and
        # each pod's Pending/Running dwell comes from its node's seeded
        # latency profile instead of run_delay/complete_delay; a
        # VirtualClock replaces threading.Timer so every phase
        # transition fires deterministically in virtual time.
        fleet=None,
        clock=None,
    ):
        self.cluster = cluster
        self.run_delay = run_delay
        self.complete_delay = complete_delay
        self.telemetry_url = telemetry_url
        self.push_steps = push_steps
        self.checkpoint_delay = checkpoint_delay
        self.decide = decide or (lambda pod: ("Succeeded", 0))
        self.logs = logs or (
            lambda pod, phase, code:
            f"{pod['metadata']['name']}: {phase} exit={code}\naccuracy=0.9876\n"
        )
        self.max_nodes = max_nodes
        self.fleet = fleet
        self.clock = clock
        self._node_seq = 0
        self._bind_rr = 0
        # Node pool bookkeeping: a deleted pod releases its (still
        # healthy) node for reuse, so long churn runs hold the node
        # count at ~peak concurrent pods instead of growing one node
        # per pod ever created — tainted/NotReady nodes are never
        # reused (a preempted VM is replaced, not recycled).
        self._node_of_pod: Dict[str, str] = {}
        self._free_nodes: List[str] = []
        # capacity freeze (a REAL dip, not just taints): while frozen,
        # no fresh nodes are provisioned — pods beyond the freed-node
        # pool wait here unbound/Pending until a node frees or the
        # freeze lifts.  CapacityFlap(freeze_capacity=True) drives it.
        self._capacity_frozen = False
        self._bind_queue: List[tuple] = []
        self._timers: Dict[str, threading.Timer] = {}
        self._lock = make_lock("fake-kubelet")
        self._stopped = False

    def start(self) -> None:
        if self.fleet is not None:
            self.fleet.provision(self.cluster)
        self.cluster.pods.add_listener(self._on_pod_event)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            for t in self._timers.values():
                t.cancel()
            self._timers.clear()
        self.cluster.pods.remove_listener(self._on_pod_event)

    # -- node pool ---------------------------------------------------------
    def _provision_node(self) -> str:
        with self._lock:
            self._node_seq += 1
            name = f"fake-tpu-node-{self._node_seq}"
        try:
            self.cluster.nodes.create("default", new_tpu_node(name))
        except ApiError:
            pass  # name collision with a pre-seeded node: reuse it
        return name

    @staticmethod
    def _schedulable(node: dict) -> bool:
        if (node.get("spec") or {}).get("taints"):
            return False
        for cond in (node.get("status") or {}).get("conditions") or []:
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False

    def _pop_free_node(self) -> Optional[str]:
        """The next still-schedulable freed node, or None when the pool
        is dry."""
        # never hold self._lock across a cluster-store call: store
        # listeners run under the cluster lock and re-enter here
        while True:
            with self._lock:
                candidate = (self._free_nodes.pop()
                             if self._free_nodes else None)
            if candidate is None:
                return None
            try:
                node = self.cluster.nodes.get("default", candidate)
            except NotFoundError:
                continue
            if self._schedulable(node):
                return candidate

    def _pick_node(self) -> Optional[str]:
        """A freed healthy node when one exists, else a fresh node
        (one per live pod — one worker per TPU VM); bounded round-robin
        over healthy nodes when ``max_nodes`` caps the pool; None while
        the capacity freeze is on and no freed node is available."""
        with self._lock:
            frozen = self._capacity_frozen
        if frozen:
            return self._pop_free_node()
        if self.fleet is not None:
            return self.fleet.assign()
        if self.max_nodes is None:
            reused = self._pop_free_node()
            return reused if reused is not None else self._provision_node()
        healthy = sorted(
            n["metadata"]["name"]
            for n in self.cluster.nodes.list()
            if self._schedulable(n)
        )
        if len(healthy) < self.max_nodes:
            return self._provision_node()
        with self._lock:
            self._bind_rr = (self._bind_rr + 1) % len(healthy)
            return healthy[self._bind_rr]

    def _bind_pod(self, ns: str, name: str, pod: dict) -> bool:
        """Bind the pod to a node.  Returns False only when the
        capacity freeze left no node to bind to — the pod is queued and
        stays Pending until a node frees or the freeze lifts."""
        if (pod.get("spec") or {}).get("nodeName"):
            return True
        node = self._pick_node()
        if node is None:
            with self._lock:
                if (ns, name) not in self._bind_queue:
                    self._bind_queue.append((ns, name))
            return False
        try:
            self.cluster.pods.patch(ns, name, {"spec": {"nodeName": node}})
        except NotFoundError:
            return True  # pod raced deletion: downstream phase timers no-op
        with self._lock:
            self._node_of_pod[f"{ns}/{name}"] = node
        return True

    def _release_node(self, ns: str, name: str) -> None:
        with self._lock:
            node = self._node_of_pod.pop(f"{ns}/{name}", None)
        if node is None:
            return
        if self.fleet is not None:
            self.fleet.release(node)
            return
        try:
            healthy = self._schedulable(
                self.cluster.nodes.get("default", node))
        except NotFoundError:
            return
        if healthy:
            with self._lock:
                self._free_nodes.append(node)
            # a node freed mid-freeze goes straight to a waiting pod —
            # within a dip the surviving capacity keeps circulating
            self._drain_bind_queue()

    def _pod_delays(self, ns: str, name: str):
        """(run_delay, complete_delay) for one pod: the bound node's
        fleet profile when a NodeFleet paces this kubelet, the global
        knobs otherwise."""
        if self.fleet is None:
            return self.run_delay, self.complete_delay
        with self._lock:
            node = self._node_of_pod.get(f"{ns}/{name}")
        profile = self.fleet.profile(node) if node else None
        if profile is None:
            return self.run_delay, self.complete_delay
        return profile.run_delay, profile.complete_delay

    # -- capacity freeze ---------------------------------------------------
    def freeze_capacity(self) -> None:
        """Stop provisioning fresh nodes: the fleet's current healthy
        nodes are ALL the capacity there is (a genuine dip).  Unbindable
        pods stay Pending until a node frees or ``unfreeze_capacity``."""
        with self._lock:
            self._capacity_frozen = True

    def unfreeze_capacity(self) -> None:
        with self._lock:
            self._capacity_frozen = False
        self._drain_bind_queue()

    def _drain_bind_queue(self) -> None:
        while True:
            with self._lock:
                if not self._bind_queue:
                    return
                ns, name = self._bind_queue.pop(0)
            try:
                pod = self.cluster.pods.get(ns, name)
            except NotFoundError:
                continue  # deleted while waiting: just drop it
            if not self._bind_pod(ns, name, pod):
                return  # still no capacity: _bind_pod re-queued it
            self._schedule(f"{ns}/{name}/run",
                           self._pod_delays(ns, name)[0],
                           self._run_pod, ns, name)

    def _ts(self) -> str:
        """RFC3339 stamp on the kubelet's clock (virtual when injected)."""
        return _now_iso(self.clock.now() if self.clock is not None else None)

    # -- chaos injection ---------------------------------------------------
    def taint_node(self, name: str, key: str = IMPENDING_PREEMPTION_TAINT,
                   value: str = "", effect: str = "NoSchedule") -> None:
        """Append a taint to the node (idempotent per key) — how GCE
        announces an impending preemption ahead of the actual kill."""
        node = self.cluster.nodes.get("default", name)
        taints = (node.get("spec") or {}).get("taints") or []
        if any(t.get("key") == key for t in taints):
            return
        taints = taints + [{"key": key, "value": value, "effect": effect,
                            "timeAdded": self._ts()}]
        self.cluster.nodes.patch("default", name, {"spec": {"taints": taints}})

    def set_node_ready(self, name: str, ready: bool,
                       reason: str = "") -> None:
        """Flip the node's Ready condition (NotReady TPU nodes are a
        disruption signal of their own)."""
        status = "True" if ready else "False"
        self.cluster.nodes.patch("default", name, {"status": {"conditions": [
            {"type": "Ready", "status": status, "reason": reason,
             "lastTransitionTime": self._ts()},
        ]}})

    def pods_on_node(self, name: str) -> List[dict]:
        return [
            p for p in self.cluster.pods.list()
            if (p.get("spec") or {}).get("nodeName") == name
        ]

    def fail_pod(self, ns: str, name: str,
                 exit_code: int = SIGTERM_EXIT_CODE) -> None:
        """Kill one pod: cancel its pending phase timers and mark it
        Failed with the given exit code (143 = SIGTERM'd by the node)."""
        with self._lock:
            for key in (f"{ns}/{name}/run", f"{ns}/{name}/complete"):
                timer = self._timers.pop(key, None)
                if timer is not None:
                    timer.cancel()
        try:
            self.cluster.pods.set_status(ns, name, {
                "phase": "Failed",
                "reason": "Terminated",
                "containerStatuses": [
                    {
                        "name": "pytorch",
                        "restartCount": 0,
                        "state": {"terminated": {"exitCode": exit_code}},
                    }
                ],
            })
        except NotFoundError:
            pass

    def inject_preemption(self, node_name: str, taint_delay: float = 0.0,
                          grace: float = 0.05,
                          exit_code: int = SIGTERM_EXIT_CODE,
                          taint_key: str = IMPENDING_PREEMPTION_TAINT) -> None:
        """Script one node preemption: taint at T+``taint_delay``, then
        after ``grace`` kill every pod still bound to the node with
        ``exit_code``.  The window between taint and kill is what the
        disruption subsystem races — a proactive gang restart inside it
        replaces N independent failure/backoff cycles."""

        def _kill() -> None:
            for pod in self.pods_on_node(node_name):
                meta = pod.get("metadata") or {}
                self.fail_pod(meta.get("namespace", "default"),
                              meta.get("name", ""), exit_code)

        def _taint() -> None:
            try:
                self.taint_node(node_name, key=taint_key, effect="NoSchedule")
            except NotFoundError:
                return
            self._schedule(f"node/{node_name}/kill", grace, _kill)

        if taint_delay > 0:
            self._schedule(f"node/{node_name}/taint", taint_delay, _taint)
        else:
            _taint()

    def untaint_node(self, name: str, key: Optional[str] = None) -> None:
        """Remove the node's taints (all of them, or just ``key``) — the
        capacity-returns half of a CapacityFlap: a reclaimed spot VM
        handed back to the pool."""
        node = self.cluster.nodes.get("default", name)
        taints = (node.get("spec") or {}).get("taints") or []
        if key is not None:
            taints = [t for t in taints if t.get("key") != key]
        else:
            taints = []
        self.cluster.nodes.patch(
            "default", name, {"spec": {"taints": taints or None}})

    # -- elastic drain protocol --------------------------------------------
    def _maybe_ack_checkpoint(self, ns: str, name: str, pod: dict) -> None:
        """A pod the controller signalled to checkpoint answers with the
        checkpointed ack after ``checkpoint_delay`` — the sim's stand-in
        for the SIGTERM-driven orbax save a real trainer performs."""
        if self.checkpoint_delay is None:
            return
        meta = pod.get("metadata") or {}
        annotations = meta.get("annotations") or {}
        if _api_constants.ANNOTATION_CHECKPOINT_REQUESTED not in annotations:
            return
        if _api_constants.ANNOTATION_CHECKPOINTED in annotations:
            return
        if ((pod.get("status") or {}).get("phase")) in ("Succeeded",
                                                        "Failed"):
            return  # already dead: nothing left to checkpoint
        self._schedule(f"{ns}/{name}/checkpoint", self.checkpoint_delay,
                       self._ack_checkpoint, ns, name)

    def _ack_checkpoint(self, ns: str, name: str) -> None:
        try:
            pod = self.cluster.pods.get(ns, name)
        except NotFoundError:
            return
        annotations = (pod.get("metadata") or {}).get("annotations") or {}
        if _api_constants.ANNOTATION_CHECKPOINTED in annotations:
            return
        try:
            self.cluster.pods.patch(ns, name, {"metadata": {"annotations": {
                _api_constants.ANNOTATION_CHECKPOINTED: self._ts(),
            }}})
        except NotFoundError:
            pass

    def complete_pod_now(self, ns: str, name: str) -> None:
        """Test hook: run the completion decision for one pod
        immediately — pods parked Running by a ``decide`` that returned
        None re-consult the (possibly swapped) decision."""
        self._complete_pod(ns, name)

    # ------------------------------------------------------------------
    def _on_pod_event(self, event_type: str, pod: dict) -> None:
        meta = pod.get("metadata", {})
        ns, name = meta.get("namespace", "default"), meta.get("name", "")
        if event_type == "DELETED":
            self._release_node(ns, name)
            return
        if event_type == "MODIFIED":
            self._maybe_ack_checkpoint(ns, name, pod)
            return
        if event_type != ADDED:
            return
        bound = self._bind_pod(ns, name, pod)
        self._set_phase(ns, name, "Pending")
        if bound:
            self._schedule(f"{ns}/{name}/run",
                           self._pod_delays(ns, name)[0],
                           self._run_pod, ns, name)

    def _run_pod(self, ns: str, name: str) -> None:
        self._set_phase(ns, name, "Running")
        self._schedule(
            f"{ns}/{name}/complete", self._pod_delays(ns, name)[1],
            self._complete_pod, ns, name
        )

    def _complete_pod(self, ns: str, name: str) -> None:
        try:
            pod = self.cluster.pods.get(ns, name)
        except NotFoundError:
            return
        decision = self.decide(pod)
        if decision is None:
            return
        phase, exit_code = decision
        status = {
            "phase": phase,
            "containerStatuses": [
                {
                    "name": "pytorch",
                    "restartCount": 0,
                    "state": {"terminated": {"exitCode": exit_code}},
                }
            ],
        }
        self._push_telemetry(pod)
        try:
            # logs BEFORE the terminal status: a process writes its
            # output and then exits, and follow-mode log streams close
            # on the terminal phase — writing the text first guarantees
            # a tailer sees the final lines before the stream ends
            log_text = self.logs(pod, phase, exit_code)
            if log_text:
                self.cluster.pods.patch(ns, name, {
                    "metadata": {"annotations": {"fake.kubelet/logs": log_text}}
                })
            self.cluster.pods.set_status(ns, name, status)
        except NotFoundError:
            pass

    def _push_telemetry(self, pod: dict) -> None:
        """Push synthetic per-step samples for the pod's owning job —
        the trainer's side of the telemetry loop, played by the sim
        tier.  Best-effort by design: a missing or dead metrics server
        must not change pod lifecycle."""
        url = self.telemetry_url
        if not url:
            return
        meta = pod.get("metadata") or {}
        job_name = (meta.get("labels") or {}).get(
            _api_constants.LABEL_JOB_NAME)
        if not job_name:
            return
        job = f"{meta.get('namespace', 'default')}/{job_name}"
        # the trainer reads its push-identity token from the env the
        # operator injected at pod build time — the fake kubelet plays
        # that side by reading the rendered pod spec
        token = None
        for container in (pod.get("spec") or {}).get("containers") or []:
            for env in container.get("env") or []:
                if env.get("name") == _api_constants.ENV_PUSH_TOKEN:
                    token = env.get("value")
                    break
        try:
            from pytorch_operator_tpu.telemetry.push import push_job_steps

            # fixed synthetic step shape: complete_delay spread over
            # push_steps steps, nominal sim-tier throughput figures
            step = max(self.complete_delay / max(1, self.push_steps), 1e-4)
            push_job_steps(url, job, [step] * self.push_steps,
                           tokens_per_sec=round(4096.0 / step, 1),
                           mfu=0.5, timeout=2.0, token=token)
        except Exception:
            pass

    def _set_phase(self, ns: str, name: str, phase: str) -> None:
        try:
            self.cluster.pods.set_status(ns, name, {"phase": phase})
        except NotFoundError:
            pass

    def _schedule(self, key: str, delay: float, fn, *args) -> None:
        with self._lock:
            if self._stopped:
                return
            if self.clock is not None:
                timer = self.clock.timer(delay, fn, args)
            else:
                # lint: wall-clock-ok intended fallback when no VirtualClock is injected — the live-timer kubelet tier runs on real threading timers
                timer = threading.Timer(delay, fn, args=args)
                timer.daemon = True
            self._timers[key] = timer
            timer.start()
