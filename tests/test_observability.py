"""Sim-e2e acceptance for the observability layer (ISSUE 3): a job run
to Succeeded on the fake cluster exposes labeled workqueue and
sync-duration series on /metrics, /debug/traces returns a complete
reconcile trace whose child spans cover the creates and the status
patch, and /healthz /readyz reflect the registered checks."""

from __future__ import annotations

import json
import re
import threading
import urllib.error
import urllib.request

import pytest

from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.metrics.server import start_metrics_server
from pytorch_operator_tpu.runtime import JobControllerConfig
from pytorch_operator_tpu.runtime.tracing import Tracer
from testutil import new_job, wait_for


def _get(port: int, path: str):
    return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                  timeout=5)


def _series_value(text: str, series: str) -> float:
    m = re.search(rf"^{re.escape(series)} (\S+)$", text, re.M)
    assert m, f"series {series!r} not found in exposition"
    return float(m.group(1))


@pytest.fixture
def world(e2e_artifacts):
    cluster = FakeCluster()
    registry = Registry()
    tracer = Tracer(buffer_size=64)
    ctl = PyTorchController(cluster, config=JobControllerConfig(),
                            registry=registry, tracer=tracer)
    kubelet = FakeKubelet(cluster)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    server = start_metrics_server(
        registry, 0, host="127.0.0.1", tracer=tracer,
        health_checks={
            "healthz": lambda: (not stop.is_set(), {}),
            "readyz": lambda: (ctl.informers_synced(),
                               {"informers_synced": ctl.informers_synced()}),
        })
    # a failing e2e test gets its /metrics + /debug/traces captured
    # into the artifact dir before this fixture tears the server down
    e2e_artifacts["port"] = server.server_address[1]
    yield cluster, ctl, registry, kubelet, server.server_address[1]
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()
    server.shutdown()


def _job_succeeded(cluster, name: str) -> bool:
    job = cluster.jobs.get("default", name)
    return any(c.get("type") == "Succeeded" and c.get("status") == "True"
               for c in (job.get("status") or {}).get("conditions") or [])


def test_sim_e2e_labeled_metrics_and_traces(world):
    cluster, ctl, registry, kubelet, port = world
    cluster.jobs.create("default", new_job(workers=2, name="obs-job")
                        .to_dict())
    assert wait_for(lambda: _job_succeeded(cluster, "obs-job"), timeout=30)

    text = _get(port, "/metrics").read().decode()
    # labeled workqueue depth/latency series (client-go names)
    assert _series_value(text, 'workqueue_depth{name="pytorchjob"}') >= 0
    assert _series_value(
        text, 'workqueue_adds_total{name="pytorchjob"}') > 0
    assert _series_value(
        text,
        'workqueue_queue_duration_seconds_count{name="pytorchjob"}') > 0
    assert _series_value(
        text,
        'workqueue_work_duration_seconds_count{name="pytorchjob"}') > 0
    # sync-duration histogram labeled by result
    assert _series_value(
        text,
        'pytorch_operator_reconcile_duration_seconds_count'
        '{result="success"}') > 0
    # informer + fan-out batch series rode along
    assert _series_value(
        text, 'pytorch_operator_informer_events_total'
              '{informer="pods",type="added"}') >= 3
    assert _series_value(
        text, 'pytorch_operator_batch_duration_seconds_count'
              '{kind="pod",op="create"}') > 0

    # at least one complete reconcile trace covering creates + status patch
    traces = json.loads(_get(port, "/debug/traces").read())["traces"]
    assert traces

    def names(trace, acc):
        acc.add(trace["name"])
        for child in trace.get("children", []):
            names(child, acc)
        return acc

    covering = [t for t in traces
                if t["name"] == "reconcile"
                and {"creates", "status-patch"} <= names(t, set())]
    assert covering, [sorted(names(t, set())) for t in traces]
    trace = covering[0]
    assert trace["attrs"]["key"] == "default/obs-job"
    assert trace["duration_ms"] >= 0
    # per-item create spans propagated through the fan-out executor
    all_names = names(trace, set())
    assert "create-pod" in all_names

    # ?limit honored
    limited = json.loads(
        _get(port, "/debug/traces?limit=1").read())["traces"]
    assert len(limited) == 1


def test_health_endpoints(world):
    _cluster, _ctl, _registry, _kubelet, port = world
    assert _get(port, "/healthz").status == 200
    body = json.loads(_get(port, "/readyz").read())
    assert body["status"] == "ok"
    assert body["informers_synced"] is True


def test_readyz_reports_503_when_not_ready():
    registry = Registry()
    server = start_metrics_server(
        registry, 0, host="127.0.0.1",
        health_checks={"readyz": lambda: (False, {"leader": False})})
    port = server.server_address[1]
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(port, "/readyz")
        assert exc.value.code == 503
        assert json.loads(exc.value.read())["status"] == "unavailable"
        # healthz has no registered check: bare liveness is 200
        assert _get(port, "/healthz").status == 200
        # no tracer configured: the debug endpoint 404s
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(port, "/debug/traces")
        assert exc.value.code == 404
    finally:
        server.shutdown()


def test_operator_flags_exist():
    """--trace-buffer-size / --slow-reconcile-threshold parse."""
    from pytorch_operator_tpu.cmd.operator import build_parser

    args = build_parser().parse_args(
        ["--trace-buffer-size", "16",
         "--slow-reconcile-threshold", "250ms"])
    assert args.trace_buffer_size == 16
    assert args.slow_reconcile_threshold == "250ms"
