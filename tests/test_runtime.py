"""Runtime machinery tests: workqueue, expectations, informer, adoption.

Mirrors the reference's pod_test.go:34 / service_test.go:33 expectation
bookkeeping tests plus client-go workqueue semantics.
"""

import threading
import time

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import (
    ControllerExpectations,
    FakeRecorder,
    Informer,
    JobControllerConfig,
    WorkQueue,
    expectation_pods_key,
)

from testutil import TEST_NAMESPACE, new_job


# --------------------------------------------------------------------------
# workqueue
# --------------------------------------------------------------------------


def test_workqueue_dedup_while_queued():
    q = WorkQueue()
    q.add("a")
    q.add("a")
    q.add("b")
    assert len(q) == 2


def test_workqueue_no_concurrent_processing():
    """An item re-added while processing is deferred until done()."""
    q = WorkQueue()
    q.add("a")
    item, _ = q.get(timeout=0.1)
    assert item == "a"
    q.add("a")  # while processing
    got, _ = q.get(timeout=0.05)
    assert got is None  # not handed out again yet
    q.done("a")
    item2, _ = q.get(timeout=0.1)
    assert item2 == "a"


def test_workqueue_add_after():
    q = WorkQueue()
    q.add_after("x", 0.05)
    got, _ = q.get(timeout=0.01)
    assert got is None
    got, _ = q.get(timeout=0.5)
    assert got == "x"


def test_workqueue_rate_limit_backoff_and_forget():
    q = WorkQueue()
    assert q.num_requeues("k") == 0
    q.add_rate_limited("k")
    assert q.num_requeues("k") == 1
    q.add_rate_limited("k")
    assert q.num_requeues("k") == 2
    q.forget("k")
    assert q.num_requeues("k") == 0


def test_workqueue_shutdown_unblocks():
    q = WorkQueue()
    results = []

    def worker():
        results.append(q.get())

    t = threading.Thread(target=worker)
    t.start()
    time.sleep(0.05)
    q.shutdown()
    t.join(timeout=1)
    assert results == [(None, True)]


# --------------------------------------------------------------------------
# expectations
# --------------------------------------------------------------------------


def test_expectations_lifecycle():
    e = ControllerExpectations()
    key = expectation_pods_key("ns/job", "Worker")
    assert e.satisfied(key)  # never set
    e.expect_creations(key, 2)
    assert not e.satisfied(key)
    e.creation_observed(key)
    assert not e.satisfied(key)
    e.creation_observed(key)
    assert e.satisfied(key)
    e.expect_deletions(key, 1)
    assert not e.satisfied(key)
    e.deletion_observed(key)
    assert e.satisfied(key)


# --------------------------------------------------------------------------
# informer
# --------------------------------------------------------------------------


def test_informer_sync_and_watch():
    c = FakeCluster()
    c.pods.create("ns", {"metadata": {"name": "pre", "namespace": "ns"}})
    inf = Informer(c.pods)
    adds, updates, deletes = [], [], []
    inf.add_event_handler(
        on_add=lambda o: adds.append(o["metadata"]["name"]),
        on_update=lambda old, new: updates.append(
            (old["metadata"]["resourceVersion"], new["metadata"]["resourceVersion"])
        ),
        on_delete=lambda o: deletes.append(o["metadata"]["name"]),
    )
    inf.start()
    assert inf.has_synced()
    assert adds == ["pre"]

    c.pods.create("ns", {"metadata": {"name": "live", "namespace": "ns"}})
    c.pods.set_status("ns", "live", {"phase": "Running"})
    c.pods.delete("ns", "live")
    assert adds == ["pre", "live"]
    assert len(updates) == 1 and updates[0][0] != updates[0][1]
    assert deletes == ["live"]
    assert inf.store.get_by_key("ns/pre") is not None
    assert inf.store.get_by_key("ns/live") is None


def test_informer_resync_heals_divergence():
    # simulate a cache that missed ADDED, MODIFIED and DELETED events while
    # a watch stream was down, then resync() — the store reconverges and
    # synthetic events fire
    c = FakeCluster()
    c.pods.create("ns", {"metadata": {"name": "stays", "namespace": "ns"}})
    c.pods.create("ns", {"metadata": {"name": "goes", "namespace": "ns"}})
    inf = Informer(c.pods)
    inf.start()
    inf.stop()  # detach the watch: changes below are invisible to it
    c.pods.remove_listener(inf._on_watch_event)

    c.pods.delete("ns", "goes")                       # missed DELETED
    c.pods.create("ns", {"metadata": {"name": "new", "namespace": "ns"}})
    c.pods.set_status("ns", "stays", {"phase": "Running"})  # missed MODIFIED

    adds, updates, deletes = [], [], []
    inf.add_event_handler(
        on_add=lambda o: adds.append(o["metadata"]["name"]),
        on_update=lambda old, new: updates.append(new["metadata"]["name"]),
        on_delete=lambda o: deletes.append(o["metadata"]["name"]),
    )
    inf.resync()
    assert adds == ["new"]
    assert "stays" in updates  # changed rv fires update (unchanged would too)
    assert deletes == ["goes"]
    assert inf.store.get_by_key("ns/goes") is None
    assert inf.store.get_by_key("ns/new") is not None


def test_informer_periodic_resync_thread():
    c = FakeCluster()
    inf = Informer(c.pods, resync_period=0.05)
    adds = []
    inf.add_event_handler(on_add=lambda o: adds.append(o["metadata"]["name"]))
    inf.start()
    try:
        c.pods.remove_listener(inf._on_watch_event)  # force watch blindness
        c.pods.create("ns", {"metadata": {"name": "healed", "namespace": "ns"}})
        deadline = time.monotonic() + 5
        while "healed" not in adds and time.monotonic() < deadline:
            time.sleep(0.01)
        assert "healed" in adds  # periodic resync found it without a watch
    finally:
        inf.stop()


def test_informer_resync_no_deadlock_with_concurrent_writers():
    # regression: resync used to take its apply lock and then the cluster
    # lock (via source.list()), while the fake store notifies watch
    # listeners holding its RLock and then takes the apply lock — a
    # classic lock-order inversion that froze the operator
    c = FakeCluster()
    inf = Informer(c.pods, resync_period=0.001)
    inf.add_event_handler(on_add=lambda o: None)
    inf.start()
    try:
        done = threading.Event()

        def writer():
            for i in range(50):
                c.pods.create("ns", {"metadata": {"name": f"p{i}",
                                                  "namespace": "ns"}})
            done.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        assert done.wait(20), "writer deadlocked against resync"
        t.join(timeout=5)
        deadline = time.monotonic() + 10
        while len(inf.store.list()) < 50 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(inf.store.list()) == 50
    finally:
        inf.stop()


def test_parse_duration():
    from pytorch_operator_tpu.cmd.operator import parse_duration

    assert parse_duration("12h") == 43200.0
    assert parse_duration("30s") == 30.0
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration("45") == 45.0
    assert parse_duration("500ms") == 0.5
    assert parse_duration("") == 0.0
    with pytest.raises(ValueError):
        parse_duration("bogus")
    with pytest.raises(ValueError):
        parse_duration("500msgarbage")


# --------------------------------------------------------------------------
# adoption / orphaning (jobcontroller/pod.go:165-241)
# --------------------------------------------------------------------------


def _controller():
    cluster = FakeCluster()
    ctl = PyTorchController(
        cluster, config=JobControllerConfig(), recorder=FakeRecorder(), registry=Registry()
    )
    return ctl, cluster


def test_orphan_adoption():
    ctl, cluster = _controller()
    job = new_job(workers=1)
    job_dict = job.to_dict()
    labels = ctl.gen_labels(job.metadata.name)
    labels[constants.LABEL_REPLICA_TYPE] = "worker"
    labels[constants.LABEL_REPLICA_INDEX] = "0"
    cluster.pods.create(
        TEST_NAMESPACE,
        {"metadata": {"name": "orphan", "namespace": TEST_NAMESPACE, "labels": labels}},
    )
    pods = ctl.get_pods_for_job(job_dict)
    assert len(pods) == 1
    refs = pods[0]["metadata"]["ownerReferences"]
    assert refs[0]["uid"] == job.metadata.uid and refs[0]["controller"]
    # persisted in the cluster too
    stored = cluster.pods.get(TEST_NAMESPACE, "orphan")
    assert stored["metadata"]["ownerReferences"][0]["uid"] == job.metadata.uid


def test_foreign_controlled_pod_not_claimed():
    ctl, cluster = _controller()
    job = new_job(workers=1)
    labels = ctl.gen_labels(job.metadata.name)
    cluster.pods.create(
        TEST_NAMESPACE,
        {
            "metadata": {
                "name": "foreign",
                "namespace": TEST_NAMESPACE,
                "labels": labels,
                "ownerReferences": [{"uid": "other-uid", "controller": True}],
            }
        },
    )
    assert ctl.get_pods_for_job(job.to_dict()) == []


def test_label_mismatch_not_listed():
    """An owned pod whose labels no longer match the job selector is out of
    scope: the selector-list never returns it (reference pod.go:165-178)."""
    ctl, cluster = _controller()
    job = new_job(workers=1)
    cluster.pods.create(
        TEST_NAMESPACE,
        {
            "metadata": {
                "name": "mismatched",
                "namespace": TEST_NAMESPACE,
                "labels": {"unrelated": "yes"},
                "ownerReferences": [
                    {"uid": job.metadata.uid, "controller": True, "kind": constants.KIND}
                ],
            }
        },
    )
    assert ctl.get_pods_for_job(job.to_dict()) == []


def test_informer_callbacks_enqueue_owner():
    """add_pod resolves the controller ref through the job cache, observes
    the expectation and enqueues (pod.go:20-67)."""
    ctl, cluster = _controller()
    job = new_job(workers=1)
    ctl.job_informer.store.add(job.to_dict())
    key = job.key
    ctl.expectations.expect_creations(expectation_pods_key(key, "worker"), 1)
    pod = {
        "metadata": {
            "name": "p",
            "namespace": TEST_NAMESPACE,
            "labels": {constants.LABEL_REPLICA_TYPE: "worker"},
            "ownerReferences": [
                {
                    "kind": constants.KIND,
                    "name": job.metadata.name,
                    "uid": job.metadata.uid,
                    "controller": True,
                }
            ],
        }
    }
    ctl.add_pod(pod)
    assert ctl.expectations.satisfied(expectation_pods_key(key, "worker"))
    item, _ = ctl.work_queue.get(timeout=0.1)
    assert item == key


def test_workqueue_is_dirty_tracks_pending_state():
    q = WorkQueue()
    assert not q.is_dirty("a")
    q.add("a")
    assert q.is_dirty("a")
    q.get(timeout=0.1)
    assert not q.is_dirty("a")  # processing, not dirty
    q.add("a")  # re-added during processing
    assert q.is_dirty("a")


def test_workqueue_forget_cancels_pending_retry():
    """forget() after a successful sync must cancel the scheduled backoff
    retry — otherwise the retry fires later and double-processes a key
    that already converged."""
    q = WorkQueue()
    q.add_rate_limited("a")  # ~5ms backoff
    q.forget("a")
    got, _ = q.get(timeout=0.2)
    assert got is None


def test_workqueue_plain_add_after_survives_forget():
    """Deadline/TTL timers ride add_after and must NOT be cancelled by
    forget() (every successful sync forgets the key; the
    ActiveDeadlineSeconds wake-up still has to fire)."""
    q = WorkQueue()
    q.add_after("a", 0.05)
    q.forget("a")
    got, _ = q.get(timeout=2.0)
    assert got == "a"


def test_workqueue_retry_deduped_against_queued_key():
    """A rate-limited requeue plus a live watch event used to
    double-process one key after the first done(): the retry for an
    already-dirty key is dropped (the imminent processing supersedes
    it)."""
    q = WorkQueue()
    q.add("a")
    item, _ = q.get(timeout=0.5)
    assert item == "a"
    q.add("a")  # live watch event while processing: dirty again
    q.add_rate_limited("a")  # failed sync schedules a retry -> deduped
    q.done("a")
    item, _ = q.get(timeout=0.5)
    assert item == "a"  # the single re-process
    q.done("a")
    got, _ = q.get(timeout=0.2)
    assert got is None, "retry ghost double-processed the key"


def test_workqueue_newer_retry_supersedes_pending():
    q = WorkQueue()
    q.add_rate_limited("a")  # 5ms
    q.add_rate_limited("a")  # 10ms — replaces the pending entry
    item, _ = q.get(timeout=2.0)
    assert item == "a"
    q.done("a")
    got, _ = q.get(timeout=0.3)
    assert got is None, "superseded retry entry still fired"


# --------------------------------------------------------------------------
# informer burst coalescing
# --------------------------------------------------------------------------


class _ListSource:
    """Minimal informer source: scripted LIST + manual event emission."""

    def __init__(self, objs=()):
        self.objs = list(objs)
        self.listeners = []

    def add_listener(self, fn):
        self.listeners.append(fn)

    def remove_listener(self, fn):
        self.listeners.remove(fn)

    def list(self, namespace=None):
        return list(self.objs)

    def emit(self, etype, obj):
        for fn in list(self.listeners):
            fn(etype, obj)


def _obj(name, rv, spec=None):
    return {"metadata": {"namespace": "ns", "name": name,
                         "resourceVersion": str(rv)},
            "spec": spec or {}}


def test_informer_coalesces_modified_while_key_dirty():
    dirty = set()
    src = _ListSource()
    inf = Informer(src, coalesce=lambda key, old, new: key in dirty)
    updates = []
    inf.add_event_handler(on_update=lambda old, new: updates.append(
        new["metadata"]["resourceVersion"]))
    inf.start()

    src.emit("ADDED", _obj("a", 1))
    src.emit("MODIFIED", _obj("a", 2))  # not dirty: dispatched
    dirty.add("ns/a")
    src.emit("MODIFIED", _obj("a", 3))  # dirty: store updated, no dispatch
    src.emit("MODIFIED", _obj("a", 4))
    assert updates == ["2"]
    assert inf.store.get_by_key("ns/a")["metadata"]["resourceVersion"] == "4"
    dirty.clear()
    src.emit("MODIFIED", _obj("a", 5))  # clean again: dispatched
    assert updates == ["2", "5"]


def test_informer_resync_dispatches_each_key_once_per_pass():
    src = _ListSource([_obj("a", 1), _obj("b", 1)])
    inf = Informer(src)
    counts = {}
    inf.add_event_handler(on_update=lambda old, new: counts.__setitem__(
        new["metadata"]["name"], counts.get(new["metadata"]["name"], 0) + 1))
    inf.start()
    inf.resync()
    assert counts == {"a": 1, "b": 1}
    inf.resync()
    assert counts == {"a": 2, "b": 2}


def test_informer_resync_respects_coalesce():
    dirty = {"ns/a"}
    src = _ListSource([_obj("a", 1), _obj("b", 1)])
    inf = Informer(src, coalesce=lambda key, old, new: key in dirty)
    updates = []
    inf.add_event_handler(on_update=lambda old, new: updates.append(
        new["metadata"]["name"]))
    inf.start()
    src.objs = [_obj("a", 2), _obj("b", 2)]
    inf.resync()
    assert updates == ["b"]  # dirty key coalesced, store still healed
    assert inf.store.get_by_key("ns/a")["metadata"]["resourceVersion"] == "2"


def test_pod_control_create_many_overlaps_requests(monkeypatch):
    """The fan-out batch must issue creates concurrently: a barrier only
    opens when all four creates are in flight at once, so a serialized
    implementation deadlocks (and fails the barrier timeout)."""
    monkeypatch.setenv("PYTORCH_OPERATOR_CREATE_FANOUT", "8")
    from pytorch_operator_tpu.k8s.objects import OwnerReference
    from pytorch_operator_tpu.runtime.controls import PodControl

    barrier = threading.Barrier(4, timeout=5)

    class SlowPods:
        def create(self, namespace, pod):
            barrier.wait()
            return pod

    control = PodControl(SlowPods(), FakeRecorder())
    ref = OwnerReference(api_version="v1", kind="PyTorchJob",
                         name="j", uid="u")
    pods = [{"metadata": {"name": f"p-{i}"}} for i in range(4)]
    results = control.create_many("ns", pods, {}, ref)
    assert [err for _, err in results] == [None] * 4
    assert [created["metadata"]["name"]
            for created, _ in results] == ["p-0", "p-1", "p-2", "p-3"]


def test_pod_control_create_many_sequential_width_one(monkeypatch):
    """Width 1 restores the sequential path (the bench's --io sequential
    pin) and still reports per-object errors without aborting the
    batch."""
    monkeypatch.setenv("PYTORCH_OPERATOR_CREATE_FANOUT", "1")
    from pytorch_operator_tpu.k8s.errors import ApiError
    from pytorch_operator_tpu.k8s.objects import OwnerReference
    from pytorch_operator_tpu.runtime.controls import PodControl

    calls = []

    class Pods:
        def create(self, namespace, pod):
            calls.append(pod["metadata"]["name"])
            if pod["metadata"]["name"] == "p-1":
                raise ApiError("boom")
            return pod

    control = PodControl(Pods(), FakeRecorder())
    ref = OwnerReference(api_version="v1", kind="PyTorchJob",
                         name="j", uid="u")
    pods = [{"metadata": {"name": f"p-{i}"}} for i in range(3)]
    results = control.create_many("ns", pods, {}, ref)
    assert calls == ["p-0", "p-1", "p-2"]
    assert results[0][1] is None and results[2][1] is None
    assert isinstance(results[1][1], ApiError)


def test_submit_creates_rolls_back_all_expectations_on_batch_failure():
    """If the batch submission itself dies (not a per-item error), every
    raised expectation must be rolled back — otherwise the job parks
    unsynced until the 5-minute expectations TTL."""
    from pytorch_operator_tpu.runtime.controls import (
        submit_creates_with_expectations,
    )

    e = ControllerExpectations()
    key = expectation_pods_key("ns/job", "worker")

    def exploding_create_many(namespace, objs, controller_obj, ref):
        raise RuntimeError("pool torn down mid-batch")

    with pytest.raises(RuntimeError):
        submit_creates_with_expectations(
            e, key, exploding_create_many, "ns",
            [{"metadata": {"name": f"p-{i}"}} for i in range(3)], {}, None)
    assert e.satisfied(key)


def test_fanout_pool_keyed_by_configured_width_not_batch_size(monkeypatch):
    """Two concurrent batches of different sizes must share the one
    pool for the configured width — per-batch-size pools would tear
    each other down mid-submit."""
    monkeypatch.setenv("PYTORCH_OPERATOR_CREATE_FANOUT", "8")
    from pytorch_operator_tpu.runtime import controls

    seen_pools = set()
    orig = controls._fanout_pool_for

    def spy(width):
        pool = orig(width)
        seen_pools.add(id(pool))
        return pool

    monkeypatch.setattr(controls, "_fanout_pool_for", spy)
    for n in (7, 2, 5):
        results = controls.run_create_batch(
            lambda obj: obj, [{"i": i} for i in range(n)])
        assert len(results) == n and all(e is None for _, e in results)
    assert len(seen_pools) == 1
