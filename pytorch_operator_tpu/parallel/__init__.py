"""TPU parallelism layer: device meshes, sharding rules, train steps.

The reference operator is topology-agnostic above the rank/world-size
level — it only injects MASTER_ADDR/RANK/WORLD_SIZE for c10d rendezvous
(reference: pkg/controller.v1/pytorch/pod.go:234-281).  The TPU-native
data plane expresses parallelism directly as a `jax.sharding.Mesh` with
named axes (dp/fsdp/tp/sp); XLA GSPMD inserts the collectives that the
reference delegates to gloo/nccl/mpi (reference:
examples/mnist/mnist.py:99-138).
"""

from pytorch_operator_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_EP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_SP,
    AXIS_TP,
    batch_spec,
    data_axes,
    factor_devices,
    make_mesh,
    make_named_mesh,
    make_sp_mesh,
)
from pytorch_operator_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_value_and_grad,
)
from pytorch_operator_tpu.parallel.ring_attention import ring_attention
from pytorch_operator_tpu.parallel.ulysses import ulysses_attention
from pytorch_operator_tpu.parallel.train import (
    cross_entropy_loss,
    make_pp_train_step,
    make_sp_train_step,
    make_train_step,
    reshard_state,
    restore_on_mesh,
    sharded_init,
    state_shardings,
)

__all__ = [
    "AXIS_DP",
    "AXIS_EP",
    "AXIS_FSDP",
    "AXIS_PP",
    "AXIS_SP",
    "AXIS_TP",
    "batch_spec",
    "data_axes",
    "factor_devices",
    "make_mesh",
    "make_named_mesh",
    "make_sp_mesh",
    "pipeline_apply",
    "pipeline_value_and_grad",
    "ring_attention",
    "ulysses_attention",
    "cross_entropy_loss",
    "make_pp_train_step",
    "make_sp_train_step",
    "make_train_step",
    "reshard_state",
    "restore_on_mesh",
    "sharded_init",
    "state_shardings",
]
