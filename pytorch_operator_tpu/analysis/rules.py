"""AST lint rules for concurrency & determinism invariants.

Each rule is a pure function over one parsed module: it yields raw
findings ``(line, end_line, message)``; the engine scopes rules to
paths (``config.py``), applies pragma waivers, and decides exit codes.

The rules deliberately resolve names through the module's own imports
(``import time as t`` still flags ``t.monotonic()``), and deliberately
do NOT flag *references* — ``clock: Callable = time.monotonic`` as a
default argument is the injection idiom these rules exist to protect,
only the *call* ``time.monotonic()`` bypasses it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

RawFinding = Tuple[int, int, str]

# -- shared name resolution -------------------------------------------------

#: module roots whose attribute calls the rules care about
_TRACKED_MODULES = {
    "time", "datetime", "threading", "random", "subprocess", "socket",
    "urllib", "urllib.request", "requests",
}


def _import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Map local names to canonical dotted paths for tracked imports.

    ``import time as t``          -> {"t": "time"}
    ``from time import monotonic``-> {"monotonic": "time.monotonic"}
    ``from datetime import datetime as dt`` -> {"dt": "datetime.datetime"}
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                root = a.name.split(".")[0]
                if root in _TRACKED_MODULES or a.name in _TRACKED_MODULES:
                    aliases[a.asname or root] = (
                        a.name if a.asname else root)
        elif isinstance(node, ast.ImportFrom) and node.module:
            root = node.module.split(".")[0]
            if root in _TRACKED_MODULES:
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _resolve(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted path of a Name/Attribute expression, or None."""
    if isinstance(node, ast.Name):
        return aliases.get(node.id)
    if isinstance(node, ast.Attribute):
        base = _resolve(node.value, aliases)
        return f"{base}.{node.attr}" if base else None
    return None


def _span(node: ast.AST) -> Tuple[int, int]:
    return node.lineno, getattr(node, "end_lineno", node.lineno)


# -- rule: wall-clock -------------------------------------------------------

#: calls that read or act on the real clock unconditionally
_WALL_CLOCK_CALLS = {
    "time.time", "time.monotonic", "time.sleep", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
    "threading.Timer",
}

#: calls that default to "now" when the time argument is omitted:
#: canonical name -> index of the optional time argument
_WALL_CLOCK_DEFAULT_NOW = {
    "time.gmtime": 0,
    "time.localtime": 0,
    "time.strftime": 1,
    "time.ctime": 0,
}


def rule_wall_clock(tree: ast.AST) -> Iterator[RawFinding]:
    """Raw wall-clock calls in a clock-injectable module.

    One ``time.monotonic()`` on a path the simulator drives silently
    breaks the same-seed determinism guarantee: the fingerprint then
    depends on host scheduling, not the virtual timeline.  Take the
    injected clock (``clock=`` / ``VirtualClock.timer``) or waive with
    ``# lint: wall-clock-ok <reason>``.
    """
    aliases = _import_aliases(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = _resolve(node.func, aliases)
        if target is None:
            continue
        if target in _WALL_CLOCK_CALLS:
            lo, hi = _span(node)
            yield lo, hi, (
                f"wall-clock call {target}() in a clock-injectable "
                f"module — thread the injected clock through instead")
        elif target in _WALL_CLOCK_DEFAULT_NOW:
            # only a wall-clock read when the time argument is omitted
            idx = _WALL_CLOCK_DEFAULT_NOW[target]
            has_time_arg = (
                len(node.args) > idx
                or any(isinstance(a, ast.Starred) for a in node.args)
                or any(kw.arg is None for kw in node.keywords))
            if not has_time_arg:
                lo, hi = _span(node)
                yield lo, hi, (
                    f"{target}() without a time argument reads the real "
                    f"clock — pass an injected timestamp")


# -- rule: builtin-hash -----------------------------------------------------

def rule_builtin_hash(tree: ast.AST) -> Iterator[RawFinding]:
    """Builtin ``hash()`` anywhere in the operator package.

    ``hash()`` of a str/bytes is salted by PYTHONHASHSEED: using it for
    shard placement, cache keys or any persisted/compared value means a
    restart reshards the fleet.  Use ``hashlib.blake2b`` (see
    ``runtime.sharding.shard_of``) or waive with
    ``# lint: builtin-hash-ok <reason>``.
    """
    shadowed = {
        n.asname or n.name.split(".")[0]
        for node in ast.walk(tree)
        if isinstance(node, (ast.Import, ast.ImportFrom))
        for n in node.names
    }
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "hash"
                and "hash" not in shadowed):
            lo, hi = _span(node)
            yield lo, hi, (
                "builtin hash() is PYTHONHASHSEED-salted — restart "
                "reshards/rekeys; use hashlib.blake2b like "
                "runtime.sharding.shard_of")


# -- rule: unseeded-random --------------------------------------------------

#: module-level random functions drawing from the shared, unseeded RNG
_GLOBAL_RANDOM_FNS = {
    "random", "randint", "uniform", "choice", "choices", "shuffle",
    "sample", "randrange", "gauss", "betavariate", "expovariate",
    "normalvariate", "lognormvariate", "vonmisesvariate", "paretovariate",
    "weibullvariate", "triangular", "getrandbits", "randbytes", "seed",
}


def rule_unseeded_random(tree: ast.AST) -> Iterator[RawFinding]:
    """Module-level ``random.*`` calls (the shared, unseeded RNG).

    Every stochastic knob in this repo (fleet latency profiles, fault
    plans, churn arrival) draws from a ``random.Random(seed)`` instance
    so the same seed replays the same scenario; the module-level
    functions share one process-global generator that any import can
    perturb.  Seeded instances are fine; waive with
    ``# lint: unseeded-random-ok <reason>``.
    """
    aliases = _import_aliases(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        target = _resolve(node.func, aliases)
        if (target and target.startswith("random.")
                and target.split(".", 1)[1] in _GLOBAL_RANDOM_FNS):
            lo, hi = _span(node)
            yield lo, hi, (
                f"{target}() draws from the process-global unseeded RNG "
                f"— use a random.Random(seed) instance")


# -- rule: blocking-in-lock -------------------------------------------------

#: canonical call targets that block on I/O or sleep
_BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "socket.create_connection",
    "urllib.request.urlopen",
    "requests.get", "requests.post", "requests.put", "requests.delete",
    "requests.patch", "requests.head", "requests.request",
}

#: bare attribute/function names that block regardless of receiver
#: (``self._sleep(...)`` is an injected sleep — still a real block)
_BLOCKING_ATTRS = {"sleep"}


def _expr_text(node: ast.AST) -> str:
    """Best-effort dotted text of a Name/Attribute for lock matching."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_expr_text(node.value)}.{node.attr}"
    if isinstance(node, ast.Call):
        return _expr_text(node.func)
    return ""


def _looks_like_lock(expr: ast.AST) -> bool:
    text = _expr_text(expr)
    last = text.rsplit(".", 1)[-1].lower()
    return ("lock" in last or "mutex" in last or last in ("mu", "cv")) \
        and "unlock" not in last


def _iter_body_calls(body: Sequence[ast.stmt]) -> Iterator[ast.Call]:
    """Calls lexically inside ``body``, not descending into nested
    function/class definitions (those run later, outside the lock)."""
    stack: List[ast.AST] = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def rule_blocking_in_lock(tree: ast.AST) -> Iterator[RawFinding]:
    """Blocking calls lexically inside a ``with <lock>:`` body.

    A sleep, subprocess or network round-trip while holding a lock
    convoys every thread that needs it — the token bucket's "sleep
    outside the lock: no convoy" comment is the invariant this rule
    enforces mechanically.  Waive with
    ``# lint: blocking-in-lock-ok <reason>``.
    """
    aliases = _import_aliases(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.With):
            continue
        lock_items = [i.context_expr for i in node.items
                      if _looks_like_lock(i.context_expr)]
        if not lock_items:
            continue
        lock_texts = {_expr_text(i) for i in lock_items}
        for call in _iter_body_calls(node.body):
            target = _resolve(call.func, aliases)
            blocked = None
            if target in _BLOCKING_CALLS:
                blocked = target
            elif isinstance(call.func, ast.Attribute):
                recv = _expr_text(call.func.value)
                if call.func.attr in _BLOCKING_ATTRS:
                    blocked = f"{recv}.{call.func.attr}" if recv \
                        else call.func.attr
                elif (call.func.attr in ("join", "wait")
                      and recv not in lock_texts
                      and any(h in recv.lower()
                              for h in ("thread", "timer", "pool",
                                        "proc", "future", "event",
                                        "stop"))):
                    # t.join() / stop_event.wait() while holding a lock;
                    # cond-var waits on the held lock itself are the
                    # legitimate release-and-sleep idiom and excluded
                    blocked = f"{recv}.{call.func.attr}"
            elif (isinstance(call.func, ast.Name)
                  and call.func.id in _BLOCKING_ATTRS
                  and call.func.id not in aliases):
                blocked = call.func.id
            if blocked:
                lo, hi = _span(call)
                yield lo, hi, (
                    f"blocking call {blocked}() lexically inside "
                    f"`with {sorted(lock_texts)[0]}:` — move it outside "
                    f"the critical section")


# -- rule: swallowed-except -------------------------------------------------

_BROAD_EXC_NAMES = {"Exception", "BaseException"}


def _handler_is_broad(handler: ast.ExceptHandler) -> Optional[str]:
    if handler.type is None:
        return "bare except"
    t = handler.type
    if isinstance(t, ast.Name) and t.id in _BROAD_EXC_NAMES:
        return f"except {t.id}"
    if isinstance(t, ast.Tuple):
        for el in t.elts:
            if isinstance(el, ast.Name) and el.id in _BROAD_EXC_NAMES:
                return f"except (...{el.id}...)"
    return None


def _body_is_silent(body: Sequence[ast.stmt]) -> bool:
    """True when the handler body neither re-raises, logs, counts, nor
    mutates any state — only pass/continue/break/docstring/bare return."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Return) and stmt.value is None:
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)):
            continue  # docstring / ellipsis
        return False
    return True


def rule_swallowed_except(tree: ast.AST) -> Iterator[RawFinding]:
    """Broad/bare ``except`` that silently swallows on a reconcile path.

    A handler that catches Exception and does literally nothing turns a
    failed sync into a wedged job: no requeue, no event, no log line to
    find it by.  Handle it (log, count, re-raise) or waive with
    ``# lint: swallowed-except-ok <reason>`` — the recorder's
    "event emission must never break reconciliation" is the canonical
    legitimate waiver.
    """
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            broad = _handler_is_broad(handler)
            if broad and _body_is_silent(handler.body):
                lo = handler.lineno
                hi = getattr(handler, "end_lineno", lo)
                yield lo, hi, (
                    f"{broad} silently swallows errors on a reconcile "
                    f"path — log, count, or re-raise")


# -- rule: cache-mutation ---------------------------------------------------

#: informer event handlers and watch callbacks — their object parameters
#: are shared cache references, never owned
_HANDLER_NAME_RE = re.compile(
    r"^_?(?:on_)?(?:add|update|delete)_(?:job|pod|service|node)s?$")

#: functions whose return value is a cached object handed out by
#: reference (controller cache accessors)
_CACHE_ACCESSOR_FNS = {"_get_job_from_cache", "_resolve_controller_ref"}

#: methods that read *into* a tainted container without transferring
#: ownership — the result aliases the cached tree
_ALIASING_METHODS = {"get", "items", "values", "keys"}

#: in-place mutators on dicts/lists — writing through any of these on a
#: cached object corrupts every other consumer of the same reference
_MUTATOR_METHODS = {
    "update", "setdefault", "pop", "popitem", "clear",
    "append", "extend", "insert", "remove", "sort", "reverse",
}


def _is_cache_source_call(call: ast.Call) -> bool:
    """Calls that hand out a cached object by reference."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr == "get_by_key":
            return True
        if fn.attr == "list" and "store" in _expr_text(fn.value).lower():
            return True
        if fn.attr in _CACHE_ACCESSOR_FNS:
            return True
    elif isinstance(fn, ast.Name) and fn.id in _CACHE_ACCESSOR_FNS:
        return True
    return False


def _is_tainted(expr: ast.AST, tainted: Set[str]) -> bool:
    """Does ``expr`` evaluate to (part of) a cache-owned object?

    Attribute/Subscript access and the aliasing dict methods propagate
    taint; every other call is treated as an ownership transfer — that
    is exactly the laundering vocabulary (``copy.deepcopy``,
    ``_copy_obj``, a serde parse, ``analysis.owned``) plus ordinary
    value-producing calls, which cannot return the cached tree itself.
    """
    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, (ast.Attribute, ast.Subscript, ast.Starred)):
        return _is_tainted(expr.value, tainted)
    if isinstance(expr, ast.Call):
        if _is_cache_source_call(expr):
            return True
        if (isinstance(expr.func, ast.Attribute)
                and expr.func.attr in _ALIASING_METHODS):
            return _is_tainted(expr.func.value, tainted)
        return False
    if isinstance(expr, ast.BoolOp):
        return any(_is_tainted(v, tainted) for v in expr.values)
    if isinstance(expr, ast.IfExp):
        return (_is_tainted(expr.body, tainted)
                or _is_tainted(expr.orelse, tainted))
    if isinstance(expr, ast.NamedExpr):
        return _is_tainted(expr.value, tainted)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_is_tainted(v, tainted) for v in expr.elts)
    return False


def _bind(target: ast.AST, is_tainted: bool, tainted: Set[str]) -> None:
    """Record a (re)binding: tainted values taint the name, owned
    values clear it."""
    if isinstance(target, ast.Name):
        if is_tainted:
            tainted.add(target.id)
        else:
            tainted.discard(target.id)
    elif isinstance(target, (ast.Tuple, ast.List)):
        for el in target.elts:
            _bind(el, is_tainted, tainted)
    elif isinstance(target, ast.Starred):
        _bind(target.value, is_tainted, tainted)


def _expr_calls(expr: ast.AST) -> Iterator[ast.Call]:
    """Call nodes lexically inside ``expr`` (not inside lambdas)."""
    stack: List[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _mutator_sinks(expr: ast.AST, tainted: Set[str],
                   out: List[RawFinding]) -> None:
    for call in _expr_calls(expr):
        fn = call.func
        if (isinstance(fn, ast.Attribute)
                and fn.attr in _MUTATOR_METHODS
                and _is_tainted(fn.value, tainted)):
            lo, hi = _span(call)
            out.append((lo, hi, (
                f".{fn.attr}() mutates a cache-owned object in place — "
                f"take analysis.owned()/copy.deepcopy first")))


def _write_sink(target: ast.AST, tainted: Set[str], stmt: ast.stmt,
                out: List[RawFinding], what: str) -> None:
    if (isinstance(target, (ast.Attribute, ast.Subscript))
            and _is_tainted(target.value, tainted)):
        lo, hi = _span(stmt)
        out.append((lo, hi, (
            f"{what} writes into a cache-owned object — informer/watch "
            f"objects are shared read-only; take analysis.owned()/"
            f"copy.deepcopy before mutating")))


def _scan_stmts(stmts: Sequence[ast.stmt], tainted: Set[str],
                out: List[RawFinding]) -> None:
    """Ordered, single-pass taint walk — no CFG, no fixpoint.  Branch
    bodies are walked in source order against one shared taint set: a
    rebinding anywhere clears the name for everything after, which
    trades a few theoretical false negatives for zero loop-analysis
    cost and very predictable findings."""
    for stmt in stmts:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue  # runs in its own scope; checked independently
        if isinstance(stmt, ast.Assign):
            _mutator_sinks(stmt.value, tainted, out)
            value_tainted = _is_tainted(stmt.value, tainted)
            for tgt in stmt.targets:
                _write_sink(tgt, tainted, stmt, out, "assignment")
                _bind(tgt, value_tainted, tainted)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                _mutator_sinks(stmt.value, tainted, out)
                _write_sink(stmt.target, tainted, stmt, out, "assignment")
                _bind(stmt.target, _is_tainted(stmt.value, tainted),
                      tainted)
        elif isinstance(stmt, ast.AugAssign):
            _mutator_sinks(stmt.value, tainted, out)
            _write_sink(stmt.target, tainted, stmt, out,
                        "augmented assignment")
        elif isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                _write_sink(tgt, tainted, stmt, out, "del")
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            _mutator_sinks(stmt.iter, tainted, out)
            _bind(stmt.target, _is_tainted(stmt.iter, tainted), tainted)
            _scan_stmts(stmt.body, tainted, out)
            _scan_stmts(stmt.orelse, tainted, out)
        elif isinstance(stmt, (ast.If, ast.While)):
            _mutator_sinks(stmt.test, tainted, out)
            _scan_stmts(stmt.body, tainted, out)
            _scan_stmts(stmt.orelse, tainted, out)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                _mutator_sinks(item.context_expr, tainted, out)
                if item.optional_vars is not None:
                    _bind(item.optional_vars,
                          _is_tainted(item.context_expr, tainted), tainted)
            _scan_stmts(stmt.body, tainted, out)
        elif isinstance(stmt, ast.Try):
            _scan_stmts(stmt.body, tainted, out)
            for handler in stmt.handlers:
                _scan_stmts(handler.body, tainted, out)
            _scan_stmts(stmt.orelse, tainted, out)
            _scan_stmts(stmt.finalbody, tainted, out)
        else:
            for child in ast.iter_child_nodes(stmt):
                _mutator_sinks(child, tainted, out)


def rule_cache_mutation(tree: ast.AST) -> Iterator[RawFinding]:
    """In-place writes to objects handed out by a shared cache.

    ``Store.get_by_key``/``Store.list`` return the cached dicts
    directly, ``FakeCluster._notify`` shares one copy per watch event
    across all listeners, and informer event handlers receive those
    same references.  A single ``obj["status"] = ...`` therefore
    corrupts every sibling consumer and the sim's determinism
    fingerprint.  Take an explicit ownership transfer
    (``analysis.owned()``, ``copy.deepcopy``, ``k8s.fake._copy_obj``,
    a serde parse) before mutating, or waive with
    ``# lint: cache-mutation-ok <reason>``.
    """
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        tainted: Set[str] = set()
        if (_HANDLER_NAME_RE.match(node.name)
                or node.name.endswith("_event")):
            params = list(node.args.posonlyargs) + list(node.args.args)
            for i, arg in enumerate(params):
                if i == 0 and arg.arg in ("self", "cls"):
                    continue
                tainted.add(arg.arg)
            for arg in node.args.kwonlyargs:
                tainted.add(arg.arg)
        out: List[RawFinding] = []
        _scan_stmts(node.body, tainted, out)
        yield from out


# -- registry ---------------------------------------------------------------

#: rule key -> (rule fn, scope attribute on AnalysisConfig or None for
#: tree-wide).  Keys double as the pragma vocabulary:
#: ``# lint: <key>-ok <reason>``.
RULES = {
    "wall-clock": (rule_wall_clock, "is_clock_injectable"),
    "builtin-hash": (rule_builtin_hash, None),
    "unseeded-random": (rule_unseeded_random, None),
    "blocking-in-lock": (rule_blocking_in_lock, None),
    "swallowed-except": (rule_swallowed_except, "is_reconcile_path"),
    "cache-mutation": (rule_cache_mutation, "is_cache_consumer"),
}
