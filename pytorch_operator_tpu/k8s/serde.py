"""Dataclass <-> Kubernetes-style JSON object conversion.

The reference operator relies on k8s.io/apimachinery generated code
(``zz_generated.deepcopy.go``, swagger models) to move between typed Go
structs and the JSON wire format.  This module is the first-party
equivalent: a small reflection layer that maps ``snake_case`` dataclass
fields to ``camelCase`` JSON keys, recursing through ``Optional``,
``List``, ``Dict`` and nested dataclasses.

Conventions (matching Kubernetes marshalling):
  * ``None`` values and empty containers are omitted on output.
  * Unknown keys on input are ignored (forward compatibility).
  * A field may override its wire name via
    ``field(metadata={"k8s": "wireName"})``.
"""

from __future__ import annotations

import copy
import dataclasses
import functools
import typing
from typing import Any, Optional, Type, TypeVar, Union, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}

# Per-class (field name, wire name, type hint, hint-is-optional) plans:
# reflection (dataclasses.fields + get_type_hints + metadata lookups)
# per call made serde the hottest control-plane path after the store
# itself — the kubemark tier parses/serializes status trees hundreds of
# thousands of times per scenario, and the plans never change.
_PLAN_CACHE: dict[type, list] = {}


def _plan(cls: type) -> list:
    plan = _PLAN_CACHE.get(cls)
    if plan is None:
        hints = _hints(cls)
        plan = [(f.name, _wire_name(f), hints[f.name],
                 _is_optional(hints[f.name]))
                for f in dataclasses.fields(cls)]
        _PLAN_CACHE[cls] = plan
    return plan


@functools.lru_cache(maxsize=None)
def _type_info(tp: Any):
    """(kind, unwrapped type, element hint) for one field hint —
    computed once per distinct hint (typing objects hash)."""
    tp = _unwrap_optional(tp)
    if dataclasses.is_dataclass(tp) and isinstance(tp, type):
        return ("dataclass", tp, None)
    origin = get_origin(tp)
    if origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        return ("list", tp, elem)
    if origin is dict:
        args = get_args(tp)
        return ("dict", tp, args[1] if len(args) == 2 else Any)
    return ("scalar", tp, None)


def camel_case(snake: str) -> str:
    parts = snake.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _wire_name(f: dataclasses.Field) -> str:
    return f.metadata.get("k8s", camel_case(f.name))


def _hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    return hints


def _unwrap_optional(tp: Any) -> Any:
    if get_origin(tp) is Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _is_optional(tp: Any) -> bool:
    return get_origin(tp) is Union and type(None) in get_args(tp)


def _encode_value(v: Any) -> Any:
    if dataclasses.is_dataclass(v):
        return to_dict(v)
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    return v


def to_dict(obj: Any) -> dict:
    """Serialize a dataclass to a camelCase JSON-ready dict."""
    out: dict[str, Any] = {}
    for name, wire, _hint, _opt in _plan(type(obj)):
        v = getattr(obj, name)
        if v is None:
            continue
        encoded = _encode_value(v)
        # Go-style omitempty: drop empty strings/lists/dicts (and nested
        # dataclasses that serialized to nothing); keep 0 and False.
        if encoded == "" or (isinstance(encoded, (list, dict)) and not encoded):
            continue
        out[wire] = encoded
    return out


def _decode_value(tp: Any, v: Any) -> Any:
    if v is None:
        return None
    kind, tp, elem = _type_info(tp)
    if kind == "dataclass":
        if not isinstance(v, dict):
            return v
        return from_dict(tp, v)
    if kind == "list" and isinstance(v, list):
        return [_decode_value(elem, x) for x in v]
    if kind == "dict" and isinstance(v, dict):
        return {k: _decode_value(elem, x) for k, x in v.items()}
    return v


def from_dict(cls: Type[T], data: Optional[dict]) -> T:
    """Deserialize a camelCase dict into dataclass ``cls``.

    Unknown keys are ignored; missing keys fall back to field defaults.
    """
    if data is None:
        data = {}
    kwargs: dict[str, Any] = {}
    for name, wire, hint, optional in _plan(cls):
        if wire in data:
            value = data[wire]
            if value is None and not optional:
                # Explicit JSON null on a non-Optional field: keep the
                # field default rather than violating the type contract.
                continue
            kwargs[name] = _decode_value(hint, value)
    return cls(**kwargs)


def deep_copy(obj: T) -> T:
    """Equivalent of the generated DeepCopy methods."""
    return copy.deepcopy(obj)
