"""Live shard re-hash + queue-depth autoscaling (ISSUE 12): the fenced
migration sweep that changes --shard-count without a restart (old and
new rings coexist while labels are re-stamped), the exactly-one-queue
fence for jobs PATCHed between rings, degraded-but-200 readiness during
the window, and the AutoscalePolicy the bench harness consumes."""

from __future__ import annotations

import threading
import time

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import JobControllerConfig
from pytorch_operator_tpu.runtime.autoscaler import (
    AutoscalePolicy,
    fleet_loads,
)
from pytorch_operator_tpu.runtime.informer import Informer
from pytorch_operator_tpu.runtime.sharding import (
    read_ring,
    request_reshard,
    ring_epoch_of,
    shard_of,
    sharded_source,
)

from tests.test_sharding import _condition_true, new_job, wait_for


def _controller(cluster, replica_id, shards=2, registry=None):
    from pytorch_operator_tpu.controller import PyTorchController

    cfg = JobControllerConfig(
        shard_count=shards, replica_id=replica_id,
        shard_lease_duration=1.0, shard_renew_interval=0.05)
    return PyTorchController(cluster, config=cfg,
                             registry=registry or Registry())


# ---------------------------------------------------------------------------
# the migration fence, unit level


class TestMigrationFence:
    def test_sweep_requires_synced_admission_cache(self):
        """An unsynced admission cache cannot prove the sweep complete:
        the fence holder must keep the migration window open."""
        ctl = _controller(FakeCluster(), "fence", shards=2)
        assert ctl._run_migration_sweep(2, 3, 1) is False
        ctl.shutdown()

    def test_aborted_sweep_is_resumable_and_idempotent(self):
        """The sweep is bounded (batch cap) and stateless over the
        store: losing the migration Lease mid-stamp costs at most one
        batch — the next holder's pass re-stamps nothing twice and
        reports done only when a full pass found nothing to move."""
        cluster = FakeCluster()
        ctl = _controller(cluster, "fence", shards=2)
        ctl._admission_informer.start()
        jobs = [cluster.jobs.create("default", new_job(f"mig-{j}"))
                for j in range(3)]
        # one job has a pre-existing child that must ride the re-stamp
        cluster.pods.create("default", {
            "metadata": {"name": "mig-0-master-0",
                         "labels": ctl.gen_labels("mig-0")},
            "spec": {}})
        ctl.MIGRATION_SWEEP_BATCH = 1  # force the abort-per-stamp path
        # three aborted passes (one stamp each), then the clean pass
        for expected_done in (False, False, False, True):
            assert ctl._run_migration_sweep(2, 3, 1) is expected_done
        for job in jobs:
            fresh = cluster.jobs.get("default",
                                     job["metadata"]["name"])
            labels = fresh["metadata"]["labels"]
            assert ring_epoch_of(fresh) == 1
            assert labels[constants.LABEL_SHARD] == str(shard_of(
                "default", fresh["metadata"]["uid"], 3))
        pod = cluster.pods.get("default", "mig-0-master-0")
        assert ring_epoch_of(pod) == 1
        # idempotent: nothing left to move, labels unchanged
        before = [cluster.jobs.get("default", j["metadata"]["name"])
                  ["metadata"]["labels"] for j in jobs]
        assert ctl._run_migration_sweep(2, 3, 1) is True
        after = [cluster.jobs.get("default", j["metadata"]["name"])
                 ["metadata"]["labels"] for j in jobs]
        assert before == after
        ctl.shutdown()

    def test_job_patched_between_rings_lands_in_exactly_one_store(self):
        """The informer-level fence: re-stamping a job from the old
        ring to the new one must EVICT it from the old shard's informer
        (synthesized DELETED) and ADD it to the new shard's — one add,
        one delete, no double-enqueue, no orphan."""
        cluster = FakeCluster()
        job = cluster.jobs.create("default", new_job("fenced"))
        uid = job["metadata"]["uid"]
        old_shard = shard_of("default", uid, 2)
        new_shard = shard_of("default", uid, 3)
        old_src = sharded_source(cluster, "pytorchjobs", old_shard, 0)
        new_src = sharded_source(cluster, "pytorchjobs", new_shard, 1)
        old_inf, new_inf = Informer(old_src), Informer(new_src)
        events = {"old": [], "new": []}
        old_inf.add_event_handler(
            on_add=lambda o: events["old"].append("add"),
            on_delete=lambda o: events["old"].append("delete"))
        new_inf.add_event_handler(
            on_add=lambda o: events["new"].append("add"),
            on_delete=lambda o: events["new"].append("delete"))
        old_inf.start()
        new_inf.start()
        # stamp into the OLD ring: visible to the old informer only
        cluster.jobs.patch("default", "fenced", {"metadata": {"labels": {
            constants.LABEL_SHARD: str(old_shard)}}})
        assert old_inf.store.contains("default/fenced")
        assert not new_inf.store.contains("default/fenced")
        # the migration re-stamp: old ring -> new ring in one PATCH
        cluster.jobs.patch("default", "fenced", {"metadata": {"labels": {
            constants.LABEL_SHARD: str(new_shard),
            constants.LABEL_RING_EPOCH: "1"}}})
        assert not old_inf.store.contains("default/fenced")
        assert new_inf.store.contains("default/fenced")
        assert events["old"] == ["add", "delete"]
        assert events["new"] == ["add"]


# ---------------------------------------------------------------------------
# live reshard, end to end


class TestLiveReshard:
    def test_live_4_to_6_reshard_relabels_and_converges(self):
        """The tentpole acceptance: a running fleet changes
        --shard-count 4 -> 6 WITHOUT a restart.  Every job ends with
        exactly ONE new-ring shard label, sits in exactly one shard
        runtime's store, all jobs converge Succeeded, and the migration
        window is visible through the resharding gauge."""
        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster)
        kubelet.start()
        registry = Registry()
        ctl = _controller(cluster, "live", shards=4, registry=registry)
        stop = threading.Event()
        ctl.run(threadiness=2, stop_event=stop)
        window_seen = []
        try:
            assert wait_for(lambda: ctl.owned_shards() == {0, 1, 2, 3})
            for j in range(6):
                cluster.jobs.create("default", new_job(f"rh-{j}"))
            assert wait_for(lambda: all(
                _condition_true(cluster.jobs.get("default", f"rh-{j}"),
                                "Succeeded") for j in range(6)),
                timeout=30)
            assert "pytorch_operator_ring_epoch 0" in registry.expose()

            request_reshard(cluster.resource("leases"), 6)

            def flipped():
                if ctl.resharding_in_progress():
                    window_seen.append(registry.expose())
                mgr = ctl.shard_manager
                return (mgr.ring_epoch == 1 and mgr.shard_count == 6
                        and ctl.owned_shards() == set(range(6)))

            assert wait_for(flipped, timeout=30)
            # a job created AFTER the flip is admitted on the new ring
            cluster.jobs.create("default", new_job("rh-post"))
            names = [f"rh-{j}" for j in range(6)] + ["rh-post"]
            assert wait_for(lambda: all(
                _condition_true(cluster.jobs.get("default", n),
                                "Succeeded") for n in names),
                timeout=30)
            assert read_ring(cluster.resource("leases")) == (6, 1, None)
            for n in names:
                job = cluster.jobs.get("default", n)
                labels = job["metadata"]["labels"]
                assert ring_epoch_of(job) == 1
                assert labels[constants.LABEL_SHARD] == str(shard_of(
                    "default", job["metadata"]["uid"], 6))
                # exactly one runtime store holds the key: no orphan,
                # no double-ownership across the retired and live rings
                holders = [s for s, rt in ctl._shard_runtimes.items()
                           if rt.job_informer.store.contains(
                               f"default/{n}")]
                assert holders == [int(labels[constants.LABEL_SHARD])]
            # children re-stamped onto the new ring with their jobs
            for pod in cluster.pods.list("default"):
                assert ring_epoch_of(pod) == 1
            # the migration window was observable while it was open...
            assert any("pytorch_operator_resharding_in_progress 1"
                       in text for text in window_seen)
            # ...and is closed (epoch advanced) in the final scrape
            text = registry.expose()
            assert "pytorch_operator_resharding_in_progress 0" in text
            assert "pytorch_operator_ring_epoch 1" in text
        finally:
            stop.set()
            ctl.shutdown()
            kubelet.stop()


# ---------------------------------------------------------------------------
# readiness during the window (satellite: degraded, not unready)


class _FakeSharded:
    """Just enough controller surface for make_readyz."""

    def __init__(self, synced=True, pending=(), resharding=False):
        self.shard_manager = object()
        self._synced = synced
        self._pending = list(pending)
        self._resharding = resharding

    def base_informers_synced(self):
        return self._synced

    def owned_shards(self):
        return {0, 1}

    def unsynced_shards(self):
        return self._pending

    def resharding_in_progress(self):
        return self._resharding


class TestReadyzDuringMigration:
    def _readyz(self, controller):
        from pytorch_operator_tpu.cmd.operator import make_readyz

        return make_readyz(controller, threading.Event(),
                           {"leading": False}, object())

    def test_steady_state_is_ready_and_not_degraded(self):
        ok, detail = self._readyz(_FakeSharded())()
        assert ok and "degraded" not in detail
        assert detail["shards"] == [0, 1]

    def test_resharding_reports_degraded_but_stays_ready(self):
        """Flapping /readyz on a routine ring migration would eject the
        replica from service exactly while it is moving work: the
        window must read DEGRADED at 200, never 503."""
        ok, detail = self._readyz(_FakeSharded(resharding=True))()
        assert ok is True
        assert detail["degraded"] is True and detail["resharding"] is True

    def test_freshly_acquired_unsynced_shards_degrade(self):
        ok, detail = self._readyz(
            _FakeSharded(pending=["2", "e1:3"]))()
        assert ok is True
        assert detail["degraded"] is True
        assert detail["unsynced_shards"] == ["2", "e1:3"]

    def test_unsynced_base_informers_are_unready(self):
        """The admission/node caches are the one hard gate: without
        them the replica cannot stamp or route anything."""
        ok, _detail = self._readyz(_FakeSharded(synced=False))()
        assert ok is False

    def test_live_controller_exposes_readyz_surface(self):
        """The fake above must not drift from the real controller: a
        live sharded controller answers the same calls."""
        ctl = _controller(FakeCluster(), "rz", shards=2)
        readyz = self._readyz(ctl)
        ok, detail = readyz()
        assert ok in (True, False) and "shards" in detail
        ctl.shutdown()


# ---------------------------------------------------------------------------
# queue-depth autoscaling (ISSUE 12 part 3)


class TestAutoscaler:
    def test_fleet_loads_parses_heartbeat_annotations(self):
        cluster = FakeCluster()
        leases = cluster.resource("leases")
        leases.create("default", {
            "metadata": {
                "name": "pytorch-operator-replica-r0",
                "labels": {constants.LABEL_LEASE_COMPONENT:
                           constants.LEASE_COMPONENT_HEARTBEAT},
                "annotations": {constants.ANNOTATION_SHARD_LOAD:
                                '{"0": 3, "1": 5.5}'}},
            "spec": {"holderIdentity": "r0"}})
        leases.create("default", {
            "metadata": {
                "name": "pytorch-operator-replica-r1",
                "labels": {constants.LABEL_LEASE_COMPONENT:
                           constants.LEASE_COMPONENT_HEARTBEAT},
                "annotations": {constants.ANNOTATION_SHARD_LOAD:
                                "not json"}},
            "spec": {"holderIdentity": "r1"}})
        # a non-heartbeat Lease must not be scanned at all
        leases.create("default", {
            "metadata": {"name": "pytorch-operator-shard-0"},
            "spec": {"holderIdentity": "r0"}})
        loads = fleet_loads(leases)
        # malformed payload skips the replica, not the scan
        assert loads == {"r0": {0: 3.0, 1: 5.5}}

    def test_scale_up_follows_total_depth(self):
        policy = AutoscalePolicy(target_depth_per_replica=10,
                                 max_replicas=8)
        rec = policy.recommend({"r0": {0: 25.0}, "r1": {1: 10.0}},
                               current_shard_count=2)
        assert rec.replicas == 4  # ceil(35 / 10)
        # every recommended replica can own at least one shard
        assert rec.shard_count == 4

    def test_scale_down_is_damped_one_step(self):
        policy = AutoscalePolicy(target_depth_per_replica=10)
        loads = {f"r{i}": {i: 0.0} for i in range(4)}
        rec = policy.recommend(loads)
        assert rec.replicas == 3  # 4 replicas, drained queue: one step
        assert "stepping down" in rec.reason

    def test_clamps_and_shard_floor(self):
        policy = AutoscalePolicy(target_depth_per_replica=1,
                                 min_replicas=2, max_replicas=3)
        rec = policy.recommend({"r0": {0: 1000.0}},
                               current_shard_count=6)
        assert rec.replicas == 3  # clamped to max
        assert rec.shard_count == 6  # never shrinks the current ring
        idle = policy.recommend({}, current_replicas=1)
        assert idle.replicas == 2  # clamped to min

    def test_rejects_non_positive_budget(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(target_depth_per_replica=0)

    def test_heartbeats_publish_loads_end_to_end(self):
        """A live sharded controller's heartbeat Lease carries the
        per-shard depth payload fleet_loads parses — the exact loop the
        operator's autoscale gauge closes."""
        cluster = FakeCluster()
        ctl = _controller(cluster, "load-pub", shards=2)
        stop = threading.Event()
        ctl.run(threadiness=1, stop_event=stop)
        try:
            assert wait_for(lambda: ctl.owned_shards() == {0, 1})
            leases = cluster.resource("leases")
            # the payload rides heartbeat RENEWALS: the entry for a
            # freshly built runtime appears one renew interval later
            assert wait_for(lambda: set(
                fleet_loads(leases).get("load-pub", {}).keys())
                == {0, 1})
            loads = fleet_loads(leases)
            rec = AutoscalePolicy().recommend(
                loads, current_shard_count=2)
            assert rec.replicas >= 1 and rec.shard_count >= 2
        finally:
            stop.set()
            ctl.shutdown()
