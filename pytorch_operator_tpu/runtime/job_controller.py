"""Generic job-controller base shared by job types.

First-party reimplementation of the reference's vendored runtime
(vendor/github.com/kubeflow/tf-operator/pkg/common/jobcontroller/):

  * JobController holds the pod/service controls, expectations cache,
    rate-limited workqueue and event recorder (jobcontroller.go:79-147);
  * pod/service informer callbacks resolve the controlling owner, mark
    expectations observed and enqueue the owning job (pod.go:20-241,
    service.go:17-148);
  * GetPodsForJob / GetServicesForJob list by the job's base labels and
    adopt orphans / release non-matching objects via owner references
    (pod.go:165-241), with an uncached deletion-timestamp recheck before
    adoption (pod.go:184-195);
  * name/key helpers (util.go:24-57) and gang-scheduling PodGroup sync
    (jobcontroller.go:224-299).

The concrete controller supplies job-type specifics through the
``ControllerInterface``-shaped hooks (jobcontroller.go:31-61).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..analysis.witness import make_lock
from ..api.v1 import constants
from ..k8s import serde
from ..k8s.errors import NotFoundError
from ..k8s.objects import OwnerReference
from .controls import FanoutExecutor, PodControl, ServiceControl
from .expectations import (
    ControllerExpectations,
    expectation_pods_key,
    expectation_services_key,
)
from .informer import Informer, meta_namespace_key
from .propagation import PropagationLedger
from .recorder import EventRecorder
from .timebudget import ReplicaTimeBudget
from .workqueue import WorkQueue, WorkQueueMetrics


def gen_general_name(job_name: str, rtype: str, index) -> str:
    """``{job}-{rtype}-{index}`` with ``/`` sanitized (util.go:24-28)."""
    return f"{job_name}-{rtype}-{index}".replace("/", "-")


def gen_pod_group_name(job_name: str) -> str:
    return job_name


class JobControllerConfig:
    def __init__(
        self,
        enable_gang_scheduling: bool = False,
        gang_scheduler_name: str = "volcano",
        init_container_image: str = "alpine:3.10",
        tpu_auto_gang: bool = True,
        resync_period_seconds: float = 0.0,
        enable_disruption_handling: bool = False,
        max_preemption_restarts: int = 3,
        drain_deadline_seconds: float = 30.0,
        max_elastic_resizes: int = 3,
        shard_count: int = 1,
        replica_id: str = "",
        shard_lease_duration: float = 15.0,
        shard_renew_interval: float = 5.0,
        create_fanout_width: Optional[int] = None,
        clock: Optional[Callable[[], float]] = None,
        push_token_secret: str = "",
        job_timeline_max_jobs: int = 2048,
        enable_admission: bool = False,
        quota_jobs: int = 0,
        quota_chips: int = 0,
        quota_overrides: Optional[Dict[str, Tuple[int, int]]] = None,
        cluster_max_jobs: int = 0,
        cluster_max_chips: int = 0,
        journal_capacity: int = 4096,
        informer_job_resync: float = 30.0,
        worker_poll_interval: float = 0.5,
    ):
        self.enable_gang_scheduling = enable_gang_scheduling
        self.gang_scheduler_name = gang_scheduler_name
        self.init_container_image = init_container_image
        # Disruption subsystem (--enable-disruption-handling): watch Node
        # taints / pod DisruptionTarget conditions and proactively
        # gang-restart preempted jobs instead of waiting out N per-pod
        # failure/backoff cycles.  max_preemption_restarts bounds the
        # proactive restarts per job (annotation-overridable per job).
        self.enable_disruption_handling = enable_disruption_handling
        self.max_preemption_restarts = max_preemption_restarts
        # Elastic gangs (--drain-deadline / --max-elastic-resizes): how
        # long a doomed pod gets to checkpoint before the shrink deletes
        # it anyway, and how many shrinks a job may consume before
        # falling back to the legacy full-gang restart
        # (annotation-overridable per job).
        self.drain_deadline_seconds = drain_deadline_seconds
        self.max_elastic_resizes = max_elastic_resizes
        # Periodic informer relist-and-diff (reference --resyc-period,
        # options.go:24, default 12h; the job informer additionally resyncs
        # every 30s, informer.go:24).  0 disables (unit-test default);
        # the CLI passes the parsed flag value.
        self.resync_period_seconds = resync_period_seconds
        # TPU-first deviation from the reference (options.go:73 keeps gang
        # opt-in): jobs requesting google.com/tpu get gang semantics even
        # with enable_gang_scheduling False, because a partially scheduled
        # TPU slice deadlocks.  Set False to restore reference behavior.
        self.tpu_auto_gang = tpu_auto_gang
        # Active-active sharded control plane (--shard-count > 1): jobs
        # hash to shards, each shard is owned via its own Lease, and
        # this replica runs informers + a workqueue per OWNED shard
        # instead of hot-standby leader election.  shard_count 1 (the
        # default) is behavior-identical to the leader-elected operator.
        self.shard_count = max(1, int(shard_count))
        self.replica_id = replica_id
        self.shard_lease_duration = shard_lease_duration
        self.shard_renew_interval = shard_renew_interval
        # Per-controller create/delete fan-out width (None follows the
        # PYTORCH_OPERATOR_CREATE_FANOUT env knob on the shared pools;
        # an int gives this controller a private pool of that width,
        # shut down with the controller).
        self.create_fanout_width = create_fanout_width
        # Injectable time source (sim.VirtualClock.now) honored by the
        # workqueue's delayed adds, the shard manager's lease
        # renew/expiry and the disruption handler's drain deadlines —
        # the cluster-scale simulator runs the whole control plane on
        # one deterministic virtual timeline through this.  None (the
        # default) is wall time everywhere, byte-identical to before.
        self.clock = clock
        # Push-identity secret (--push-token-secret): folded into every
        # per-job push token derived at pod build time and at the
        # gateway's ingestion check.  Empty (the default) still binds
        # tokens to the job incarnation's uid.
        self.push_token_secret = push_token_secret
        # Lifecycle-timeline store bound (--job-timeline-max-jobs):
        # per-job milestone/segment records kept for /debug/jobs before
        # LRU eviction.
        self.job_timeline_max_jobs = max(1, int(job_timeline_max_jobs))
        # Multi-tenant admission (--enable-admission): per-namespace
        # quotas (jobs + aggregate google.com/tpu chips, 0 = unlimited;
        # quota_overrides carves per-namespace exceptions as
        # {ns: (jobs, chips)}) and cluster-wide ceilings, enforced by a
        # fair-share DRR queue in front of the reconciler (admission/).
        # Off by default: the gate is pass-through and no Queued
        # conditions are ever written.
        self.enable_admission = enable_admission
        self.quota_jobs = max(0, int(quota_jobs))
        self.quota_chips = max(0, int(quota_chips))
        self.quota_overrides = dict(quota_overrides or {})
        self.cluster_max_jobs = max(0, int(cluster_max_jobs))
        self.cluster_max_chips = max(0, int(cluster_max_chips))
        # Flight-recorder ring bound (--journal-capacity): structured
        # control-plane events (lease transitions, ring flips, admission
        # verdicts, ...) kept for /debug/events before the oldest drop
        # (dropped events are counted, never silent).
        self.journal_capacity = max(1, int(journal_capacity))
        # Steady-state cadences, promoted from hard-coded constants so
        # the latency-budget bench can sweep them.  informer_job_resync
        # (--informer-job-resync) caps the JOB informer's resync period
        # (reference informer.go:24 hard-codes 30s; the effective value
        # is still min(cap, --resync-period) and 0 disables).
        # worker_poll_interval (--worker-poll-interval) is how long a
        # sync worker blocks in WorkQueue.get before re-checking for
        # shutdown — the floor on worker teardown latency, and pure
        # queue_idle time in the replica budget.
        self.informer_job_resync = max(0.0, float(informer_job_resync))
        self.worker_poll_interval = max(0.01, float(worker_poll_interval))


def _make_runtime_core(clock=None):
    """Expectations + workqueue, C++ when available (native/), Python
    otherwise.  PYTORCH_OPERATOR_NATIVE contract via
    native.resolve_backend (=0 forces Python, =1 hard error).  An
    injected ``clock`` (the simulator's virtual time) forces the Python
    pair — the native queue's delay heap lives in C++ against the real
    clock and cannot be driven by a virtual one."""
    if clock is not None:
        return (ControllerExpectations(clock=clock),
                WorkQueue(clock=clock))
    from pytorch_operator_tpu.native import (
        NativeExpectations,
        NativeWorkQueue,
        resolve_backend,
    )

    if resolve_backend("core"):
        return NativeExpectations(), NativeWorkQueue()
    return ControllerExpectations(), WorkQueue()


class JobController:
    """Generic base; a concrete controller subclasses and provides
    the GroupVersionKind identity plus reconcile logic."""

    # -- ControllerInterface identity hooks (override in subclass) ---------
    API_GROUP_VERSION = constants.API_VERSION
    KIND = constants.KIND
    CONTROLLER_NAME = constants.CONTROLLER_NAME
    GROUP_NAME = constants.GROUP_NAME

    def __init__(self, cluster, config: Optional[JobControllerConfig] = None,
                 recorder=None, registry=None):
        """``cluster`` is any object exposing resource clients as
        attributes: .pods .services .events .podgroups plus the job kind —
        both FakeCluster and the real client qualify.  ``registry``
        receives the runtime's instrumentation (workqueue, informer and
        batch-latency series); the shared default registry when None."""
        self.cluster = cluster
        self.config = config or JobControllerConfig()
        if registry is None:
            from ..metrics import default_registry
            registry = default_registry
        self.registry = registry
        # one injectable monotonic source for everything this controller
        # times (sync durations, queue metrics, informer lag) — the
        # simulator's virtual ``now`` when config.clock is set
        self.mono_clock = self.config.clock or time.monotonic
        self.recorder = recorder or EventRecorder(
            cluster.events, self.CONTROLLER_NAME, clock=self.config.clock)
        # The fan-out executor is OWNED by the controller (constructor-
        # injected into both controls, shut down in shutdown()) so each
        # replica of a sharded fleet can run its own width.
        self.fanout = FanoutExecutor(self.config.create_fanout_width)
        batch_clock = self.config.clock or time.perf_counter
        self.pod_control = PodControl(cluster.pods, self.recorder,
                                      registry=registry,
                                      executor=self.fanout,
                                      clock=batch_clock)
        self.service_control = ServiceControl(cluster.services, self.recorder,
                                              registry=registry,
                                              executor=self.fanout,
                                              clock=batch_clock)
        self.expectations, self.work_queue = _make_runtime_core(
            self.config.clock)
        # shard-runtime registry (populated by the concrete controller
        # when --shard-count > 1): shard index -> an object with a
        # ``queue`` (WorkQueue) and a ``job_informer`` whose store holds
        # the shard's jobs.  Empty in single-replica mode, where every
        # queue operation resolves to self.work_queue unchanged.
        self._shard_runtimes: Dict[int, object] = {}
        # target-ring runtimes during a live reshard (shard index under
        # the NEW ring geometry -> runtime); promoted wholesale into
        # _shard_runtimes at the ring flip.  Empty outside a migration.
        self._next_shard_runtimes: Dict[int, object] = {}
        self._shard_lock = make_lock("controller.shards")
        # client-go workqueue metric families for the one sync queue;
        # both the Python and the native C++ queue take the same hooks.
        self.work_queue_metrics = WorkQueueMetrics(registry, "pytorchjob",
                                                   clock=self.mono_clock)
        self.work_queue.set_metrics(self.work_queue_metrics)
        # Steady-state latency instrumentation: the propagation ledger
        # stamps each job event's journey (informer receive -> enqueue
        # -> get -> reconcile -> commit; the ledger's wall clock rides
        # the virtual clock in sim runs so snapshots stay
        # byte-deterministic), the time budget classifies this replica's
        # wall time into activity buckets.  Both serve /debug/timebudget.
        self.timebudget = ReplicaTimeBudget(
            registry=registry, clock=self.mono_clock,
            replica_id=self.config.replica_id)
        self.propagation = PropagationLedger(
            registry=registry, clock=self.mono_clock,
            wall=self.config.clock,
            replica_id=self.config.replica_id)
        self.work_queue.set_propagation(self.propagation)
        resync = self.config.resync_period_seconds
        self.pod_informer = Informer(cluster.pods, resync_period=resync,
                                     name="pods", registry=registry,
                                     clock=self.mono_clock,
                                     budget=self.timebudget)
        self.service_informer = Informer(cluster.services,
                                         resync_period=resync,
                                         name="services", registry=registry,
                                         clock=self.mono_clock,
                                         budget=self.timebudget)
        # Node informer: only materialized when disruption handling is on
        # and the cluster backend models Nodes (FakeCluster/RestCluster
        # both do; bare test doubles may not).  The concrete controller's
        # disruption watcher registers its handlers on it.
        self.node_informer: Optional[Informer] = None
        if self.config.enable_disruption_handling:
            nodes = getattr(cluster, "nodes", None)
            if nodes is not None:
                self.node_informer = Informer(nodes, resync_period=resync,
                                              name="nodes",
                                              registry=registry,
                                              clock=self.mono_clock,
                                              budget=self.timebudget)
        self._stop = threading.Event()

        self.pod_informer.add_event_handler(
            on_add=self.add_pod, on_update=self.update_pod, on_delete=self.delete_pod
        )
        self.service_informer.add_event_handler(
            on_add=self.add_service, on_delete=self.delete_service)

    # -- labels / owner refs ----------------------------------------------
    def gen_labels(self, job_name: str) -> Dict[str, str]:
        """jobcontroller.go:210-222."""
        name = job_name.replace("/", "-")
        return {
            constants.LABEL_GROUP_NAME: self.GROUP_NAME,
            constants.LABEL_JOB_NAME: name,
            constants.LABEL_PYTORCH_JOB_NAME: name,
            constants.LABEL_CONTROLLER_NAME: self.CONTROLLER_NAME,
        }

    def gen_owner_reference(self, job: dict) -> OwnerReference:
        meta = job.get("metadata", {})
        return OwnerReference(
            api_version=self.API_GROUP_VERSION,
            kind=self.KIND,
            name=meta.get("name", ""),
            uid=meta.get("uid", ""),
            controller=True,
            block_owner_deletion=True,
        )

    # -- enqueue -----------------------------------------------------------
    def _shard_runtime_snapshot(self) -> List[object]:
        if not self._shard_runtimes and not self._next_shard_runtimes:
            return []
        with self._shard_lock:
            return (list(self._shard_runtimes.values())
                    + list(self._next_shard_runtimes.values()))

    def _ring_epochs(self):
        """(current ring epoch, next ring epoch or None) — overridden
        by the sharded controller, which reads its ShardManager.  The
        base is permanently pre-resharding."""
        return 0, None

    def _owns_job_key(self, key: str) -> bool:
        """Sharded ownership test: is ``key`` in one of this replica's
        shard-informer stores?  Always True in single-replica mode
        (everything is ours); a SHARDED replica owning zero shards owns
        zero jobs — the mode test must be the config, never the
        runtime dict's emptiness."""
        if self.config.shard_count <= 1:
            return True
        for runtime in self._shard_runtime_snapshot():
            if runtime.job_informer.store.contains(key):
                return True
        return False

    def _queue_for_key(self, key: str):
        """The workqueue responsible for ``key``: the owning shard's
        queue when this replica runs sharded and a shard runtime's job
        store holds the key, else the controller-wide queue (the
        single-replica path, byte-identical to before sharding)."""
        for runtime in self._shard_runtime_snapshot():
            if runtime.job_informer.store.contains(key):
                return runtime.queue
        return self.work_queue

    def enqueue_job(self, job: dict) -> None:
        key = meta_namespace_key(job)
        if self._shard_runtimes or self._next_shard_runtimes:
            labels = (job.get("metadata") or {}).get("labels") or {}
            shard = labels.get(constants.LABEL_SHARD)
            if shard is not None and shard.isdigit():
                # a shard index is only meaningful together with its
                # ring epoch: during a live reshard the same index
                # exists in BOTH rings, and routing by index alone
                # would double-deliver re-stamped jobs
                from .sharding import ring_epoch_of

                current_epoch, next_epoch = self._ring_epochs()
                obj_epoch = ring_epoch_of(job)
                with self._shard_lock:
                    if obj_epoch == current_epoch:
                        runtime = self._shard_runtimes.get(int(shard))
                    elif (next_epoch is not None
                          and obj_epoch == next_epoch):
                        runtime = self._next_shard_runtimes.get(
                            int(shard))
                    else:
                        runtime = None
                if runtime is not None:
                    runtime.queue.add(key)
                    return
            self._queue_for_key(key).add(key)
            return
        self.work_queue.add(key)

    def shutdown(self) -> None:
        """Stop the controller's owned machinery: the sync queue(s),
        every shard runtime, the shard manager (when sharded) and the
        fan-out executor.  Replaces bare ``work_queue.shutdown()`` as
        the operator's teardown entry point; calling both is harmless."""
        self.work_queue.shutdown()
        manager = getattr(self, "shard_manager", None)
        if manager is not None:
            manager.stop()
        with self._shard_lock:
            runtimes = (list(self._shard_runtimes.values())
                        + list(self._next_shard_runtimes.values()))
            self._shard_runtimes.clear()
            self._next_shard_runtimes.clear()
        for runtime in runtimes:
            runtime.stop()
        self.fanout.shutdown()

    # -- pod informer callbacks (jobcontroller/pod.go:20-163) --------------
    def _resolve_controller_ref(self, namespace: str, ref) -> Optional[dict]:
        if ref is None or ref.kind != self.KIND:
            return None
        try:
            job = self._get_job_from_cache(namespace, ref.name)
        except NotFoundError:
            return None
        if job is None:
            return None
        if (job.get("metadata", {}).get("uid") or "") != ref.uid:
            return None
        return job

    def _get_job_from_cache(self, namespace: str, name: str) -> Optional[dict]:
        """Override point: fetch the job object (dict) from the local cache."""
        raise NotImplementedError

    def add_pod(self, pod: dict) -> None:
        meta = pod.get("metadata", {})
        if meta.get("deletionTimestamp"):
            self.delete_pod(pod)
            return
        ref = _controller_ref_of(meta)
        if ref is None:
            return
        job = self._resolve_controller_ref(meta.get("namespace", ""), ref)
        if job is None:
            return
        job_key = meta_namespace_key(job)
        rtype = meta.get("labels", {}).get(constants.LABEL_REPLICA_TYPE)
        if rtype is None:
            return
        self.expectations.creation_observed(expectation_pods_key(job_key, rtype))
        self.enqueue_job(job)

    def update_pod(self, old_pod: dict, new_pod: dict) -> None:
        old_meta = old_pod.get("metadata", {})
        new_meta = new_pod.get("metadata", {})
        if old_meta.get("resourceVersion") == new_meta.get("resourceVersion"):
            return
        if new_meta.get("deletionTimestamp"):
            self.delete_pod(new_pod)
            return
        old_ref = _controller_ref_of(old_meta)
        new_ref = _controller_ref_of(new_meta)
        if old_ref and (not new_ref or old_ref.uid != new_ref.uid):
            # controller ref changed: sync the old controller too
            old_job = self._resolve_controller_ref(old_meta.get("namespace", ""), old_ref)
            if old_job is not None:
                self.enqueue_job(old_job)
        if new_ref is not None:
            job = self._resolve_controller_ref(new_meta.get("namespace", ""), new_ref)
            if job is not None:
                self.enqueue_job(job)

    def delete_pod(self, pod: dict) -> None:
        meta = pod.get("metadata", {})
        ref = _controller_ref_of(meta)
        if ref is None:
            return
        job = self._resolve_controller_ref(meta.get("namespace", ""), ref)
        if job is None:
            return
        job_key = meta_namespace_key(job)
        rtype = meta.get("labels", {}).get(constants.LABEL_REPLICA_TYPE)
        if rtype is None:
            return
        self.expectations.deletion_observed(expectation_pods_key(job_key, rtype))
        self.enqueue_job(job)

    # -- service informer callbacks (jobcontroller/service.go:17-66) -------
    def add_service(self, service: dict) -> None:
        meta = service.get("metadata", {})
        ref = _controller_ref_of(meta)
        if ref is None:
            return
        job = self._resolve_controller_ref(meta.get("namespace", ""), ref)
        if job is None:
            return
        job_key = meta_namespace_key(job)
        rtype = meta.get("labels", {}).get(constants.LABEL_REPLICA_TYPE)
        if rtype is None:
            return
        self.expectations.creation_observed(expectation_services_key(job_key, rtype))
        self.enqueue_job(job)

    def delete_service(self, service: dict) -> None:
        """Observe a service deletion (mirror of delete_pod): the batch
        delete path raises deletion expectations up-front, so DELETED
        events must decrement them or the job parks until the TTL."""
        meta = service.get("metadata", {})
        ref = _controller_ref_of(meta)
        if ref is None:
            return
        job = self._resolve_controller_ref(meta.get("namespace", ""), ref)
        if job is None:
            return
        job_key = meta_namespace_key(job)
        rtype = meta.get("labels", {}).get(constants.LABEL_REPLICA_TYPE)
        if rtype is None:
            return
        self.expectations.deletion_observed(expectation_services_key(job_key, rtype))
        self.enqueue_job(job)

    # -- list + adopt/orphan (jobcontroller/pod.go:165-241) ----------------
    def get_pods_for_job(self, job: dict) -> List[dict]:
        return self._claim_objects(job, self.cluster.pods)

    def get_services_for_job(self, job: dict) -> List[dict]:
        return self._claim_objects(job, self.cluster.services)

    def _claim_objects(self, job: dict, client) -> List[dict]:
        meta = job.get("metadata", {})
        namespace = meta.get("namespace", "default")
        job_uid = meta.get("uid", "")
        selector = self.gen_labels(meta.get("name", ""))
        # Label-selector list, exactly as the reference (pod.go:165-178
        # lists with MatchLabels=GenLabels); orphans eligible for adoption
        # match the selector by definition.
        claimed = []
        for obj in client.list(namespace=namespace, label_selector=selector):
            obj_meta = obj.get("metadata", {})
            refs = obj_meta.get("ownerReferences") or []
            controller_ref = next((r for r in refs if r.get("controller")), None)
            if controller_ref is not None:
                if controller_ref.get("uid") == job_uid:
                    claimed.append(obj)
                # else: owned by someone else — leave it alone
            else:
                # Adopt, unless the job or object is being deleted
                # (RecheckDeletionTimestamp, util.go:30-44).
                if meta.get("deletionTimestamp") or obj_meta.get("deletionTimestamp"):
                    continue
                ref = serde.to_dict(self.gen_owner_reference(job))
                try:
                    adopted = client.patch(
                        namespace,
                        obj_meta.get("name", ""),
                        {"metadata": {"ownerReferences": refs + [ref]}},
                    )
                    claimed.append(adopted)
                except NotFoundError:
                    pass
        return claimed

    @staticmethod
    def filter_pods_for_replica_type(pods: List[dict], replica_type: str) -> List[dict]:
        """FilterPodsForReplicaType (lowercase type label match)."""
        rt = replica_type.lower()
        return [
            p
            for p in pods
            if (p.get("metadata", {}).get("labels") or {}).get(constants.LABEL_REPLICA_TYPE) == rt
        ]

    filter_services_for_replica_type = filter_pods_for_replica_type

    @staticmethod
    def get_pod_slices(pods: List[dict], replicas: int) -> List[List[dict]]:
        """Group pods by their replica-index label (pytorch/pod.go:119-139)."""
        slices: List[List[dict]] = [[] for _ in range(replicas)]
        for pod in pods:
            labels = pod.get("metadata", {}).get("labels") or {}
            index_str = labels.get(constants.LABEL_REPLICA_INDEX)
            if index_str is None:
                continue
            try:
                index = int(index_str)
            except ValueError:
                continue
            if 0 <= index < replicas:
                slices[index].append(pod)
        return slices

    get_service_slices = get_pod_slices

    # -- gang scheduling (jobcontroller.go:224-299) ------------------------
    def sync_pod_group(self, job: dict, min_available: int) -> dict:
        meta = job.get("metadata", {})
        name = gen_pod_group_name(meta.get("name", ""))
        namespace = meta.get("namespace", "default")
        try:
            pg = self.cluster.podgroups.get(namespace, name)
            # Replicas resized after creation: keep minMember equal to the
            # current total or the gang constraint silently goes stale
            # (the reference never updates it — jobcontroller.go:233-248
            # creates once and returns the cached group forever).
            if int((pg.get("spec") or {}).get("minMember") or 0) != min_available:
                pg = self.cluster.podgroups.patch(
                    namespace, name, {"spec": {"minMember": min_available}}
                )
            return pg
        except NotFoundError:
            pass
        ref = serde.to_dict(self.gen_owner_reference(job))
        pg = {
            "apiVersion": "scheduling.incubator.k8s.io/v1alpha1",
            "kind": "PodGroup",
            "metadata": {
                "name": name,
                "namespace": namespace,
                "ownerReferences": [ref],
            },
            "spec": {"minMember": min_available},
        }
        return self.cluster.podgroups.create(namespace, pg)

    def delete_pod_group(self, job: dict) -> None:
        meta = job.get("metadata", {})
        try:
            self.cluster.podgroups.delete(
                meta.get("namespace", "default"), gen_pod_group_name(meta.get("name", ""))
            )
        except NotFoundError:
            pass


def _controller_ref_of(meta: dict) -> Optional[OwnerReference]:
    for r in meta.get("ownerReferences") or []:
        if r.get("controller"):
            return serde.from_dict(OwnerReference, r)
    return None
