"""Multi-tenant admission (ISSUE 17): per-namespace quotas, integer
job priorities, the weighted deficit-round-robin release queue,
priority preemption through the elastic checkpoint-drain path, and the
condition-rebuild durability that survives a SIGKILL of the owning
replica.

Acceptance: a hostile tenant submitting 10x its quota degrades only
its own admission latency (the small hostile-tenant scenario here, the
full churn tier under ``@pytest.mark.slow`` via
``scripts/run-tests.sh --tenancy``); a preempted elastic victim
checkpoints before any delete with zero duplicate creates while a
non-elastic victim takes the unchanged legacy restart; and a rebuilt
admission ledger (fresh controller over the same job objects) loses no
queued job and admits none twice.
"""

from __future__ import annotations

import pytest

from pytorch_operator_tpu.admission import (
    AdmissionController,
    KIND_GROW,
    KIND_RESTART,
    QuotaPolicy,
    job_chips,
    job_min_chips,
    job_priority,
    parse_quota_overrides,
)
from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.api.v1.defaults import set_defaults
from pytorch_operator_tpu.api.v1.types import ElasticPolicy, PyTorchJob
from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.controller import status as status_machine
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import (
    FakePodControl,
    FakeServiceControl,
    JobControllerConfig,
)
from pytorch_operator_tpu.sim import TenancyConfig, run_tenancy

from testutil import new_job, wait_for


class FakeClock:
    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def admission_job(name, namespace="team-a", workers=2, tpu_chips=4,
                  priority=None, elastic_min=None) -> PyTorchJob:
    job = new_job(workers=workers, name=name, namespace=namespace,
                  tpu_chips=tpu_chips)
    if elastic_min is not None:
        job.spec.elastic_policy = ElasticPolicy(min_replicas=elastic_min)
    if priority is not None:
        job.spec.priority = priority
    set_defaults(job)
    return job


# ---------------------------------------------------------------------------
# Quota accounting (admission/quota.py)
# ---------------------------------------------------------------------------


class TestQuotaAccounting:
    def test_job_chips_counts_the_whole_gang(self):
        # master (1x4) + 8 workers (8x4)
        job = admission_job("j", workers=8, tpu_chips=4)
        assert job_chips(job) == 36

    def test_job_min_chips_is_the_elastic_floor(self):
        job = admission_job("j", workers=8, tpu_chips=4, elastic_min=4)
        # master + minReplicas workers
        assert job_min_chips(job) == 20
        # non-elastic jobs have no floor below full size
        plain = admission_job("p", workers=8, tpu_chips=4)
        assert job_min_chips(plain) == job_chips(plain) == 36

    def test_job_priority_spec_wins_over_annotation(self):
        job = admission_job("j", priority=7)
        job.metadata.annotations = {constants.ANNOTATION_PRIORITY: "3"}
        assert job_priority(job) == 7

    def test_job_priority_annotation_fallback(self):
        job = admission_job("j")
        job.metadata.annotations = {constants.ANNOTATION_PRIORITY: " 5 "}
        assert job_priority(job) == 5

    def test_job_priority_garbage_annotation_is_unset(self):
        job = admission_job("j")
        job.metadata.annotations = {constants.ANNOTATION_PRIORITY: "urgent"}
        assert job_priority(job) == 0

    def test_job_priority_bool_spec_is_not_one(self):
        job = admission_job("j")
        job.spec.priority = True  # bypasses validation, as tests do
        assert job_priority(job) == 0

    def test_parse_quota_overrides_roundtrip(self):
        got = parse_quota_overrides("team-a=4:64, team-b=2:0")
        assert got == {"team-a": (4, 64), "team-b": (2, 0)}
        assert parse_quota_overrides("") == {}
        assert parse_quota_overrides(None) == {}

    def test_parse_quota_overrides_rejects_malformed(self):
        # quota config is security config: never silently dropped
        with pytest.raises(ValueError):
            parse_quota_overrides("team-a")
        with pytest.raises(ValueError):
            parse_quota_overrides("team-a=4")
        with pytest.raises(ValueError):
            parse_quota_overrides("team-a=four:64")

    def test_quota_policy_overrides_and_weight_floor(self):
        policy = QuotaPolicy(default_jobs=2, default_chips=32,
                             overrides={"big": (8, 256), "zero": (0, 0)})
        assert policy.quota_jobs("anyone") == 2
        assert policy.quota_jobs("big") == 8
        assert policy.quota_chips("big") == 256
        assert policy.weight("big") == 8
        # unlimited namespaces weigh 1, never 0
        assert policy.weight("zero") == 1


# ---------------------------------------------------------------------------
# DRR fairness under a fake clock (admission/queue.py, no controller)
# ---------------------------------------------------------------------------


def _drr(policy=None, clock=None, preempt=None, **kw):
    released = []
    adm = AdmissionController(
        policy, clock=(clock or FakeClock()).now if clock is None else
        clock.now, preempt=preempt,
        on_release=lambda key, kind: released.append((key, kind)), **kw)
    return adm, released


class TestDRRFairness:
    def _hostile_world(self):
        clock = FakeClock()
        adm, released = _drr(QuotaPolicy(default_jobs=1),
                             clock=clock, cluster_max_jobs=1)
        jobs = []
        # the hostile backlog arrives FIRST — a pure-FIFO queue would
        # drain all 10 before any compliant tenant runs
        for i in range(10):
            jobs.append(admission_job(f"h-{i}", namespace="tenant-hostile",
                                      workers=1, tpu_chips=0))
        for ns in ("team-a", "team-b"):
            for i in range(2):
                jobs.append(admission_job(f"{ns}-{i}", namespace=ns,
                                          workers=1, tpu_chips=0))
        for job in jobs:
            adm.offer(job, has_pods=False)
        return clock, adm, released, jobs

    def _drain(self, clock, adm, released, total):
        done = 0
        while len(released) < total:
            clock.advance(1.0)
            adm.note_terminal(released[done][0])
            done += 1
        return [key for key, _ in released]

    def test_hostile_backlog_cannot_starve_compliant_tenants(self):
        clock, adm, released, jobs = self._hostile_world()
        order = self._drain(clock, adm, released, len(jobs))
        assert len(order) == 14
        compliant = {f"team-a/team-a-{i}" for i in range(2)} | {
            f"team-b/team-b-{i}" for i in range(2)}
        # one hostile job held the single slot at submit time; every
        # compliant job is released before the rest of the flood drains
        assert set(order[1:5]) == compliant
        assert all(key.startswith("tenant-hostile/") for key in order[5:])

    def test_release_order_is_deterministic(self):
        first = self._drain(*self._hostile_world()[:3], total=14)
        repeat = self._drain(*self._hostile_world()[:3], total=14)
        assert first == repeat

    def test_priority_orders_within_namespace(self):
        clock = FakeClock()
        adm, released = _drr(QuotaPolicy(default_jobs=1), clock=clock)
        low = admission_job("low", workers=1, tpu_chips=0)
        mid = admission_job("mid", workers=1, tpu_chips=0)
        high = admission_job("high", workers=1, tpu_chips=0, priority=5)
        assert adm.offer(low, has_pods=False) is True
        assert adm.offer(mid, has_pods=False) is False
        assert adm.offer(high, has_pods=False) is False
        adm.note_terminal(low.key)
        # the later-enqueued high-priority job jumps its sibling
        assert released[-1] == (high.key, "admit")
        adm.note_terminal(high.key)
        assert released[-1] == (mid.key, "admit")

    def test_wait_measured_on_the_injected_clock(self):
        clock = FakeClock()
        waits = []
        adm = AdmissionController(
            QuotaPolicy(default_jobs=1), clock=clock.now,
            wait_observer=lambda ns, wait, kind: waits.append(
                (ns, wait, kind)))
        adm.offer(admission_job("a", workers=1, tpu_chips=0),
                  has_pods=False)
        blocked = admission_job("b", workers=1, tpu_chips=0)
        adm.offer(blocked, has_pods=False)
        clock.advance(42.0)
        adm.note_terminal("team-a/a")
        assert ("team-a", 42.0, "admit") in waits

    def test_chips_quota_blocks_then_frees(self):
        adm, released = _drr(QuotaPolicy(default_chips=40),
                             clock=FakeClock())
        big = admission_job("big", workers=8, tpu_chips=4)      # 36
        small = admission_job("small", workers=3, tpu_chips=4)  # 16
        assert adm.offer(big, has_pods=False) is True
        assert adm.offer(small, has_pods=False) is False
        snap = adm.snapshot()
        assert snap["team-a"] == {"admitted_jobs": 1, "chips": 36,
                                  "waiting": 1}
        adm.note_terminal(big.key)
        assert released[-1] == (small.key, "admit")
        assert adm.snapshot()["team-a"]["chips"] == 16


class TestQueuePreemption:
    def test_elastic_preemption_frees_chips_and_arms_grow_back(self):
        clock = FakeClock()
        decisions = []

        def preempt(victim_key, waiter_key):
            decisions.append((victim_key, waiter_key))
            return "elastic"

        adm, released = _drr(QuotaPolicy(default_chips=40), clock=clock,
                             preempt=preempt)
        victim = admission_job("victim", workers=8, tpu_chips=4,
                               elastic_min=4)          # 36, floor 20
        waiter = admission_job("waiter", workers=3, tpu_chips=4,
                               priority=10)            # 16
        assert adm.offer(victim, has_pods=False) is True
        assert adm.offer(waiter, has_pods=False) is True
        assert decisions == [(victim.key, waiter.key)]
        assert adm.waiting_kind(victim.key) == KIND_GROW
        assert adm.grow_allowed(victim.key) is False
        # victim keeps its floor; waiter got the shed chips
        assert adm.snapshot()["team-a"]["chips"] == 20 + 16
        # the waiter finishing releases the grow-back claim
        adm.note_terminal(waiter.key)
        assert released[-1] == (victim.key, KIND_GROW)
        assert adm.grow_allowed(victim.key) is True
        assert adm.snapshot()["team-a"]["chips"] == 36

    def test_restart_preemption_frees_the_whole_grant(self):
        adm, released = _drr(QuotaPolicy(default_jobs=1),
                             clock=FakeClock(),
                             preempt=lambda v, w: "restart")
        victim = admission_job("victim", workers=2, tpu_chips=4)
        waiter = admission_job("waiter", workers=2, tpu_chips=4,
                               priority=5)
        assert adm.offer(victim, has_pods=False) is True
        assert adm.offer(waiter, has_pods=False) is True
        assert adm.waiting_kind(victim.key) == KIND_RESTART
        adm.note_terminal(waiter.key)
        assert released[-1] == (victim.key, KIND_RESTART)

    def test_refused_preemption_leaves_the_waiter_queued(self):
        adm, _ = _drr(QuotaPolicy(default_jobs=1), clock=FakeClock(),
                      preempt=lambda v, w: None)
        victim = admission_job("victim", workers=1, tpu_chips=0)
        waiter = admission_job("waiter", workers=1, tpu_chips=0,
                               priority=5)
        assert adm.offer(victim, has_pods=False) is True
        # the callback refuses (e.g. budget exhausted): no ledger change
        assert adm.offer(waiter, has_pods=False) is False
        assert adm.is_waiting(waiter.key)
        assert adm.waiting_kind(victim.key) is None

    def test_equal_priority_never_preempts(self):
        decisions = []

        def preempt(victim_key, waiter_key):
            decisions.append(victim_key)
            return "restart"

        adm, _ = _drr(QuotaPolicy(default_jobs=1), clock=FakeClock(),
                      preempt=preempt)
        adm.offer(admission_job("first", workers=1, tpu_chips=0),
                  has_pods=False)
        assert adm.offer(admission_job("second", workers=1, tpu_chips=0),
                         has_pods=False) is False
        assert decisions == []


# ---------------------------------------------------------------------------
# Controller integration: the gate, elastic drain, legacy restart
# ---------------------------------------------------------------------------


def _admission_world(**cfg_kwargs):
    cluster = FakeCluster()
    ctl = PyTorchController(
        cluster,
        config=JobControllerConfig(enable_admission=True, **cfg_kwargs),
        registry=Registry())
    ctl.pod_control = FakePodControl()
    ctl.service_control = FakeServiceControl()
    return cluster, ctl


def _bound_pod(ctl, job, name, node, rtype="worker", index="0",
               phase="Running"):
    labels = dict(ctl.gen_labels(job.metadata.name))
    labels[constants.LABEL_REPLICA_TYPE] = rtype
    labels[constants.LABEL_REPLICA_INDEX] = index
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": name, "namespace": job.metadata.namespace,
            "labels": labels,
            "ownerReferences": [{
                "apiVersion": constants.API_VERSION,
                "kind": constants.KIND,
                "name": job.metadata.name,
                "uid": job.metadata.uid, "controller": True}],
        },
        "spec": {"nodeName": node,
                 "containers": [{"name": "pytorch", "image": "i"}]},
        "status": {"phase": phase},
    }


def _gang_pods(cluster, ctl, job):
    name = job.metadata.name
    ns = job.metadata.namespace
    workers = int(job.spec.pytorch_replica_specs[
        constants.REPLICA_TYPE_WORKER].replicas or 0)
    pods = [_bound_pod(ctl, job, f"{name}-master-0", "node-m",
                       rtype="master")]
    for i in range(workers):
        pods.append(_bound_pod(ctl, job, f"{name}-worker-{i}",
                               f"node-{i}", index=str(i)))
    for pod in pods:
        cluster.pods.create(ns, pod)
    return [cluster.pods.get(ns, p["metadata"]["name"]) for p in pods]


def _queued_cond(job):
    return status_machine.get_condition(job.status, constants.JOB_QUEUED)


class TestPriorityPreemption:
    def test_elastic_victim_checkpoints_before_delete_no_dup_creates(self):
        cluster, ctl = _admission_world(quota_chips=40)
        victim = admission_job("victim", namespace="default", workers=8,
                               tpu_chips=4, elastic_min=4)
        waiter = admission_job("waiter", namespace="default", workers=3,
                               tpu_chips=4, priority=10)
        for job in (victim, waiter):
            cluster.jobs.create("default", job.to_dict())
        ctl.start_informers()
        try:
            assert wait_for(lambda: ctl._get_job_from_cache(
                "default", "victim") is not None)
            assert ctl._admission_gate(victim, []) is True
            pods = _gang_pods(cluster, ctl, victim)

            # the waiter's own gate call triggers the preemption
            assert ctl._admission_gate(waiter, []) is True
            assert ctl.admission.waiting_kind(victim.key) == KIND_GROW
            assert ctl._admission_grow_allowed(victim) is False

            # phase 1: nothing deleted, nothing created — the doomed
            # tail (workers above the floor) is signalled to checkpoint
            assert ctl.maybe_handle_disruption(
                victim, victim.to_dict(), pods) is True
            assert ctl.pod_control.delete_pod_names == []
            assert ctl.pod_control.templates == []
            doomed = [f"victim-worker-{i}" for i in range(4, 8)]
            for pod_name in doomed:
                anns = cluster.pods.get("default", pod_name)[
                    "metadata"]["annotations"]
                assert constants.ANNOTATION_CHECKPOINT_REQUESTED in anns
            survivor = cluster.pods.get("default", "victim-worker-0")
            assert constants.ANNOTATION_CHECKPOINT_REQUESTED not in (
                survivor["metadata"].get("annotations") or {})

            # phase 2: acks land -> ONLY the doomed tail is deleted
            for pod_name in doomed:
                cluster.pods.patch("default", pod_name, {
                    "metadata": {"annotations": {
                        constants.ANNOTATION_CHECKPOINTED: "now"}}})
            pods = cluster.pods.list("default")
            assert ctl.maybe_continue_elastic(
                victim, victim.to_dict(), pods) is True
            assert sorted(ctl.pod_control.delete_pod_names) == doomed
            assert ctl.pod_control.templates == []  # zero dup creates

            # the shrunken victim keeps running, condition True with
            # the preempted reason (this IS the durable grow claim)
            survivors = [p for p in cluster.pods.list("default")
                         if p["metadata"]["name"] not in doomed]
            assert ctl._admission_gate(victim, survivors) is True
            cond = _queued_cond(victim)
            assert cond is not None and cond.status == "True"
            assert cond.reason == constants.ADMISSION_PREEMPTED_REASON

            # waiter finishes -> grow-back released and re-armed
            ctl.admission.note_terminal(waiter.key)
            assert ctl._admission_grow_allowed(victim) is True
            with ctl._disruption_lock:
                assert victim.key in ctl._pending_grows
        finally:
            ctl.shutdown()

    def test_non_elastic_victim_takes_the_legacy_restart(self):
        cluster, ctl = _admission_world(quota_jobs=1)
        victim = admission_job("victim", namespace="default", workers=2,
                               tpu_chips=4)
        waiter = admission_job("waiter", namespace="default", workers=2,
                               tpu_chips=4, priority=5)
        for job in (victim, waiter):
            cluster.jobs.create("default", job.to_dict())
        ctl.start_informers()
        try:
            assert wait_for(lambda: ctl._get_job_from_cache(
                "default", "victim") is not None)
            assert ctl._admission_gate(victim, []) is True
            pods = _gang_pods(cluster, ctl, victim)

            assert ctl._admission_gate(waiter, []) is True
            assert ctl.admission.waiting_kind(victim.key) == KIND_RESTART

            # unchanged legacy path: one batched gang delete
            assert ctl.maybe_handle_disruption(
                victim, victim.to_dict(), pods) is True
            assert sorted(ctl.pod_control.delete_pod_names) == sorted(
                p["metadata"]["name"] for p in pods)
            conds = {c.type: c for c in victim.status.conditions}
            assert conds[constants.JOB_RESTARTING].status == "True"

            # recreation is gated until the queue re-releases the victim
            assert ctl._admission_gate(victim, []) is False
            cond = _queued_cond(victim)
            assert cond.status == "True"
            assert cond.reason == constants.ADMISSION_PREEMPTED_REASON

            ctl.admission.note_terminal(waiter.key)
            assert ctl._admission_gate(victim, []) is True
        finally:
            ctl.shutdown()

    def test_preemption_refused_when_restart_budget_exhausted(self):
        cluster, ctl = _admission_world(quota_jobs=1)
        victim = admission_job("victim", namespace="default", workers=2,
                               tpu_chips=4)
        victim.status.preemption_restarts = 99
        waiter = admission_job("waiter", namespace="default", workers=2,
                               tpu_chips=4, priority=5)
        for job in (victim, waiter):
            cluster.jobs.create("default", job.to_dict())
        ctl.start_informers()
        try:
            assert wait_for(lambda: ctl._get_job_from_cache(
                "default", "victim") is not None)
            assert ctl._admission_gate(victim, []) is True
            _gang_pods(cluster, ctl, victim)
            # killing the gang would strand it at the gate: refuse, the
            # waiter stays queued rather than wedging the victim
            assert ctl._admission_gate(waiter, []) is False
            assert ctl.admission.is_waiting(waiter.key)
            assert ctl.admission.waiting_kind(victim.key) is None
        finally:
            ctl.shutdown()


# ---------------------------------------------------------------------------
# Handover durability: SIGKILL of the owner loses nothing, doubles nothing
# ---------------------------------------------------------------------------


class TestHandoverDurability:
    def test_sigkill_rebuild_loses_no_job_and_admits_none_twice(self):
        cluster, ctl1 = _admission_world(quota_jobs=1)
        job_a = admission_job("job-a", namespace="team-r", workers=1,
                              tpu_chips=0)
        job_b = admission_job("job-b", namespace="team-r", workers=1,
                              tpu_chips=0)
        assert ctl1._admission_gate(job_a, []) is True
        assert ctl1._admission_gate(job_b, []) is False
        cond = _queued_cond(job_b)
        assert cond.status == "True"
        assert cond.reason == constants.ADMISSION_QUEUED_REASON
        pods_a = [_bound_pod(ctl1, job_a, "job-a-master-0", "n0",
                             rtype="master")]

        # SIGKILL of the owner: a fresh controller (fresh ledger) sees
        # the same job objects through its informer LIST
        _, ctl2 = _admission_world(quota_jobs=1)
        releases = []
        ctl2.admission.on_release = lambda key, kind: releases.append(
            (key, kind))
        # A rebuilds as already-admitted: no second release event
        assert ctl2._admission_gate(job_a, pods_a) is True
        assert releases == []
        # B rebuilds as waiting: the queued job is not lost...
        assert ctl2._admission_gate(job_b, []) is False
        assert ctl2.admission.is_waiting(job_b.key)
        # ...and a re-offer is idempotent (no duplicate ledger entry)
        assert ctl2._admission_gate(job_b, []) is False
        snap = ctl2.admission.snapshot()
        assert snap["team-r"] == {"admitted_jobs": 1, "chips": 0,
                                  "waiting": 1}
        # quota frees -> B admitted EXACTLY once
        ctl2.admission.note_terminal(job_a.key)
        assert releases == [(job_b.key, "admit")]
        assert ctl2._admission_gate(job_b, []) is True

    def test_rebuild_restores_a_shrunken_victims_grow_claim(self):
        # Queued=True + live pods == elastic preemption victim running
        # at its floor; the new owner must re-charge the floor and
        # reinstate the grow-back entry, not admit at full size
        _, ctl = _admission_world(quota_chips=40)
        # the preemption beneficiary still holds the shed chips, so the
        # rebuilt grow-back entry must wait instead of releasing
        holder = admission_job("holder", namespace="default", workers=3,
                               tpu_chips=4)  # 16 chips
        holder_pods = [_bound_pod(ctl, holder, "holder-master-0", "n0",
                                  rtype="master")]
        assert ctl._admission_gate(holder, holder_pods) is True
        victim = admission_job("victim", namespace="default", workers=8,
                               tpu_chips=4, elastic_min=4)
        status_machine.update_job_conditions(
            victim.status, constants.JOB_QUEUED,
            constants.ADMISSION_PREEMPTED_REASON, "shrunken victim")
        pods = [_bound_pod(ctl, victim, "victim-master-0", "n1",
                           rtype="master")]
        assert ctl._admission_gate(victim, pods) is True
        assert ctl.admission.waiting_kind(victim.key) == KIND_GROW
        assert ctl._admission_grow_allowed(victim) is False
        assert ctl.admission.snapshot()["default"]["chips"] == 16 + 20

    def test_rebuild_restores_a_restart_victims_queue_slot(self):
        # Queued=True + no pods + preempted reason == non-elastic victim
        # awaiting recreation: it re-enters the queue as a restart entry
        def restart_victim():
            victim = admission_job("victim", namespace="default",
                                   workers=2, tpu_chips=4)
            status_machine.update_job_conditions(
                victim.status, constants.JOB_QUEUED,
                constants.ADMISSION_PREEMPTED_REASON,
                "awaiting recreation")
            return victim

        # on an empty queue the rebuilt entry releases immediately —
        # but as a RESTART release, not a fresh admit
        releases = []
        _, ctl = _admission_world(quota_jobs=1)
        ctl.admission.on_release = lambda key, kind: releases.append(kind)
        assert ctl._admission_gate(restart_victim(), []) is True
        assert releases == [KIND_RESTART]

        # under contention it waits in line like any restart entry
        releases2 = []
        _, ctl2 = _admission_world(quota_jobs=1)
        ctl2.admission.on_release = lambda key, kind: releases2.append(kind)
        blocker = admission_job("blocker", namespace="default", workers=1,
                                tpu_chips=0)
        assert ctl2._admission_gate(blocker, []) is True
        victim = restart_victim()
        assert ctl2._admission_gate(victim, []) is False
        assert ctl2.admission.waiting_kind(victim.key) == KIND_RESTART
        ctl2.admission.note_terminal(blocker.key)
        assert releases2[-1] == KIND_RESTART


# ---------------------------------------------------------------------------
# Hostile-tenant simulation e2e (sim/scale.py run_tenancy)
# ---------------------------------------------------------------------------


def _small_tenancy_cfg(**overrides):
    base = dict(namespaces=4, jobs_per_namespace=3, hostile_factor=10,
                quota_jobs=2, cluster_max_jobs=5, workers=1, nodes=10,
                seed=7, arrival_seconds=120.0)
    base.update(overrides)
    return TenancyConfig(**base)


class TestTenancySim:
    def test_small_hostile_tenant_scenario_is_fair(self):
        cfg = _small_tenancy_cfg()
        res = run_tenancy(cfg)
        first = res["runs"][0]
        assert first["converged"] is True
        assert first["succeeded"] == cfg.total_jobs() == 42
        assert res["deterministic"] is True
        assert res["no_tenant_starved"] is True
        assert res["hostile_degraded"] is True
        assert res["compliant_bounded"] is True
        assert res["fair"] is True
        # the flood queued behind its own quota: every compliant tenant
        # both submitted and finished its full load
        for stats in first["per_namespace"].values():
            assert stats["succeeded"] == stats["submitted"] == 3
        assert first["hostile"]["succeeded"] == cfg.hostile_jobs() == 30

    @pytest.mark.slow
    def test_tenancy_tier_fairness_at_scale(self):
        # the run-tests.sh --tenancy tier: a mid-size slice of the
        # committed bench scenario (the full 10k-job verdict lives in
        # BENCH_CONTROL_PLANE.md via bench_control_plane.py --tenancy)
        cfg = _small_tenancy_cfg(namespaces=16, jobs_per_namespace=8,
                                 quota_jobs=4, cluster_max_jobs=32,
                                 nodes=40, arrival_seconds=300.0)
        res = run_tenancy(cfg)
        first = res["runs"][0]
        assert first["succeeded"] == cfg.total_jobs() == 208
        assert res["fair"] is True
        assert first["hostile_wait_p99_s"] >= 2.0 * max(
            first["compliant_wait_p99_max_s"], 0.001)
