"""Flagship perf evidence: Llama training MFU + Pallas kernel comparisons.

Produces the committed artifact ``BENCH_DETAIL.md`` (VERDICT round-1 item 2):

  1. **Llama single-chip training MFU** — a ~0.9B-param Llama config
     (flash attention + fused RMSNorm + per-layer remat, bf16, AdamW)
     trained on one real TPU chip; reports step time, achieved TFLOP/s
     and MFU against the chip's peak bf16 rate.
  2. **Flash vs dense attention** — forward and forward+backward wall
     time at seq 1024 / 4096 for the Pallas kernel
     (ops/flash_attention.py) vs the dense XLA path, same shapes.
  3. **Fused RMSNorm vs XLA** — Pallas kernel (ops/rms_norm.py) vs the
     unfused f32-upcast XLA implementation.

The reference publishes no kernel/MFU numbers (its headline is the
dist-MNIST wall-clock envelope, README.md:37 — covered by bench.py), so
this artifact is the repo's own reproducible flagship evidence.

Run on a TPU host:   python scripts/bench_detail.py --out BENCH_DETAIL.md
Quick smoke (CPU):   python scripts/bench_detail.py --smoke
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Peak dense bf16 TFLOP/s per chip by device_kind substring.  Sources:
# public TPU spec sheets (v5e 197, v4 275, v5p 459, v6e 918).
PEAK_BF16_TFLOPS = {
    "v5 lite": 197.0,
    "v5e": 197.0,
    "v4": 275.0,
    "v5p": 459.0,
    "v5": 459.0,
    "v6 lite": 918.0,
    "v6e": 918.0,
}


def _peak_tflops(device_kind: str) -> float | None:
    dk = device_kind.lower()
    for key, val in sorted(PEAK_BF16_TFLOPS.items(), key=lambda kv: -len(kv[0])):
        if key in dk:
            return val
    return None


def _time_scanned(body, init_carry, iters: int, repeats: int = 3,
                  calibrate: bool = True) -> float:
    """Per-iteration device time of ``body`` (carry -> carry).

    Two-point method: time ONE jitted lax.scan of `iters` chained
    applications and one of `2*iters`, and report (t2 - t1) / iters —
    the fixed per-launch cost (tens of milliseconds through the device
    tunnel: dispatch round-trip + the host fetch that forces
    completion) cancels in the subtraction, so short kernels are not
    inflated by it.  The carry chain stops XLA hoisting loop-invariant
    work, and the summed-scalar return forces completion on fetch
    (block_until_ready does not block through the tunnel).  Best of
    `repeats` rounds filters shared-chip contention."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def make_run(length):
        @jax.jit
        def run(carry):
            out = lax.scan(lambda c, _: (body(c), None), carry, None,
                           length=length)[0]
            return sum(jnp.sum(x.astype(jnp.float32))
                       for x in jax.tree_util.tree_leaves(out))
        return run

    # Auto-calibrate in ONE jump (each distinct scan length is a fresh
    # TPU compile — a doubling search would spend minutes compiling):
    # time the starting length, subtract the cached per-launch overhead,
    # and jump straight to a length whose region is >=0.3s, so the
    # difference (t2 - t1) rises well above launch cost and shared-chip
    # noise (at small iters a <100us kernel measures as 0 or negative).
    run1 = make_run(iters)
    float(run1(init_carry))  # compile + warmup
    t0 = time.perf_counter()
    float(run1(init_carry))
    total = time.perf_counter() - t0
    per_iter = max((total - _launch_overhead()) / iters, 1e-7)
    if calibrate and total < 0.3:
        iters = min(max(int(0.3 / per_iter) + 1, iters), 1 << 16)
        run1 = make_run(iters)
        float(run1(init_carry))
    run2 = make_run(2 * iters)
    float(run2(init_carry))
    # Difference of per-run minima, NOT min over per-round differences:
    # a contention spike inflating one run1 round would otherwise make
    # that round's difference the smallest (possibly negative) and
    # min() would select exactly the corrupted round.
    best1 = best2 = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(run1(init_carry))
        t1 = time.perf_counter()
        float(run2(init_carry))
        t2 = time.perf_counter()
        best1 = min(best1, t1 - t0)
        best2 = min(best2, t2 - t1)
    per_iter = (best2 - best1) / iters
    if per_iter <= 0:
        print(f"[bench_detail] WARNING: non-positive timing "
              f"({per_iter * 1e6:.1f} us/iter) — contention corrupted "
              f"this measurement; reporting NaN", file=sys.stderr)
        return float("nan")
    return per_iter


_LAUNCH_OVERHEAD = None


def _launch_overhead() -> float:
    """Fixed per-launch cost (dispatch round-trip + completion fetch
    through the device tunnel), measured once with a trivial program."""
    global _LAUNCH_OVERHEAD
    if _LAUNCH_OVERHEAD is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def nop(x):
            return jnp.sum(x)

        x = jnp.ones((8, 8), jnp.float32)
        float(nop(x))
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            float(nop(x))
            best = min(best, time.perf_counter() - t0)
        _LAUNCH_OVERHEAD = best
    return _LAUNCH_OVERHEAD


# ---------------------------------------------------------------------------
# 1. Llama training MFU


def bench_llama_mfu(smoke: bool) -> dict:
    import jax.numpy as jnp

    from pytorch_operator_tpu.models import llama

    if smoke:
        cfg = llama.tiny(use_flash=False, use_fused_norm=False, remat=True,
                         dtype=jnp.bfloat16)
        batch, seq = 2, 128
        iters = 2
    else:
        # ~0.9B params on one 16GB v5e chip, bf16 AdamW.  Measured-best
        # single-chip config (2026-07-30 sweep): batch 2 WITHOUT remat
        # beats batch 4 + remat on both MFU (61.9% vs 55.4%) and
        # tokens/s (21.3k vs 19.0k) — activations for B2/T2048 still
        # fit, so paying the remat recompute (~4/3x hardware FLOPs)
        # buys nothing here.  B3+ without remat fails to compile (OOM);
        # multi-chip / longer-seq configs re-enable remat
        # (remat_policy="dots_with_no_batch_dims_saveable" was the best
        # remat variant: 58.0% at B4 — see bench_llama_long_seq).
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
            n_kv_heads=16, ffn_dim=5632, max_seq_len=2048,
            dtype=jnp.bfloat16, remat=False,
            use_flash=True, use_fused_norm=True,
        )
        batch, seq = 2, 2048
        iters = 20
    return _measure_llama_step(cfg, batch, seq, iters)


def _measure_llama_step(cfg, batch: int, seq: int, iters: int,
                        chunked_ce: bool = False) -> dict:
    import jax
    import jax.numpy as jnp  # noqa: F401  (kept: cfg dtypes reference jnp)
    import optax

    from pytorch_operator_tpu.models import llama

    params = llama.init_params(jax.random.key(0), cfg)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))

    opt = optax.adamw(3e-4, weight_decay=0.1)
    opt_state = opt.init(params)
    # (batch, seq+1) so the trained T = seq tiles the Pallas block sizes
    # (flash_attention and rms_norm fall back to dense XLA on ragged T —
    # same convention as examples/llama/train_llama.py).
    tokens = jax.random.randint(jax.random.key(1), (batch, seq + 1), 0,
                                cfg.vocab_size)

    from functools import partial

    from pytorch_operator_tpu.parallel.train import (
        chunked_tied_ce,
        cross_entropy_loss,
    )

    @partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, tokens):
        def loss(p):
            if chunked_ce:
                h = llama.forward_hidden(p, tokens[:, :-1], cfg)
                return chunked_tied_ce(h, p["embed"], tokens[:, 1:], chunk=1024)
            logits = llama.forward(p, tokens[:, :-1], cfg)
            return cross_entropy_loss(logits, tokens[:, 1:])

        l, grads = jax.value_and_grad(loss)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, l

    t0 = time.perf_counter()
    for _i in range(2):
        params, opt_state, l = step(params, opt_state, tokens)
    _ = float(l)
    compile_s = time.perf_counter() - t0

    step_s = float("inf")
    for _round in range(2):  # min-of-2 rounds filters shared-chip noise
        t0 = time.perf_counter()
        for _i in range(iters):
            params, opt_state, l = step(params, opt_state, tokens)
        final_loss = float(l)  # host fetch: forces completion of every step
        step_s = min(step_s, (time.perf_counter() - t0) / iters)

    # FLOP model (train = fwd + bwd = 3x fwd matmul FLOPs):
    #   matmuls: 6 * n_params * tokens   (2 FLOP/MAC * 3x for training)
    #   attention: 12 * L * B * T^2 * D, halved for causal masking (the
    #   flash kernel skips fully-masked key blocks).
    T = seq
    tokens_per_step = batch * T
    matmul_flops = 6.0 * n_params * tokens_per_step
    attn_flops = 12.0 * cfg.n_layers * batch * T * T * cfg.dim * 0.5
    total_flops = matmul_flops + attn_flops

    dev = jax.devices()[0]
    peak = _peak_tflops(dev.device_kind)
    achieved_tflops = total_flops / step_s / 1e12
    return {
        "model": f"Llama d{cfg.dim} L{cfg.n_layers} h{cfg.n_heads} "
                 f"ffn{cfg.ffn_dim} vocab{cfg.vocab_size}",
        "n_params": n_params,
        "batch": batch,
        "seq": seq,
        "device": dev.device_kind,
        "compile_s": round(compile_s, 1),
        "step_ms": round(step_s * 1e3, 2),
        "tokens_per_sec": round(tokens_per_step / step_s, 1),
        "achieved_tflops": round(achieved_tflops, 1),
        "peak_tflops": peak,
        "mfu_pct": round(100 * achieved_tflops / peak, 1) if peak else None,
        "final_loss": round(final_loss, 4),
        "flags": f"use_flash={cfg.use_flash} use_fused_norm={cfg.use_fused_norm} "
                 f"remat={cfg.remat}"
                 + (f"({cfg.remat_policy})" if cfg.remat_policy else "")
                 + (" chunked_ce" if chunked_ce else "")
                 + f" {jnp.dtype(cfg.dtype).name} AdamW",
    }


def bench_llama_long_seq(smoke: bool) -> list[dict]:
    """Long-sequence Llama MFU: the same ~0.9B model trained at T=4096
    and T=8192 on one chip.

    Activations at these lengths no longer fit without remat, so each
    length uses its measured-best policy (2026-07-30 sweeps).  At
    16k/32k that is the attention-preserving policy
    (remat_policy="save_attn": keep each layer's flash (out, lse) pair,
    recompute projections/MLP — the flash forward is dead code in the
    remat backward) plus the chunked tied-head CE
    (parallel.train.chunked_tied_ce), which removes the two logits-
    sized f32 scatter-add buffers that otherwise OOM the 32k config.
    Together with section 4 (flash at 16k/32k) this is the single-chip
    long-context story; ring/Ulysses SP extend it across a mesh.
    """
    import jax.numpy as jnp

    from pytorch_operator_tpu.models import llama

    if smoke:
        cfg = llama.tiny(use_flash=False, use_fused_norm=False, remat=True,
                         remat_policy="dots_with_no_batch_dims_saveable",
                         dtype=jnp.bfloat16)
        return [_measure_llama_step(cfg, 1, 128, 2)]
    rows = []
    # Per-length measured-best batch + remat policy (2026-07-30/31
    # sweeps): dots_with_no_batch_dims_saveable (save matmul outputs)
    # is fastest while its saved activations fit — B2 beats B1 at
    # T=4096 (58.8% vs 55.2% MFU) and beats save_attn B4 (57.0%).  At
    # 16k/32k the dots policy's compile blows the tunnel
    # compile-helper's memory (HTTP 500, reproducible); round 3 fell
    # back to FULL remat there (46.9%/42.7%).  Round 4's save_attn +
    # chunked CE replaced that (16k B2 52.3%, 32k B1 47.8%).  Round 5
    # added the composite save tiers (llama.LAYER_SAVE_GROUPS +
    # auto_remat_policy): at 16k the measured-best that COMPILES on
    # this tunnel is B1 save_attn+qkv (53.1%, also slightly more
    # tokens/s than B2 save_attn); every richer tier (+gateup, +normed
    # at B2, qkv+normed) hits the same compile-helper ceiling as dots,
    # and host-offload of the SwiGLU branches compiles but runs 34.5%
    # (tunnel host bandwidth).  At 32k nothing beyond save_attn
    # compiles here.  On hardware with a local compiler the auto
    # policy picks the richer tiers that this tunnel cannot build.
    for batch, seq, iters, policy, chunked in (
            (2, 4096, 6, "dots_with_no_batch_dims_saveable", False),
            (1, 8192, 5, "dots_with_no_batch_dims_saveable", False),
            (1, 16384, 3, "save_attn+qkv", True),
            (1, 32768, 2, "save_attn", True)):
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
            n_kv_heads=16, ffn_dim=5632, max_seq_len=seq,
            dtype=jnp.bfloat16, remat=True, remat_policy=policy,
            use_flash=True, use_fused_norm=True,
        )
        rows.append(_measure_llama_step(cfg, batch, seq, iters,
                                        chunked_ce=chunked))
    return rows


# ---------------------------------------------------------------------------
# 2. Flash vs dense attention


def bench_flash_vs_dense(smoke: bool) -> list[dict]:
    import jax
    import jax.numpy as jnp

    from pytorch_operator_tpu.ops import flash_attention
    from pytorch_operator_tpu.ops.flash_attention import _dense_reference

    def dense(q, k, v):
        # the exact dense XLA path flash_attention falls back to
        B, T, H, D = q.shape
        q2, k2, v2 = (x.transpose(0, 2, 1, 3).reshape(B * H, T, D)
                      for x in (q, k, v))
        out = _dense_reference(q2, k2, v2, D ** -0.5, True)
        return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    def _normed(x):
        # rescale to unit RMS so the carry chain neither decays nor blows
        # up over the scan; identical cost on every timed variant
        xf = x.astype(jnp.float32)
        return (xf * jax.lax.rsqrt(jnp.mean(xf * xf) + 1e-6)).astype(x.dtype)

    seqs = [256] if smoke else [1024, 4096]
    B, H, D = 1, 16, 128
    rows = []
    for T in seqs:
        q, k, v = (jax.random.normal(jax.random.key(i), (B, T, H, D),
                                     jnp.bfloat16) for i in range(3))

        def fwd_body(fn):
            # chain q through the output so each scan iteration depends
            # on the last (no loop-invariant hoisting)
            return lambda qc: _normed(fn(qc, k, v))

        def bwd_body(fn):
            # sum-of-squares: a NONLINEAR functional of the output, so
            # XLA cannot push the reduction through the matmuls and skip
            # the attention (a plain sum() lets it — measured fwd+bwd
            # came out faster than fwd).  The carry mixes all three
            # grads so none of dq/dk/dv is dead code.
            def loss(q, k, v):
                o = fn(q, k, v).astype(jnp.float32)
                return jnp.sum(o * o)

            grad_fn = jax.grad(loss, argnums=(0, 1, 2))

            def body(qc):
                dq, dk, dv = grad_fn(qc, k, v)
                return _normed(dq + dk + dv)

            return body

        flash = lambda q, k, v: flash_attention(q, k, v, causal=True)  # noqa: E731
        # scale iterations inversely with T² so every scan runs long
        # enough (hundreds of ms) to rise above shared-chip noise
        iters = 2 if smoke else max(50, (4096 // T) ** 2 * 50)
        t_ff = _time_scanned(fwd_body(flash), q, iters, repeats=3,
                             calibrate=not smoke)
        t_df = _time_scanned(fwd_body(dense), q, iters, repeats=3,
                             calibrate=not smoke)
        t_fg = _time_scanned(bwd_body(flash), q, iters, repeats=3,
                             calibrate=not smoke)
        t_dg = _time_scanned(bwd_body(dense), q, iters, repeats=3,
                             calibrate=not smoke)
        rows.append({
            "shape": f"B{B} T{T} H{H} D{D} bf16 causal",
            "fwd_flash_ms": round(t_ff * 1e3, 3),
            "fwd_dense_ms": round(t_df * 1e3, 3),
            "fwd_speedup": round(t_df / t_ff, 2),
            "fwdbwd_flash_ms": round(t_fg * 1e3, 3),
            "fwdbwd_dense_ms": round(t_dg * 1e3, 3),
            "fwdbwd_speedup": round(t_dg / t_fg, 2),
        })
    return rows


def bench_gqa(smoke: bool) -> list[dict]:
    """GQA-native flash vs the repeat-KV formulation.

    The kernel streams shared K/V blocks via the b//group index map
    (ops/flash_attention.py) instead of materialising K/V at H heads —
    1/group the k/v HBM read traffic.  The baseline repeats K/V
    explicitly and runs the same kernel (both are exact)."""
    import jax
    import jax.numpy as jnp

    from pytorch_operator_tpu.ops import flash_attention

    if smoke:
        shapes = [(256, 4, 2)]
    else:
        shapes = [(4096, 16, 4), (4096, 16, 8)]
    B, D = 1, 128 if not smoke else 32
    rows = []
    for T, H, G in shapes:
        ks = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(ks[0], (B, T, H, D), jnp.bfloat16)
        k = jax.random.normal(ks[1], (B, T, H // G, D), jnp.bfloat16)
        v = jax.random.normal(ks[2], (B, T, H // G, D), jnp.bfloat16)

        def native(qq, kk, vv):
            return flash_attention(qq, kk, vv, causal=True)

        def repeat(qq, kk, vv):
            return flash_attention(qq, jnp.repeat(kk, G, axis=2),
                                   jnp.repeat(vv, G, axis=2), causal=True)

        def _normed(x):
            xf = x.astype(jnp.float32)
            return (xf * jax.lax.rsqrt(jnp.mean(xf * xf) + 1e-6)
                    ).astype(x.dtype)

        def fwd_body(fn):
            return lambda qc: _normed(fn(qc, k, v))

        def bwd_body(fn):
            def loss(qq, kk, vv):
                o = fn(qq, kk, vv).astype(jnp.float32)
                return jnp.sum(o * o)

            grad_fn = jax.grad(loss, argnums=(0, 1, 2))

            def body(qc):
                dq, dk, dv = grad_fn(qc, k, v)
                s = (jnp.sum(dk.astype(jnp.float32) ** 2)
                     + jnp.sum(dv.astype(jnp.float32) ** 2))
                return _normed(dq.astype(jnp.float32) + s).astype(qc.dtype)

            return body

        iters = 2 if smoke else 40
        t_nf = _time_scanned(fwd_body(native), q, iters, repeats=3,
                             calibrate=not smoke)
        t_rf = _time_scanned(fwd_body(repeat), q, iters, repeats=3,
                             calibrate=not smoke)
        t_nb = _time_scanned(bwd_body(native), q, iters, repeats=3,
                             calibrate=not smoke)
        t_rb = _time_scanned(bwd_body(repeat), q, iters, repeats=3,
                             calibrate=not smoke)
        rows.append({
            "shape": f"B{B} T{T} H{H}/kv{H // G} D{D} bf16 causal",
            "fwd_native_ms": round(t_nf * 1e3, 3),
            "fwd_repeat_ms": round(t_rf * 1e3, 3),
            "fwd_speedup": round(t_rf / t_nf, 2),
            "fwdbwd_native_ms": round(t_nb * 1e3, 3),
            "fwdbwd_repeat_ms": round(t_rb * 1e3, 3),
            "fwdbwd_speedup": round(t_rb / t_nb, 2),
        })
    return rows


# ---------------------------------------------------------------------------
# 3. Fused RMSNorm vs XLA


def bench_rms_norm(smoke: bool) -> list[dict]:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from pytorch_operator_tpu.ops import rms_norm as rms_dispatch
    from pytorch_operator_tpu.ops.rms_norm import _rms

    def kernel_rms(x, w):
        # raw Pallas kernel, bypassing the dispatcher's VMEM/ragged
        # fallbacks — this row must measure the kernel itself
        import jax as _jax

        return _rms(x, w, 1e-5, 128, _jax.default_backend() != "tpu")

    def xla_rms(x, w):
        xf = x.astype(jnp.float32)
        inv = lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + 1e-5)
        return (xf * inv * w.astype(jnp.float32)).astype(x.dtype)

    shapes = [(256, 128)] if smoke else [(8192, 2048), (16384, 4096)]
    rows = []
    for N, D in shapes:
        x = jax.random.normal(jax.random.key(0), (N, D), jnp.bfloat16)
        w = jnp.full((D,), 1.5, jnp.bfloat16)  # != 1 so the scan has a fixpoint-free chain
        iters = 2 if smoke else 200
        # chain x through the output: rms_norm output feeds the next
        # iteration, so the scan can't hoist the computation
        fused = rms_dispatch if smoke else kernel_rms
        t_f = _time_scanned(lambda xc: fused(xc, w), x, iters,
                            repeats=3, calibrate=not smoke)
        t_p = _time_scanned(lambda xc: xla_rms(xc, w), x, iters, repeats=3,
                            calibrate=not smoke)
        rows.append({
            "shape": f"({N}, {D}) bf16",
            "fused_us": round(t_f * 1e6, 1),
            "xla_us": round(t_p * 1e6, 1),
            "speedup": round(t_p / t_f, 2),
        })
    return rows


# ---------------------------------------------------------------------------
# 4. Long-context: flash at sequence lengths dense attention cannot hold


def bench_long_context(smoke: bool) -> list[dict]:
    """Flash fwd+bwd at 16k/32k tokens on one chip.

    At these lengths the dense path is not slower — it is impossible:
    the f32 score matrix alone (B*H*T^2*4 bytes) exceeds the chip's
    entire HBM.  The flash kernel's O(T) memory makes single-chip
    long-context training real; ring/ulysses SP extend the same kernel
    across the mesh (parallel/ring_attention.py, parallel/ulysses.py).
    """
    import jax
    import jax.numpy as jnp

    from pytorch_operator_tpu.ops.flash_attention import _auto_block, _flash

    shapes = [(128, 2)] if smoke else [(16384, 8), (32768, 8)]
    rows = []
    for T, H in shapes:
        B, D = 1, 128 if not smoke else 8
        block = _auto_block(T, D)
        scale = D ** -0.5

        def attn(a, b, c, block=block, scale=scale):
            return _flash(a, b, c, scale, True, block, block,
                          jax.default_backend() != "tpu")

        iters = 2 if smoke else max(12, (32768 // T) * 12)
        t = _time_attn_fwdbwd(attn, (B * H, T, D), iters, smoke)
        rows.append({
            "shape": f"B{B} T{T} H{H} D{D} bf16 causal",
            "fwdbwd_flash_ms": round(t * 1e3, 1),
            "attn_tokens_per_sec": round(B * T / t, 0),
            "dense_scores_gib": round(B * H * T * T * 4 / 2 ** 30, 1),
        })
    rows += _bench_tail_lengths(smoke)
    return rows


def _time_attn_fwdbwd(attn_fn, shape, iters: int, smoke: bool) -> float:
    """Seconds/iter for fwd+bwd of ``attn_fn`` over a scan-chained vjp.

    Single-run timing with launch-cost subtraction (no two-point second
    compile — these are feasibility headlines, not A/Bs): the region is
    >=1s so the launch cost is a few percent even before subtraction.
    Shared by the long-context and padded-tail rows so the two cannot
    drift onto different methodologies.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, shape, jnp.bfloat16) for kk in ks)

    def _normed(x):
        return (x / jnp.sqrt(jnp.mean(x.astype(jnp.float32) ** 2,
                                      keepdims=True) + 1e-6)).astype(x.dtype)

    def body(c):
        qc, kc, vc = c
        out, vjp = jax.vjp(attn_fn, qc, kc, vc)
        dq, dk, dv = vjp(out)
        return (_normed(dq), _normed(dk), _normed(dv))

    @jax.jit
    def _run(c):
        out = lax.scan(lambda cc, _: (body(cc), None), c, None,
                       length=iters)[0]
        return sum(jnp.sum(x.astype(jnp.float32))
                   for x in jax.tree_util.tree_leaves(out))

    float(_run((q, k, v)))  # compile + warmup
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(_run((q, k, v)))
        best = min(best, time.perf_counter() - t0)
    return max((best - (_launch_overhead() if not smoke else 0.0))
               / iters, 1e-9)


def _bench_tail_lengths(smoke: bool) -> list[dict]:
    """Non-block-multiple lengths through the public flash_attention API.

    Round-3 verdict item 1: arbitrary T must run at flash speed (the
    old dense fallback at 16k-scale non-multiples would OOM outright).
    The padded-tail kernels round T up to the next block multiple and
    mask in-kernel, so e.g. T=16411 costs about the same as T=17408
    (the padded length) — flash speed, not dense impossibility.
    """
    from pytorch_operator_tpu.ops import flash_attention

    shapes = [(100, 2)] if smoke else [(16411, 8)]
    rows = []
    for T, H in shapes:
        B, D = 1, 128 if not smoke else 8

        def attn(a, b, c):
            return flash_attention(a, b, c, causal=True)

        iters = 2 if smoke else 24
        t = _time_attn_fwdbwd(attn, (B, T, H, D), iters, smoke)
        rows.append({
            "shape": f"B{B} T{T} H{H} D{D} bf16 causal (non-multiple tail)",
            "fwdbwd_flash_ms": round(t * 1e3, 1),
            "attn_tokens_per_sec": round(B * T / t, 0),
            "dense_scores_gib": round(B * H * T * T * 4 / 2 ** 30, 1),
        })
    return rows



# ---------------------------------------------------------------------------


def render_md(mfu: dict, flash: list[dict], norm: list[dict],
              longctx: list[dict], longseq: list[dict],
              gqa: list[dict]) -> str:
    now = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M UTC")
    lines = [
        "# BENCH_DETAIL — flagship perf evidence",
        "",
        f"Generated {now} by `python scripts/bench_detail.py` on "
        f"`{mfu['device']}` (single chip).  Reproduce with the same "
        "command; `--smoke` runs tiny shapes anywhere.",
        "",
        "## 1. Llama single-chip training MFU",
        "",
        f"* model: {mfu['model']} — {mfu['n_params']/1e6:.0f}M params",
        f"* batch {mfu['batch']} x seq {mfu['seq']}, {mfu['flags']}",
        f"* step time: **{mfu['step_ms']} ms** "
        f"({mfu['tokens_per_sec']:.0f} tokens/s/chip); "
        f"compile+warmup {mfu['compile_s']}s; final loss {mfu['final_loss']}",
        (f"* achieved **{mfu['achieved_tflops']} TFLOP/s** vs "
         f"{mfu['peak_tflops']} peak bf16 -> **MFU {mfu['mfu_pct']}%**"
         if mfu["peak_tflops"] else
         f"* achieved **{mfu['achieved_tflops']} TFLOP/s** "
         f"(no peak-bf16 entry for `{mfu['device']}`; MFU not computed)"),
        "",
        "FLOP accounting: 6·N·tokens matmul + causal-halved 12·L·B·T²·D "
        "attention (see script).  The reference publishes no MFU/kernel "
        "numbers (its headline is the dist-MNIST envelope — bench.py), so "
        "this is the repo's own flagship baseline to beat in later rounds.",
        "",
        "### 1b. Long-sequence training MFU (same model, remat on)",
        "",
        "| batch x seq | step ms | tokens/s/chip | TFLOP/s | MFU | flags |",
        "|---|---|---|---|---|---|",
    ] + [
        (f"| {r['batch']} x {r['seq']} | {r['step_ms']} | "
         f"{r['tokens_per_sec']:.0f} | {r['achieved_tflops']} | "
         f"**{r['mfu_pct']}%** | {r['flags']} |")
        for r in longseq
    ] + [
        "",
        "Activations at these lengths exceed HBM without "
        "rematerialisation.  4k/8k use the measured-best policy "
        "(dots_with_no_batch_dims_saveable: keep matmul outputs, "
        "recompute elementwise, ~4/3x hardware FLOPs).  16k/32k use "
        "the attention-preserving save_attn family (keep each layer's "
        "flash (out, lse) pair via checkpoint_name; the remat backward "
        "recomputes projections/MLP but the O(T^2) flash forward is "
        "dead code — jaxpr-verified by "
        "tests/test_models.py::test_save_attn_remat_skips_flash_recompute) "
        "plus the chunked tied-head CE (parallel.train.chunked_tied_ce) "
        "that removes the two logits-sized f32 scatter-add buffers "
        "which otherwise OOM the 32k step.",
        "",
        "Round 5 added composite tiers above save_attn — "
        "`save_attn+qkv`, `+gateup`, `+normed` (llama.LAYER_SAVE_GROUPS: "
        "post-RoPE projections, SwiGLU branches, norm outputs), picked "
        "batch-adaptively from HBM-headroom math by "
        "`llama.auto_remat_policy` (grads exactness + strictly-fewer-"
        "backward-dots jaxpr-verified by "
        "test_composite_save_tiers_exact_and_fewer_recomputes).  "
        "Measured on this tunnel (2026-07-31 sweep): B1 16k "
        "`save_attn+qkv` 53.1% replaces B2 `save_attn` 52.3% as the 16k "
        "row (more tokens/s too).  The richer tiers that the headroom "
        "math admits — `+gateup` at B1 16k, `+normed` at B2 16k or B1 "
        "32k, `qkv+normed` — all hit the remote compile-helper's memory "
        "ceiling (HTTP 500, the same environment limit that blocks dots "
        "policies at 16k+; the chip's HBM is not the constraint), and "
        "offloading the SwiGLU branches to pinned host compiles but "
        "runs 34.5% MFU (tunnel host bandwidth) — so 53.1/47.8 is the "
        "measured ceiling HERE, while on hardware with a local XLA "
        "compile the auto policy selects the richer tiers this tunnel "
        "cannot build.  MFU counts only useful (non-recompute) FLOPs, "
        "so the remaining remat tax shows up honestly as lower MFU "
        "than section 1's no-remat number.",
        "",
        "### 1c. SP×FSDP: per-chip memory math for the Llama-2-7B "
        "v5p-128 north star",
        "",
        "The composed layout (round 5: `make_sp_mesh(dp, sp, fsdp=n)` + "
        "`llama.sp_fsdp_param_specs` + `make_sp_train_step`) is what "
        "makes BASELINE.md config 5 — Llama-2-7B FSDP on a v5p-128 "
        "slice — expressible.  The per-chip arithmetic for the "
        "6.74B-param model on 128 chips laid out as **fsdp=16 × sp=8** "
        "(dp=1), bf16 params with f32 AdamW moments:",
        "",
        "| resident per chip | unsharded | /fsdp=16 |",
        "|---|---|---|",
        "| params (bf16, 2 B/param) | 13.5 GB | **0.84 GB** |",
        "| AdamW mu+nu (f32, 8 B/param) | 53.9 GB | **3.37 GB** |",
        "| grads (bf16, transient reduce-scatter) | 13.5 GB | "
        "**0.84 GB** |",
        "| **total state** | **80.9 GB** (≫ 1 chip) | **5.1 GB** of "
        "95 GB HBM |",
        "",
        "Activations at T=32k, B=16, d4096, L32 with the save_attn "
        "policy: the saved per-layer flash (out, lse) pair is "
        "`B·T·D·2 + B·H·T·4` ≈ 4.36 GB/layer, so ~139 GB across 32 "
        "layers unsharded — but the batch shards over fsdp (16×) and "
        "the sequence over sp (8×), leaving ~1.1 GB of saved residuals "
        "plus the live layer's working set — inside HBM with room for "
        "the chunked-CE transient (~0.3 GB at chunk 1024).  The same "
        "step on replicated params (`sp_param_specs`, the only SP "
        "layout rounds 1–4 had) needs 67.4 GB of param+optimizer state "
        "per chip and cannot fit.",
        "",
        "Proven (CPU 8-device mesh — tests/test_parallel.py::"
        "TestSpFsdp): composed (dp, fsdp, sp) two-step loss/grad-norm "
        "equivalence vs the dense and sp-only paths, AdamW mu/nu "
        "sharding asserted, graceful fsdp-axis drop for non-dividing "
        "batches; driver-visible in the round-5 multichip dryrun "
        "(`__graft_entry__.dryrun_multichip` prints `[dryrun] Llama SP "
        "x FSDP train step ok` — fsdp=2 × sp=4, GQA, flash + "
        "save_attn + chunked CE).",
        "",
        "## 2. Flash attention (Pallas) vs dense XLA",
        "",
        "| shape | fwd flash | fwd dense | fwd speedup | fwd+bwd flash | fwd+bwd dense | fwd+bwd speedup |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in flash:
        lines.append(
            f"| {r['shape']} | {r['fwd_flash_ms']} ms | {r['fwd_dense_ms']} ms "
            f"| **{r['fwd_speedup']}x** | {r['fwdbwd_flash_ms']} ms | "
            f"{r['fwdbwd_dense_ms']} ms | **{r['fwdbwd_speedup']}x** |")
    lines += [
        "",
        "Backward is the FUSED single-pass Pallas kernel "
        "(ops/flash_attention.py): dk/dv in scratch plus dq accumulated "
        "in a VMEM-resident f32 block, so p^T/dp^T are recomputed once "
        "per tile (the FA-2 5-matmul minimum) — O(T) memory, no (T,T) "
        "buffer.  Sequences whose dq exceeds the 4MB VMEM budget "
        "(T>8192 at D=128) take the two-kernel fallback.",
        "",
        "Timing: two-point jitted lax.scan chains (the region auto-grows "
        "to >=0.3s and the fixed per-launch tunnel cost cancels in the "
        "subtraction), best of 3 rounds on a shared chip.  Flash blocks "
        "auto-tune per shape (ops/flash_attention._auto_block; 512 at "
        "T<=1024, else 1024 at D<=128 — fused-backward sweep "
        "2026-07-30; the tuning objective is fwd+bwd, i.e. training).  "
        "At seq 1024 the (T,T) buffer fits XLA's fused softmax pipeline "
        "and raw dense wins the pure forward; round 5 made the public "
        "entry route that case automatically (_route_small_t, a "
        "jax.custom_vjp whose primal is dense and whose differentiated "
        "path is flash — T<=1024, default blocks, no caller knobs).  "
        "The T=1024 fwd row is therefore measured THROUGH the public "
        "entry as dense-vs-dense — parity by construction; its printed "
        "ratio is shared-chip noise around 1.0x (five 2026-07-31 "
        "sessions: 0.88–1.11x, median 1.0x; the pre-dispatch kernel "
        "read 0.72x) — while fwd+bwd keeps the flash kernels.  The "
        "flash win grows with T^2 alongside the O(T)-memory advantage.",
        "",
        "### 2b. GQA-native streaming vs repeat-KV (same kernel)",
        "",
        "| shape | fwd native | fwd repeat | speedup | fwd+bwd native "
        "| fwd+bwd repeat | speedup |",
        "|---|---|---|---|---|---|---|",
    ] + [
        (f"| {r['shape']} | {r['fwd_native_ms']} ms | "
         f"{r['fwd_repeat_ms']} ms | **{r['fwd_speedup']}x** | "
         f"{r['fwdbwd_native_ms']} ms | {r['fwdbwd_repeat_ms']} ms | "
         f"**{r['fwdbwd_speedup']}x** |")
        for r in gqa
    ] + [
        "",
        "Grouped-query K/V streams through the kernel's b//group block "
        "index map (1/group the k/v HBM reads, no repeated K/V "
        "materialised); dk/dv return at the kv head count.  Honest "
        "reading of the ~1.0x wall times: the kernel is MXU-bound at "
        "these shapes and K/V DMA overlaps compute entirely, so the "
        "saved bandwidth does not show up as speed here — the wins are "
        "HBM capacity (no H-head K/V ever exists) and wire traffic "
        "where K/V actually moves: the ring rotates unrepeated chunks "
        "(ICI bytes / group) and ulysses shards kv heads through its "
        "all-to-all (parallel/).",
        "",
        "## 3. Fused RMSNorm (Pallas) vs XLA",
        "",
        "| shape | fused | XLA | speedup |",
        "|---|---|---|---|",
    ]
    for r in norm:
        lines.append(f"| {r['shape']} | {r['fused_us']} us | {r['xla_us']} us "
                     f"| **{r['speedup']}x** |")
    lines += [
        "",
        "Standalone-forward, XLA's fused elementwise pipeline is at "
        "the HBM roofline and the raw kernel does not beat it (the "
        "rows above call the raw kernel directly).  The dispatcher "
        "(ops/rms_norm.py) therefore routes wide rows (D>2048) to the "
        "XLA path, plus ragged rows and >~12MB-VMEM shapes.  The "
        "kernel is d<=2048-only by design: a round-4 sweep of row "
        "blocks {8..256} at D=4096/8192 plateaus at ~0.45x XLA (a "
        "row's mean needs the whole row in VMEM, capping minor-dim "
        "pipelining), and a two-pass variant would read x twice from "
        "HBM in a bandwidth-bound op — it cannot reach 1.0x even in "
        "principle.  In-model the kernel "
        "still wins where dispatched: the measured-best Llama step is "
        "~10% faster with use_fused_norm=True (190.8 vs 212.9 ms at "
        "B2/T2048 d2048, 2026-07-30) because the custom VJP's analytic "
        "backward avoids the f32 intermediates XLA materializes "
        "through the norm in the backward pass — enforced by the "
        "tests/test_perf_fused_norm.py regression guard, which asserts "
        "the win itself (round 5): two-point scan-chained interleaved "
        "A/B on the real chip, fused median ≤ 1.0× unfused, with a "
        "contention re-measure and raw series on failure.",
        "",
        "## 4. Long context: flash at lengths dense attention cannot hold",
        "",
        "| shape | fwd+bwd flash | attn tokens/s | dense f32 scores would need |",
        "|---|---|---|---|",
    ]
    for r in longctx:
        tok = r['attn_tokens_per_sec']
        tok_s = "n/a" if tok != tok else str(int(tok))  # NaN-safe
        lines.append(
            f"| {r['shape']} | {r['fwdbwd_flash_ms']} ms "
            f"| {tok_s} "
            f"| **{r['dense_scores_gib']} GiB** |")
    lines += [
        "",
        "At 32k tokens the dense score matrix alone is 2x the chip's "
        "entire 16GB HBM — dense attention is not merely slower here, "
        "it cannot run.  The flash kernel's O(T) memory makes "
        "single-chip long-context training real; ring/ulysses sequence "
        "parallelism extend the same kernel across a mesh "
        "(parallel/ring_attention.py, parallel/ulysses.py).  The "
        "non-multiple row goes through the padded-tail kernels "
        "(round-4: any TRAINING call at any T >= 1 takes the Pallas "
        "path; the only dense routing left is the round-5 "
        "forward-only T<=1024 dispatcher, where dense measurably "
        "wins) — per-token throughput lands within pad overhead of "
        "the neighbouring block-multiple row, where the old dense "
        "fallback could not have run at all.",

        "",
        "## Raw JSON",
        "",
        "```json",
        json.dumps({"mfu": mfu, "long_seq": longseq, "flash": flash,
                    "gqa": gqa, "rms_norm": norm,
                    "long_context": longctx}, indent=2),
        "```",
        "",
    ]
    return "\n".join(lines)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="write BENCH_DETAIL.md here (default: stdout only)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, any backend (CI sanity check)")
    ap.add_argument("--isolate", action="store_true",
                    help="run each section in a fresh subprocess with one "
                         "retry — a TPU worker crash (shared chips restart "
                         "under other tenants) then costs one section "
                         "attempt instead of the whole run")
    ap.add_argument("--section", choices=list(SECTIONS),
                    help="(internal) run one section, print its JSON")
    args = ap.parse_args()

    if args.isolate:
        _run_isolated(args)
        return

    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    print(f"[bench_detail] device: {jax.devices()[0].device_kind}",
          file=sys.stderr)

    if args.section:
        print(json.dumps({args.section: SECTIONS[args.section](args.smoke)}))
        return

    results = {}
    for i, (name, fn) in enumerate(SECTIONS.items(), 1):
        print(f"[bench_detail] {i}/{len(SECTIONS)} {name}...",
              file=sys.stderr)
        results[name] = fn(args.smoke)
        print(f"[bench_detail]   {results[name]}", file=sys.stderr)
    _emit(results, args.out)


SECTIONS = {
    "mfu": bench_llama_mfu,
    "long_seq": bench_llama_long_seq,
    "flash": bench_flash_vs_dense,
    "gqa": bench_gqa,
    "rms_norm": bench_rms_norm,
    "long_context": bench_long_context,
}


def _emit(results: dict, out: str | None) -> None:
    md = render_md(results["mfu"], results["flash"], results["rms_norm"],
                   results["long_context"], results["long_seq"],
                   results["gqa"])
    if out:
        with open(out, "w") as f:
            f.write(md)
        print(f"[bench_detail] wrote {out}", file=sys.stderr)
    print(json.dumps(results))


def _run_isolated(args) -> None:
    import subprocess

    results = {}
    for i, name in enumerate(SECTIONS, 1):
        for attempt in (1, 2):
            print(f"[bench_detail] {i}/{len(SECTIONS)} {name} "
                  f"(isolated, attempt {attempt})...", file=sys.stderr)
            cmd = [sys.executable, os.path.abspath(__file__),
                   "--section", name]
            if args.smoke:
                cmd.append("--smoke")
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=480)
            if proc.returncode == 0:
                try:
                    results.update(json.loads(proc.stdout.strip()
                                              .splitlines()[-1]))
                    break
                except (ValueError, IndexError):
                    pass
            print(f"[bench_detail]   attempt {attempt} failed "
                  f"(rc={proc.returncode}): {proc.stderr[-300:]}",
                  file=sys.stderr)
        else:
            raise SystemExit(f"section {name} failed twice")
        print(f"[bench_detail]   {results[name]}", file=sys.stderr)
    _emit(results, args.out)


if __name__ == "__main__":
    main()
