"""Init-container configuration.

Equivalent of the reference's pkg/common/config/config.go:9-34: the worker
pods get an init container that blocks until the master's headless-service
DNS resolves, acting as a startup-ordering barrier before the rendezvous.
The template can be overridden by a config file
(/etc/config/initContainer.yaml in-cluster).
"""

from __future__ import annotations

import functools
import os
import string
from typing import List, Optional

import yaml

INIT_CONTAINER_TEMPLATE_FILE = "/etc/config/initContainer.yaml"

# ${masterAddr} / ${initContainerImage} are substituted at pod-build time.
DEFAULT_INIT_CONTAINER_TEMPLATE = """
- name: init-pytorch
  image: ${initContainerImage}
  command: ['sh', '-c', 'until nslookup ${masterAddr}; do echo waiting for master; sleep 2; done;']
  resources:
    limits:
      cpu: 100m
      memory: 20Mi
    requests:
      cpu: 50m
      memory: 10Mi
"""


def get_init_container_template(config_path: Optional[str] = None) -> str:
    path = config_path or INIT_CONTAINER_TEMPLATE_FILE
    if os.path.isfile(path):
        with open(path) as f:
            return f.read()
    return DEFAULT_INIT_CONTAINER_TEMPLATE


@functools.lru_cache(maxsize=1)
def _parsed_default_template():
    """The DEFAULT template parsed once, placeholders in place —
    rendering used to pay one full YAML parse per worker-pod build,
    which the kubemark profile showed as a top-five control-plane cost
    at 50k pods.  Only the shipped default takes this path: its shape
    is known (placeholders appear solely inside string VALUES), so a
    structural walk substituting strings is exactly equivalent to
    substitute-then-parse.  Custom templates keep the original
    per-call path — their placeholders may sit in mapping keys, splice
    YAML structure, or rely on post-substitution scalar coercion."""
    return yaml.safe_load(DEFAULT_INIT_CONTAINER_TEMPLATE) or []


def render_init_containers(
    master_addr: str, init_container_image: str, template: Optional[str] = None
) -> List[dict]:
    """Render the template into container dicts (util.go:60-78)."""
    raw = template or get_init_container_template()
    mapping = {"masterAddr": master_addr,
               "initContainerImage": init_container_image}
    if raw != DEFAULT_INIT_CONTAINER_TEMPLATE:
        rendered = string.Template(raw).substitute(mapping)
        return yaml.safe_load(rendered) or []

    def subst(v):
        if isinstance(v, str):
            return string.Template(v).substitute(mapping)
        if isinstance(v, dict):
            return {k: subst(x) for k, x in v.items()}
        if isinstance(v, list):
            return [subst(x) for x in v]
        return v

    return subst(_parsed_default_template())
