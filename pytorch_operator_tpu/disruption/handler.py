"""Controller-side disruption policy: one detection -> one gang restart,
or — for elastic jobs — one checkpoint-drain-resize.

Mixed into PyTorchController.  The watcher (and the pod informer's
``DisruptionTarget`` hook) note disruptions into a pending map keyed by
job; the next sync of that job consumes the note and — for gang jobs —
performs ONE proactive gang restart: every replica pod deleted through
the bounded ``delete_many`` fan-out with deletion expectations raised
up-front, a ``Restarting`` condition with reason ``TPUPreempted``, a
warning event, and the per-job preemption budget
(``status.preemptionRestarts`` vs ``--max-preemption-restarts`` or the
per-job annotation) decremented.  Jobs that opted out, non-gang jobs,
and jobs over budget fall through to the legacy per-pod failure path
unchanged.

Elastic extension (jobs carrying ``spec.elasticPolicy``): when the
disruption dooms a strict subset of the gang's workers and the
survivors stay at/above ``minReplicas``, the handler runs the
checkpoint-drain-resize path instead of the full restart:

  1. **drain** — the doomed pods are signalled to checkpoint (the
     ``checkpoint-requested`` annotation; the kubelet delivers SIGTERM
     alongside, and the sim's fake kubelet answers the annotation),
     ``status.desiredReplicas`` drops to the surviving worker count and
     the ``Resizing`` condition carries ``ShrinkOnPreemption``;
  2. **shrink** — once every doomed pod acked (``checkpointed``) or the
     bounded drain deadline passed, ONLY the doomed pods are deleted
     (deletion expectations up-front, so rebalance never double-creates)
     and the surviving gang keeps reconciling at the reduced size with
     its rendezvous re-rendered (elastic annotations, tpu_env);
  3. **grow** — the capacity watcher wakes shrunken jobs when
     schedulable TPU nodes return; desiredReplicas climbs back toward
     the configured count (``Resizing``/``GrowOnCapacity``) and the
     normal index reconcile recreates the missing workers.

The shrink budget (``status.elasticResizes`` vs ``--max-elastic-resizes``
or the per-job annotation) parallels the preemption-restart budget; an
exhausted budget falls back to the legacy full-gang restart.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..analysis.witness import make_lock
from ..api.v1 import constants
from ..api.v1.types import PyTorchJob
from ..k8s.errors import NotFoundError
from ..runtime.expectations import expectation_pods_key
from ..runtime.informer import meta_namespace_key
from ..runtime.job_controller import _controller_ref_of
from ..runtime.logger import logger_for_job
from ..runtime.recorder import EVENT_TYPE_NORMAL, EVENT_TYPE_WARNING
from .detector import node_schedulable_tpu, pod_disruption_reason
from .watcher import (CapacityWatcher, DisruptionWatcher, PodNodeIndex,
                      PodNodeIndexUnion)


class DisruptionHandlingMixin:
    def init_disruption_handling(self, registry) -> None:
        """Build the disruption metrics and (when enabled and the cluster
        models Nodes) the watcher over the runtime's node informer."""
        self._pending_disruptions: Dict[str, dict] = {}
        self._disruption_lock = make_lock("disruption.pending")
        self.preemptions_detected_counter = registry.counter(
            "pytorch_operator_preemptions_detected_total",
            "Counts disruption detections (node taints, DisruptionTarget "
            "conditions, NotReady TPU nodes) attributed to a job",
        )
        self.preemption_gang_restarts_counter = registry.counter(
            "pytorch_operator_preemption_gang_restarts_total",
            "Counts proactive gang restarts triggered by impending "
            "preemption",
        )
        self.preemption_restarts_suppressed_counter = registry.counter(
            "pytorch_operator_preemption_restarts_suppressed_total",
            "Counts disruptions NOT proactively restarted (opt-out, "
            "non-gang job, or exhausted restart budget)",
        )
        self.preemption_restart_latency = registry.histogram(
            "pytorch_operator_preemption_restart_latency_seconds",
            "Seconds from disruption detection to the gang restart's "
            "batched pod delete being issued",
        )
        # Elastic-gang state: pending drains (shrink in progress, doomed
        # pods checkpointing), pending grows (capacity returned, resize
        # up not yet applied), and the shrunken-job registry the
        # capacity watcher consults.  All keyed by job, uid-fenced like
        # the disruption notes, guarded by the same lock.
        self._pending_drains: Dict[str, dict] = {}
        self._pending_grows: Dict[str, dict] = {}
        self._shrunken_jobs: Dict[str, str] = {}
        # capacity claimed by grows applied but not yet completed (pods
        # not yet bound): one capacity event waking N shrunken jobs must
        # not grow them all onto the same free nodes
        self._growing_claims: Dict[str, int] = {}
        # injectable clock (JobControllerConfig(clock=...) — the
        # simulator's virtual time — else wall): drain deadlines and
        # detection->restart latency ride it; tests also override it
        # directly
        self._mono = self.config.clock or time.monotonic
        self.elastic_resizes_counter = registry.counter_vec(
            "pytorch_operator_elastic_resizes_total",
            "Counts elastic gang resizes, labeled direction: shrink "
            "(checkpoint-drain on preemption) or grow (capacity "
            "returned)",
            ("direction",))
        self.elastic_drain_seconds = registry.histogram(
            "pytorch_operator_elastic_drain_seconds",
            "Seconds from the checkpoint signal to the doomed pods' "
            "batched delete being issued (ack-early or deadline-bound)",
            buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0, 60.0,
                     120.0),
        )
        self.elastic_drain_timeouts_counter = registry.counter(
            "pytorch_operator_elastic_drain_timeouts_total",
            "Counts drains that hit the deadline with unacked doomed "
            "pods (their checkpoint state is presumed lost)",
        )
        self.disruption_watcher: Optional[DisruptionWatcher] = None
        self.capacity_watcher: Optional[CapacityWatcher] = None
        self._pod_index_union: Optional[PodNodeIndexUnion] = None
        if self.config.enable_disruption_handling and \
                self.node_informer is not None:
            # nodeName index over the pod informer (ROADMAP scalability
            # item): a disrupted node resolves its pods in one dict hit
            # instead of a cluster-wide LIST per node event.  Sharded
            # replicas never START the global pod informer (each shard
            # runs its own filtered one), so an index over it would be
            # permanently empty — they get a PodNodeIndexUnion instead,
            # fed one per-shard index per ACQUIRED shard (see
            # _on_shard_acquired), which resolves a disrupted node's
            # OWNED pods with zero apiserver traffic (the PR 7
            # cluster-wide-LIST fallback is gone).  The union backs
            # DISRUPTION resolution only: a replica restarts only gangs
            # it owns, so owned-shard scope is exactly right there —
            # but capacity OCCUPANCY needs the whole fleet (a node
            # hosting another shard's pods is NOT free), so sharded
            # CapacityWatchers keep the authoritative cluster-LIST
            # fallback; free_capacity runs only on capacity events for
            # shrunken elastic jobs, not per disrupted node.
            if self.config.shard_count <= 1:
                pod_index = capacity_index = PodNodeIndex(
                    self.pod_informer)
            else:
                pod_index = self._pod_index_union = PodNodeIndexUnion()
                capacity_index = None
            self.disruption_watcher = DisruptionWatcher(
                self.cluster, self.node_informer,
                self._note_node_disruption, kind=self.KIND,
                pod_index=pod_index,
                journal=getattr(self, "journal", None))
            self.capacity_watcher = CapacityWatcher(
                self.node_informer, self._on_capacity_returned,
                pod_index=capacity_index, cluster=self.cluster)

    def disruption_handling_enabled(self) -> bool:
        return self.config.enable_disruption_handling

    def _admission_grow_allowed(self, job: PyTorchJob) -> bool:
        """Hook for the admission subsystem: False holds a shrunken
        elastic job at its floor because its grow-back entry still waits
        in the fair-share queue.  Default (no admission) never blocks."""
        return True

    # -- detection intake --------------------------------------------------
    def _note_disruption(self, job_key: str, reason: str, source: str,
                         uid: Optional[str] = None,
                         node: Optional[str] = None,
                         pod: Optional[str] = None) -> None:
        """Record a disruption for the job and wake its sync.  Multiple
        signals for the same job coalesce while one note is pending —
        the whole point is ONE restart per disruption, not one per
        signal (taint + DisruptionTarget + N pod failures).  ``uid``
        fences the note to the job incarnation it was observed against:
        a delete-recreate under the same key drops it at sync time.
        ``node``/``pod`` scope the doomed set for the elastic drain path
        (unscoped notes always take the legacy full-gang restart).

        Sharded mode: the node watcher is global (nodes are not
        sharded), so every replica sees every disruption — but only the
        replica OWNING the job may note it (a sharded replica owning
        zero shards owns zero jobs).  Without this gate the non-owners
        would overcount the detection metric N-fold, park the key (plus
        its note) on their workerless global queue, and replay the
        stale note as a second gang restart if they later acquire the
        job's shard."""
        if not self._owns_job_key(job_key):
            return
        with self._disruption_lock:
            existing = self._pending_disruptions.get(job_key)
            if existing is not None:
                # coalesce — but a scoped signal for a DIFFERENT node
                # or pod must widen the pending note's doomed set (a
                # capacity dip tainting two nodes back-to-back, or an
                # eviction marking a pod while a node note is pending,
                # is one disruption, not two), or the later signal's
                # pods would be silently dropped from the elastic drain
                # and never told to checkpoint
                if existing.get("uid") == uid:
                    if node and node not in existing["nodes"]:
                        existing["nodes"].append(node)
                    elif pod and pod not in existing["pods"]:
                        existing["pods"].append(pod)
                return
            self._pending_disruptions[job_key] = {
                "reason": reason,
                "source": source,
                "uid": uid,
                "nodes": [node] if node else [],
                "pods": [pod] if pod else [],
                "detected_at": self._mono(),
            }
        self.preemptions_detected_counter.inc()
        self._queue_for_key(job_key).add(job_key)

    def _note_node_disruption(self, job_key: str, reason: str,
                              node_name: str,
                              uid: Optional[str] = None) -> None:
        """DisruptionWatcher callback: a node-scoped note (the elastic
        path dooms exactly the pods bound to that node)."""
        self._note_disruption(job_key, reason, node_name, uid=uid,
                              node=node_name)

    def note_pod_disruption(self, pod: dict) -> None:
        """Pod-informer hook (detection source 2): a ``DisruptionTarget``
        condition marks the pod ahead of an eviction kill.

        Pods already being deleted (a gang restart's own deletes in
        flight) or already terminal are skipped: their late-arriving
        condition updates describe a disruption that has ALREADY been
        handled (or will be, by the normal failure path) — re-noting
        would gang-restart the freshly recreated pods and burn a second
        budget unit for one real preemption."""
        reason = pod_disruption_reason(pod)
        if reason is None:
            return
        meta = pod.get("metadata") or {}
        if meta.get("deletionTimestamp"):
            return
        if ((pod.get("status") or {}).get("phase")) in ("Succeeded",
                                                        "Failed"):
            return
        ref = _controller_ref_of(meta)
        if ref is None or ref.kind != self.KIND:
            return
        # cache-validated resolution (UID checked): a signal from a pod
        # of a deleted/recreated job must not be pinned on the new one
        job = self._resolve_controller_ref(meta.get("namespace", ""), ref)
        if job is None:
            return
        job_key = meta_namespace_key(job)
        # a gang restart's own deletes may still be in flight (API
        # latency + grace on a real cluster): outstanding deletion
        # expectations for this replica set mean the disruption is
        # already being handled — re-noting would restart the
        # recreated gang a second time
        rtype = (meta.get("labels") or {}).get(constants.LABEL_REPLICA_TYPE)
        if rtype:
            exp = self.expectations.get(expectation_pods_key(job_key, rtype))
            if exp is not None and exp.dels > 0:
                return
        self._note_disruption(
            job_key, reason, f'pod/{meta.get("name", "")}',
            uid=(job.get("metadata") or {}).get("uid"),
            pod=meta.get("name", ""))

    # -- the proactive restart --------------------------------------------
    def maybe_handle_disruption(
        self, job: PyTorchJob, job_dict: dict, pods: List[dict]
    ) -> bool:
        """Consume a pending disruption note for this job.  Returns True
        when a proactive gang restart was performed (the caller persists
        status and ends the sync); False hands the sync to the normal
        reconcile path."""
        with self._disruption_lock:
            note = self._pending_disruptions.pop(job.key, None)
        if note is None:
            return False
        if note.get("uid") and job.metadata.uid and \
                note["uid"] != job.metadata.uid:
            # noted against a previous incarnation of this key: the new
            # job never saw the disruption — drop the stale note
            return False
        log = logger_for_job(self.logger, job)
        if not self.gang_scheduling_enabled(job):
            # Non-gang jobs lose only the disrupted replica; per-pod
            # restart policies already handle that cheaply.
            self.preemption_restarts_suppressed_counter.inc()
            return False
        annotations = job.metadata.annotations or {}
        if annotations.get(constants.ANNOTATION_DISRUPTION_HANDLING) == \
                constants.DISRUPTION_HANDLING_DISABLED:
            log.info("disruption on %s ignored: job opted out",
                     note["source"])
            self.preemption_restarts_suppressed_counter.inc()
            return False
        if job.spec.elastic_policy is not None:
            # Elastic path: shrink to the surviving slice instead of the
            # full restart.  An ineligible disruption (whole gang doomed,
            # master doomed, below minReplicas, budget spent, unscoped
            # note) falls through to the legacy restart below.
            try:
                if self._begin_elastic_drain(job, job_dict, pods, note):
                    return True
            except Exception:
                with self._disruption_lock:
                    self._pending_disruptions.setdefault(job.key, note)
                raise
        budget = self._preemption_budget(job)
        used = job.status.preemption_restarts or 0
        if used >= budget:
            msg = (f"PyTorchJob {job.metadata.name}: node preemption "
                   f"detected ({note['reason']}) but the proactive restart "
                   f"budget ({budget}) is exhausted; falling back to "
                   f"per-pod failure handling")
            log.warning(msg)
            self.recorder.event(
                job_dict, EVENT_TYPE_WARNING,
                constants.PREEMPTION_RESTARTS_EXHAUSTED_REASON, msg)
            self.preemption_restarts_suppressed_counter.inc()
            return False
        if not pods:
            return False  # nothing to restart (e.g. preempted pre-create)

        # One batched delete per replica type, expectations raised
        # up-front — N replicas restart as one unit instead of N
        # failure/backoff cycles.  If any delete fails the note goes
        # BACK in the map before the error requeues the sync: the
        # watcher's per-node flag will not re-fire, so a consumed note
        # is the only memory that this disruption still needs handling.
        from ..controller.job import _group_by_replica_type

        try:
            for rtype, group in sorted(
                    _group_by_replica_type(pods).items()):
                if rtype:
                    self.submit_pod_deletes(job, job_dict, rtype, group)
                else:  # unlabeled strays: no expectations key to batch under
                    for pod in group:
                        self.pod_control.delete_pod(
                            pod["metadata"].get("namespace", ""),
                            pod["metadata"].get("name", ""), job_dict)
        except Exception:
            with self._disruption_lock:
                self._pending_disruptions.setdefault(job.key, note)
            raise

        msg = (f"PyTorchJob {job.metadata.name} is restarting: impending "
               f"TPU preemption on {note['source']} ({note['reason']}); "
               f"gang-restarting all {len(pods)} replica pod(s) "
               f"[restart {used + 1}/{budget}]")
        log.warning(msg)
        from ..controller import status as status_machine

        status_machine.update_job_conditions(
            job.status, constants.JOB_RESTARTING,
            constants.TPU_PREEMPTED_REASON, msg)
        self.recorder.event(
            job_dict, EVENT_TYPE_WARNING, constants.TPU_PREEMPTED_REASON, msg)
        job.status.preemption_restarts = used + 1
        self.preemption_gang_restarts_counter.inc()
        self.preemption_restart_latency.observe(
            self._mono() - note["detected_at"])
        self.jobs_restarted_counter.inc()
        return True

    def _preemption_budget(self, job: PyTorchJob) -> int:
        return self._annotation_budget(
            job, constants.ANNOTATION_MAX_PREEMPTION_RESTARTS,
            self.config.max_preemption_restarts)

    def _elastic_budget(self, job: PyTorchJob) -> int:
        return self._annotation_budget(
            job, constants.ANNOTATION_MAX_ELASTIC_RESIZES,
            self.config.max_elastic_resizes)

    def _annotation_budget(self, job: PyTorchJob, annotation: str,
                           default: int) -> int:
        annotations = job.metadata.annotations or {}
        override = annotations.get(annotation)
        if override:
            try:
                return max(0, int(override))
            except ValueError:
                logger_for_job(self.logger, job).warning(
                    "invalid %s annotation %r; using operator default",
                    annotation, override)
        return default

    # -- the elastic checkpoint-drain-resize path --------------------------
    def elastic_worker_target(self, job: PyTorchJob) -> Optional[int]:
        """The Worker count this sync reconciles toward: None for
        non-elastic jobs; otherwise status.desiredReplicas clamped to
        the configured count (the grow ceiling)."""
        if job.spec.elastic_policy is None:
            return None
        spec = job.spec.pytorch_replica_specs.get(
            constants.REPLICA_TYPE_WORKER)
        configured = int(spec.replicas or 0) if spec else 0
        desired = job.status.desired_replicas
        if desired is None:
            return configured
        return min(desired, configured)

    def _begin_elastic_drain(self, job: PyTorchJob, job_dict: dict,
                             pods: List[dict], note: dict) -> bool:
        """Phase 1 of a shrink: signal the doomed pods to checkpoint,
        move desiredReplicas to the surviving count, arm the drain
        deadline.  Returns False when the disruption is not elastically
        survivable (caller falls back to the legacy full restart)."""
        log = logger_for_job(self.logger, job)
        key = job.key
        with self._disruption_lock:
            in_flight = key in self._pending_drains
        if in_flight:
            # a second disruption landing mid-drain widens the SAME
            # drain (one capacity change, one Resizing transition) — or,
            # if the extra loss breaks the survivable floor, abandons the
            # shrink so the legacy full restart takes over
            return self._merge_into_drain(job, job_dict, pods, note)
        doomed = self._doomed_pods(pods, note)
        if not doomed or len(doomed) >= len(pods):
            return False  # unscoped, pre-create, or the whole gang
        for pod in doomed:
            labels = (pod.get("metadata") or {}).get("labels") or {}
            if labels.get(constants.LABEL_REPLICA_TYPE) != \
                    constants.REPLICA_TYPE_WORKER.lower():
                # the Master (or an unlabeled stray) is going down with
                # the node: rank 0 anchors the rendezvous, shrink can't
                # save this gang
                return False
        current = self.elastic_worker_target(job) or 0
        new_target = current - len(doomed)
        policy = job.spec.elastic_policy
        min_replicas = policy.min_replicas or 1
        if new_target < min_replicas:
            log.warning(
                "elastic shrink of %s would leave %d worker(s), below "
                "minReplicas %d; falling back to the full gang restart",
                key, new_target, min_replicas)
            return False
        budget = self._elastic_budget(job)
        used = job.status.elastic_resizes or 0
        if used >= budget:
            msg = (f"PyTorchJob {job.metadata.name}: elastic resize "
                   f"budget ({budget}) exhausted; falling back to the "
                   f"full gang restart")
            log.warning(msg)
            self.recorder.event(
                job_dict, EVENT_TYPE_WARNING,
                constants.ELASTIC_RESIZES_EXHAUSTED_REASON, msg)
            return False

        self._signal_checkpoint(doomed)

        deadline = self.config.drain_deadline_seconds
        drain = {
            "doomed": [p["metadata"].get("name", "") for p in doomed],
            "uid": job.metadata.uid,
            "target": new_target,
            # the shrink's status payload rides in the note so a sync
            # whose end-of-sync write failed can re-assert it (the note
            # is the retry memory for the STATUS too, not just the
            # deletes — see _continue_drain)
            "resizes": used + 1,
            "signaled_at": self._mono(),
            "deadline": self._mono() + deadline,
        }
        with self._disruption_lock:
            self._pending_drains[key] = drain
        # a fresh shrink supersedes any not-yet-completed grow; the
        # claimed nodes (if still free) become claimable by siblings
        self._release_grow_claim(key)

        job.status.desired_replicas = new_target
        job.status.elastic_resizes = used + 1
        msg = (f"PyTorchJob {job.metadata.name} is resizing: impending "
               f"TPU preemption on {note['source']} ({note['reason']}) "
               f"dooms {len(doomed)} worker(s); draining them "
               f"(checkpoint signal sent, deadline {deadline:g}s) and "
               f"shrinking the gang to {new_target} worker(s) "
               f"[resize {used + 1}/{budget}]")
        log.warning(msg)
        from ..controller import status as status_machine

        status_machine.update_job_conditions(
            job.status, constants.JOB_RESIZING,
            constants.RESIZE_SHRINK_REASON, msg)
        self.recorder.event(
            job_dict, EVENT_TYPE_WARNING, constants.RESIZE_SHRINK_REASON,
            msg)
        self.elastic_resizes_counter.labels(direction="shrink").inc()
        drain["message"] = msg
        # wake the sync at the deadline even if no ack ever arrives
        self._queue_for_key(key).add_after(key, deadline)
        return True

    def _merge_into_drain(self, job: PyTorchJob, job_dict: dict,
                          pods: List[dict], note: dict) -> bool:
        """Fold a disruption that landed mid-drain into the in-flight
        drain: the newly doomed pods join the checkpoint signal and the
        target drops further — still ONE Resizing transition (the
        condition dedups on status+reason).  Returns False (and cancels
        the drain) when the widened loss can't be elastically survived,
        handing the note to the legacy full restart."""
        key = job.key
        with self._disruption_lock:
            drain = self._pending_drains.get(key)
            if drain is None:
                return False  # raced drain completion: retry as fresh
        log = logger_for_job(self.logger, job)
        already = set(drain["doomed"])
        fresh = [p for p in self._doomed_pods(pods, note)
                 if (p.get("metadata") or {}).get("name") not in already]
        if not fresh:
            return True  # nothing new; the in-flight drain covers it
        worker_rt = constants.REPLICA_TYPE_WORKER.lower()
        all_workers = all(
            ((p.get("metadata") or {}).get("labels") or {}).get(
                constants.LABEL_REPLICA_TYPE) == worker_rt
            for p in fresh)
        new_target = drain["target"] - len(fresh)
        min_replicas = job.spec.elastic_policy.min_replicas or 1
        if not all_workers or new_target < min_replicas:
            log.warning(
                "disruption widened mid-drain beyond the survivable "
                "floor for %s (target would be %d, min %d); abandoning "
                "the shrink for a full gang restart", key, new_target,
                min_replicas)
            with self._disruption_lock:
                self._pending_drains.pop(key, None)
            # the restart recreates the FULL gang; a stale shrunken
            # target would strand the recreated workers
            spec = job.spec.pytorch_replica_specs.get(
                constants.REPLICA_TYPE_WORKER)
            job.status.desired_replicas = int(spec.replicas or 0) \
                if spec else 0
            # the shrink never happened: return its budget slot and
            # clear the Resizing condition the full restart supersedes
            # (otherwise N abandoned drains silently exhaust the budget
            # a later, genuinely survivable preemption needs)
            job.status.elastic_resizes = max(
                0, (job.status.elastic_resizes or 0) - 1)
            from ..controller import status as status_machine

            status_machine.clear_condition(
                job.status, constants.JOB_RESIZING,
                constants.RESIZE_ABANDONED_REASON,
                f"PyTorchJob {job.metadata.name}: shrink abandoned "
                f"mid-drain (widened below minReplicas "
                f"{min_replicas}); restarting the full gang")
            return False
        self._signal_checkpoint(fresh)
        with self._disruption_lock:
            drain["doomed"].extend(
                (p.get("metadata") or {}).get("name", "") for p in fresh)
            drain["target"] = new_target
            # the late-doomed pods get a FULL drain window: their
            # node's termination grace started now, not when the drain
            # began — the original deadline could be moments away
            drain["deadline"] = max(
                drain["deadline"],
                self._mono() + self.config.drain_deadline_seconds)
        job.status.desired_replicas = new_target
        log.warning(
            "disruption on %s widened the in-flight drain of %s: %d more "
            "doomed worker(s), target now %d", note["source"], key,
            len(fresh), new_target)
        return True

    def _signal_checkpoint(self, doomed: List[dict]) -> None:
        """Signal every doomed pod to checkpoint now.  The annotation is
        the durable signal (the kubelet's SIGTERM rides beside it); a
        pod deleted out from under us is already as drained as it
        gets."""
        from ..controller import status as status_machine

        now_iso = status_machine.now_iso()
        for pod in doomed:
            meta = pod.get("metadata") or {}
            try:
                self.cluster.pods.patch(
                    meta.get("namespace", ""), meta.get("name", ""),
                    {"metadata": {"annotations": {
                        constants.ANNOTATION_CHECKPOINT_REQUESTED: now_iso,
                    }}})
            except NotFoundError:
                pass

    @staticmethod
    def _doomed_pods(pods: List[dict], note: dict) -> List[dict]:
        """Union of the note's node-bound and directly-named pods: a
        coalesced note can carry both scopes (a taint plus a pod-level
        DisruptionTarget), and neither set may be dropped."""
        nodes = set(note.get("nodes") or ())
        names = set(note.get("pods") or ())
        if not nodes and not names:
            return []
        return [p for p in pods
                if (p.get("spec") or {}).get("nodeName") in nodes
                or (p.get("metadata") or {}).get("name") in names]

    def maybe_continue_elastic(self, job: PyTorchJob, job_dict: dict,
                               pods: List[dict]) -> bool:
        """Per-sync elastic step, after disruption intake: advances a
        pending drain (returns True — the sync is consumed waiting for
        acks or issuing the shrink deletes), applies a pending grow, and
        completes a finished resize (condition cleared, rendezvous
        re-rendered).  Grow and completion fall through (return False)
        so the same sync's normal reconcile acts on the new target."""
        if job.spec.elastic_policy is None:
            return False
        key = job.key
        uid = job.metadata.uid or ""
        with self._disruption_lock:
            drain = self._pending_drains.get(key)
            if drain is not None and drain.get("uid") and uid and \
                    drain["uid"] != uid:
                # stale drain from a previous incarnation of this key
                self._pending_drains.pop(key, None)
                drain = None
        if drain is not None:
            return self._continue_drain(job, job_dict, pods, drain)
        with self._disruption_lock:
            grow = self._pending_grows.get(key)
        if grow is not None and not self._try_grow(job, job_dict, pods):
            # The note is the grow's retry memory (symmetric with the
            # drain note): an APPLIED grow (True) only lives in this
            # sync's in-memory status until the end-of-sync write lands,
            # and a failed write rebuilds the next sync's job from the
            # store at the shrunken size — with the created workers
            # already live and this job's capacity claim still held.
            # The surviving note re-runs _try_grow (idempotent against
            # its own creates) until the store shows the grown target.
            # A DECLINED grow (capacity short, or already at goal)
            # drops the note; the next capacity event re-adds it.
            with self._disruption_lock:
                self._pending_grows.pop(key, None)
        self._elastic_bookkeeping(job, job_dict, pods)
        return False

    def _continue_drain(self, job: PyTorchJob, job_dict: dict,
                        pods: List[dict], drain: dict) -> bool:
        """Phase 2 of a shrink: wait (bounded) for checkpoint acks, then
        delete only the doomed pods.  The drain note stays in the map
        until the deletes were issued, so a failed delete retries on the
        requeued sync without re-consuming budget."""
        from ..controller import status as status_machine

        key = job.key
        # Re-assert the shrink onto THIS sync's status: the intake
        # sync's end-of-sync write can fail after the note was armed,
        # and the requeued sync rebuilds the job from the store at the
        # pre-shrink size — without this the drain would still delete
        # the doomed pods while the store never learns the shrunken
        # target, and the next reconcile recreates the very indices it
        # just drained.  Idempotent: no counter/event re-fires, and a
        # job whose write landed sees its own values back.
        job.status.desired_replicas = drain["target"]
        if (job.status.elastic_resizes or 0) < drain.get("resizes", 0):
            job.status.elastic_resizes = drain["resizes"]
        cond = status_machine.get_condition(job.status,
                                            constants.JOB_RESIZING)
        if cond is None or cond.status != status_machine.CONDITION_TRUE:
            status_machine.update_job_conditions(
                job.status, constants.JOB_RESIZING,
                constants.RESIZE_SHRINK_REASON,
                drain.get("message", ""))
        doomed_names = set(drain["doomed"])
        alive = [p for p in pods
                 if (p.get("metadata") or {}).get("name") in doomed_names]

        def acked(pod: dict) -> bool:
            meta = pod.get("metadata") or {}
            if constants.ANNOTATION_CHECKPOINTED in (
                    meta.get("annotations") or {}):
                return True
            # a pod the preemption already killed can't checkpoint any
            # more; waiting on it would just burn the whole deadline
            return ((pod.get("status") or {}).get("phase")
                    in ("Succeeded", "Failed"))

        now = self._mono()
        pending = [p for p in alive if not acked(p)]
        if pending and now < drain["deadline"]:
            # keep the sync warm without busy-looping: re-check soon,
            # and the ack patches themselves also enqueue the job
            self._queue_for_key(key).add_after(
                key, max(0.02, min(0.25, drain["deadline"] - now)))
            return True
        if pending:
            self.elastic_drain_timeouts_counter.inc()
            logger_for_job(self.logger, job).warning(
                "drain deadline passed with %d unacked doomed pod(s) on "
                "%s; deleting anyway (their step state is presumed lost)",
                len(pending), key)

        from ..controller.job import _group_by_replica_type

        for rtype, group in sorted(_group_by_replica_type(alive).items()):
            if rtype:
                self.submit_pod_deletes(job, job_dict, rtype, group)
            else:
                for pod in group:
                    self.pod_control.delete_pod(
                        pod["metadata"].get("namespace", ""),
                        pod["metadata"].get("name", ""), job_dict)

        with self._disruption_lock:
            self._pending_drains.pop(key, None)
            self._shrunken_jobs[key] = job.metadata.uid or ""
        self.elastic_drain_seconds.observe(now - drain["signaled_at"])
        # count only REAL acks as checkpointed: a doomed pod the
        # preemption killed first is treated as acked for pacing (it
        # can't checkpoint any more) but its step state is lost — the
        # event must not report the opposite
        acked_ck = sum(
            1 for p in alive
            if constants.ANNOTATION_CHECKPOINTED in (
                (p.get("metadata") or {}).get("annotations") or {}))
        died = len(alive) - acked_ck - len(pending)
        msg = (f"PyTorchJob {job.metadata.name} shrank to "
               f"{drain['target']} worker(s): {len(alive)} drained pod(s) "
               f"deleted ({acked_ck} checkpointed, {died} died before "
               f"checkpointing, {len(pending)} timed out)")
        logger_for_job(self.logger, job).info(msg)
        self.recorder.event(job_dict, EVENT_TYPE_NORMAL,
                            constants.RESIZE_SHRINK_REASON, msg)
        return True

    def _try_grow(self, job: PyTorchJob, job_dict: dict,
                  pods: List[dict]) -> bool:
        """Apply a pending grow: desiredReplicas back to the configured
        count (bounded by maxReplicas) when enough schedulable TPU
        capacity is free.  Not enough capacity simply leaves the job
        shrunken — the next capacity event retries."""
        policy = job.spec.elastic_policy
        spec = job.spec.pytorch_replica_specs.get(
            constants.REPLICA_TYPE_WORKER)
        configured = int(spec.replicas or 0) if spec else 0
        goal = min(configured, policy.max_replicas or configured)
        current = self.elastic_worker_target(job) or 0
        if current >= goal:
            return False
        if not self._admission_grow_allowed(job):
            # The freed chips belong to a higher-priority waiter: a
            # preempted-by-priority job stays shrunken until the
            # admission queue re-releases its grow-back entry (which
            # re-arms a grow note and re-enqueues the key).  Declining
            # here drops the note like a capacity shortfall would.
            logger_for_job(self.logger, job).info(
                "grow of %s deferred: its grow-back entry still waits "
                "in the admission queue", job.key)
            return False
        existing = sum(
            1 for p in pods
            if ((p.get("metadata") or {}).get("labels") or {}).get(
                constants.LABEL_REPLICA_TYPE)
            == constants.REPLICA_TYPE_WORKER.lower())
        # only workers this sync still has to CREATE need fresh
        # capacity: a retried grow whose creates outlived a failed
        # status write (or an operator restart) finds them in `pods` —
        # bound ones already read as occupied in the free walk, pending
        # ones are covered by the prior attempt's claim kept below
        missing = goal - max(current, existing)
        # the free-capacity walk is O(nodes) — keep it OUTSIDE the
        # disruption lock so grow attempts never stall preemption
        # intake; the lock covers only the claimed-sum check and the
        # claim insertion, which is what serializes grow admission
        free_raw = self._free_tpu_capacity() if missing > 0 else 0
        with self._disruption_lock:
            claimed = sum(v for k, v in self._growing_claims.items()
                          if k != job.key)
            free = free_raw - claimed
            if missing > 0 and free >= missing:
                # reserve the capacity until this grow's pods are live:
                # sibling jobs woken by the same node event must see it
                # as spoken for, or they all grow onto the same nodes
                # and sit Pending forever.  A retry keeps a prior
                # attempt's larger claim — its pods may still be
                # Pending, so their nodes still LOOK free.
                self._growing_claims[job.key] = max(
                    self._growing_claims.get(job.key, 0), missing)
        if missing > 0 and free < missing:
            logger_for_job(self.logger, job).info(
                "capacity event for shrunken %s, but only %d unclaimed "
                "free schedulable TPU node(s) for %d missing worker(s); "
                "staying at %d", job.key, free, missing, current)
            return False
        job.status.desired_replicas = goal
        if missing > 0:
            how = f"schedulable TPU capacity returned ({free} free node(s))"
        else:
            how = (f"{existing} worker(s) already live from a prior "
                   f"grow attempt")
        msg = (f"PyTorchJob {job.metadata.name} is resizing: {how}; "
               f"growing the gang from {current} back to {goal} worker(s)")
        from ..controller import status as status_machine

        # the condition is re-asserted on EVERY apply (a failed write
        # loses it with the rest of the status), but the event, the log
        # line and the resize counter fire once per grow — the note
        # remembers the announcement across write-failure retries, so
        # one real resize is never counted N times
        status_machine.update_job_conditions(
            job.status, constants.JOB_RESIZING,
            constants.RESIZE_GROW_REASON, msg)
        with self._disruption_lock:
            note = self._pending_grows.get(job.key)
            announced = bool(note and note.get("announced"))
            if note is not None:
                note["announced"] = True
        if not announced:
            logger_for_job(self.logger, job).info(msg)
            self.recorder.event(job_dict, EVENT_TYPE_NORMAL,
                                constants.RESIZE_GROW_REASON, msg)
            self.elastic_resizes_counter.labels(direction="grow").inc()
        return True

    def _elastic_bookkeeping(self, job: PyTorchJob, job_dict: dict,
                             pods: List[dict]) -> None:
        """Resize completion: once the live worker set matches the
        target, clear the Resizing condition and re-render the gang's
        rendezvous annotations (exactly once per resize — the render
        rides the condition's True->False edge)."""
        from ..controller import status as status_machine

        key = job.key
        target = self.elastic_worker_target(job)
        spec = job.spec.pytorch_replica_specs.get(
            constants.REPLICA_TYPE_WORKER)
        configured = int(spec.replicas or 0) if spec else 0
        with self._disruption_lock:
            if target is not None and target < configured:
                self._shrunken_jobs[key] = job.metadata.uid or ""
            else:
                self._shrunken_jobs.pop(key, None)
        cond = status_machine.get_condition(job.status,
                                            constants.JOB_RESIZING)
        if cond is None or cond.status != status_machine.CONDITION_TRUE:
            if target is not None and target < configured:
                # steady shrunken state (no resize in flight): a
                # survivor's replacement pod boots with the
                # CONFIGURED-size env (build_cluster_env can't know the
                # elastic target) and missed the completion-edge render
                # — keep the gang's annotations fresh.  The render
                # diffs in memory and patches only stale pods, so this
                # is free once the annotations settle.
                self._render_elastic_env(job, pods)
            return
        workers = [
            p for p in pods
            if ((p.get("metadata") or {}).get("labels") or {}).get(
                constants.LABEL_REPLICA_TYPE)
            == constants.REPLICA_TYPE_WORKER.lower()]
        if len(workers) != target:
            return
        if any(not (p.get("spec") or {}).get("nodeName")
               for p in workers):
            # created but unplaced: a Pending pod occupies no node, so
            # completing now would release the capacity claim while the
            # nodes it reserved still LOOK free — the exact pile-up the
            # claim exists to prevent
            return
        msg = (f"PyTorchJob {job.metadata.name} finished resizing: "
               f"{target} worker(s) live")
        status_machine.clear_condition(
            job.status, constants.JOB_RESIZING,
            constants.RESIZE_COMPLETED_REASON, msg)
        # grown pods are live and bound: their nodes now show as
        # occupied, so the reservation has served its purpose
        self._release_grow_claim(key)
        logger_for_job(self.logger, job).info(msg)
        self._render_elastic_env(job, pods)

    def _render_elastic_env(self, job: PyTorchJob,
                            pods: List[dict]) -> None:
        """Re-publish WORLD_SIZE/RANK/hostnames for the current gang as
        pod annotations (tpu_env.elastic_rendezvous_annotations).
        Idempotent: pods whose annotations already carry the computed
        values are skipped, so steady-state re-renders patch nothing."""
        from ..controller.tpu_env import elastic_rendezvous_annotations

        namespace = job.metadata.namespace
        current = {
            (p.get("metadata") or {}).get("name", ""):
                (p.get("metadata") or {}).get("annotations") or {}
            for p in pods}
        for pod_name, annotations in elastic_rendezvous_annotations(
                job, pods).items():
            have = current.get(pod_name, {})
            if all(have.get(k) == v for k, v in annotations.items()):
                continue
            try:
                self.cluster.pods.patch(
                    namespace, pod_name,
                    {"metadata": {"annotations": annotations}})
            except NotFoundError:
                pass

    def _on_capacity_returned(self, node_name: str) -> None:
        """CapacityWatcher callback: wake every shrunken elastic job so
        its next sync can attempt the grow."""
        with self._disruption_lock:
            shrunken = dict(self._shrunken_jobs)
            for key, uid in shrunken.items():
                self._pending_grows.setdefault(
                    key, {"node": node_name, "uid": uid})
        for key in shrunken:
            self._queue_for_key(key).add(key)

    def _release_grow_claim(self, key: str) -> None:
        """Release a grow's capacity reservation and — if one was
        actually held — re-wake the still-shrunken jobs it was
        starving: the capacity became claimable WITHOUT a node
        transition (grow completed / job ended / job re-shrank), so
        the CapacityWatcher, which only fires on node edges, would
        never tell them."""
        with self._disruption_lock:
            released = self._growing_claims.pop(key, None)
        if released:
            self._on_capacity_returned(f"claim-released:{key}")

    def _free_tpu_capacity(self) -> int:
        if self.capacity_watcher is not None:
            return self.capacity_watcher.free_capacity()
        # no node informer (unit-test wiring): resolve straight from the
        # cluster stores
        occupied = {(p.get("spec") or {}).get("nodeName")
                    for p in self.cluster.pods.list()}
        return sum(
            1 for n in self.cluster.nodes.list()
            if node_schedulable_tpu(n)
            and (n.get("metadata") or {}).get("name") not in occupied)

    def clear_elastic_state(self, key: str) -> None:
        """Drop every elastic note for a deleted job key (called from
        sync_job's deleted branch beside the disruption-note cleanup)."""
        with self._disruption_lock:
            self._pending_drains.pop(key, None)
            self._pending_grows.pop(key, None)
            self._shrunken_jobs.pop(key, None)
        self._release_grow_claim(key)
