"""Data-plane telemetry: step profiling, throughput/MFU, push ingestion.

The control plane (metrics/, runtime/tracing.py) answers "is the
operator healthy"; this package answers "is the JOB healthy" — the
per-step timing, tokens/sec and MFU signals the reference operator
could only approximate by grepping pod logs:

  * :mod:`step_timer` — ``StepProfiler`` wraps any jitted
    ``make_*_train_step`` product: first-call compile time vs
    steady-state step time, rolling tokens/sec, analytic MFU, and a
    structured JSONL step log ``scripts/bench_trend.py`` can trend;
  * :mod:`push` — the pushgateway-style ingestion path: job pods (and
    the sim tier's fake kubelet) POST per-step samples to the
    operator's ``/push/v1/metrics``; the ``PushGateway`` re-exports
    them as ``job``-labeled families under a series budget, so one
    misbehaving fleet cannot explode the operator's exposition.
"""

from .push import PushClient, PushGateway  # noqa: F401
from .step_timer import (  # noqa: F401
    PEAK_FLOPS_PER_CHIP,
    StepProfiler,
    StepRecord,
    peak_flops_per_chip,
    read_step_log,
    train_step_flops,
)
