// Rate-limited delaying workqueue with client-go semantics.
//
// Mirrors k8s.io/client-go/util/workqueue as the reference uses it
// (jobcontroller.go:110-131): dedupe via dirty set, processing exclusion
// ("an item is never processed by two workers simultaneously"), delayed
// re-adds via a min-heap, per-item exponential backoff.

#include "tpu_operator.h"

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

// Timed condvar wait against a steady_clock deadline, issued on the
// system_clock overload.  libstdc++ maps a steady_clock wait_until to
// pthread_cond_clockwait, which older libtsan builds (GCC 10) do not
// intercept: every timed wait then reads as a phantom "double lock of
// a mutex" AND hides the real unlock/relock handoff inside the wait
// from the race detector.  Waiting on system_clock routes through the
// intercepted pthread_cond_timedwait instead.  Callers loop and
// re-check their steady deadline, so a realtime clock jump costs at
// most a spurious wakeup or one late recheck, never a wrong result.
std::cv_status WaitUntilSteady(std::condition_variable& cv,
                               std::unique_lock<std::mutex>& lk,
                               Clock::time_point deadline) {
  const auto rel = deadline - Clock::now();
  if (rel <= Clock::duration::zero()) return std::cv_status::timeout;
  return cv.wait_until(lk, std::chrono::system_clock::now() + rel);
}

struct Waiting {
  Clock::time_point ready_at;
  uint64_t seq;
  std::string item;
  // entries from AddRateLimited are cancellable (pending_retry_); plain
  // AddAfter timers (deadline/TTL wake-ups) never are
  bool is_retry;
  bool operator>(const Waiting& o) const {
    if (ready_at != o.ready_at) return ready_at > o.ready_at;
    return seq > o.seq;
  }
};

class WorkQueue {
 public:
  WorkQueue(double base_delay, double max_delay)
      : base_delay_(base_delay), max_delay_(max_delay) {}

  void Add(const std::string& item) {
    std::lock_guard<std::mutex> lk(mu_);
    AddLocked(item);
  }

  void AddAfter(const std::string& item, double delay) {
    if (delay <= 0) {
      Add(item);
      return;
    }
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    waiting_.push(Waiting{
        Clock::now() + std::chrono::duration_cast<Clock::duration>(
                           std::chrono::duration<double>(delay)),
        seq_++, item, false});
    cv_.notify_one();
  }

  // At most one live retry per item: a retry for an already-dirty key
  // is dropped (the imminent processing supersedes it), a newer retry
  // replaces a pending one, and Forget cancels it — else a rate-limited
  // requeue plus a live watch event double-processes the key.
  void AddRateLimited(const std::string& item) {
    std::lock_guard<std::mutex> lk(mu_);
    if (shutdown_) return;
    int n = failures_[item]++;
    double delay = base_delay_;
    for (int i = 0; i < n && delay < max_delay_; i++) delay *= 2;
    if (delay > max_delay_) delay = max_delay_;
    if (dirty_.count(item)) return;
    uint64_t seq = seq_++;
    pending_retry_[item] = seq;
    waiting_.push(Waiting{
        Clock::now() + std::chrono::duration_cast<Clock::duration>(
                           std::chrono::duration<double>(delay)),
        seq, item, true});
    cv_.notify_one();
  }

  // 1 = item, 0 = timeout, -1 = shutdown
  int Get(double timeout, std::string* out) {
    std::unique_lock<std::mutex> lk(mu_);
    ++active_getters_;
    int rc = GetLocked(lk, timeout, out);
    if (--active_getters_ == 0 && shutdown_) cv_.notify_all();
    return rc;
  }

  // Blocks until no thread is inside Get, so deleting the queue is safe.
  void ShutdownAndDrain() {
    std::unique_lock<std::mutex> lk(mu_);
    shutdown_ = true;
    cv_.notify_all();
    cv_.wait(lk, [this] { return active_getters_ == 0; });
  }

 private:
  int GetLocked(std::unique_lock<std::mutex>& lk, double timeout,
                std::string* out) {
    const bool forever = timeout < 0;
    const auto deadline =
        forever ? Clock::time_point::max()
                : Clock::now() + std::chrono::duration_cast<Clock::duration>(
                                     std::chrono::duration<double>(timeout));
    for (;;) {
      DrainReadyLocked();
      if (!queue_.empty()) {
        *out = queue_.front();
        queue_.pop_front();
        processing_.insert(*out);
        dirty_.erase(*out);
        return 1;
      }
      if (shutdown_) return -1;
      auto wake = deadline;
      if (!waiting_.empty() && waiting_.top().ready_at < wake)
        wake = waiting_.top().ready_at;
      if (wake == Clock::time_point::max()) {
        cv_.wait(lk);
      } else {
        if (WaitUntilSteady(cv_, lk, wake) == std::cv_status::timeout &&
            !forever && Clock::now() >= deadline) {
          // drain anything that became ready exactly at the deadline
          DrainReadyLocked();
          if (!queue_.empty()) continue;
          return 0;
        }
      }
    }
  }

 public:
  void Done(const std::string& item) {
    std::lock_guard<std::mutex> lk(mu_);
    processing_.erase(item);
    if (dirty_.count(item)) {
      queue_.push_back(item);
      cv_.notify_one();
    }
  }

  // Reset backoff AND cancel the item's pending retry (Forget runs
  // after a successful sync, making a scheduled retry pure
  // double-processing); plain AddAfter timers are untouched.
  void Forget(const std::string& item) {
    std::lock_guard<std::mutex> lk(mu_);
    failures_.erase(item);
    pending_retry_.erase(item);
  }

  int IsDirty(const std::string& item) {
    std::lock_guard<std::mutex> lk(mu_);
    return dirty_.count(item) ? 1 : 0;
  }

  int NumRequeues(const std::string& item) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = failures_.find(item);
    return it == failures_.end() ? 0 : it->second;
  }

  int Len() {
    std::lock_guard<std::mutex> lk(mu_);
    return static_cast<int>(queue_.size());
  }

  void Shutdown() {
    std::lock_guard<std::mutex> lk(mu_);
    shutdown_ = true;
    cv_.notify_all();
  }

 private:
  void AddLocked(const std::string& item) {
    if (shutdown_ || dirty_.count(item)) return;
    dirty_.insert(item);
    if (processing_.count(item)) return;
    queue_.push_back(item);
    cv_.notify_one();
  }

  void DrainReadyLocked() {
    const auto now = Clock::now();
    while (!waiting_.empty() && waiting_.top().ready_at <= now) {
      const Waiting top = waiting_.top();
      waiting_.pop();
      if (top.is_retry) {
        auto it = pending_retry_.find(top.item);
        if (it == pending_retry_.end() || it->second != top.seq)
          continue;  // superseded by a newer retry or cancelled by Forget
        pending_retry_.erase(it);
      }
      AddReadyLocked(top.item);
    }
  }

  void AddReadyLocked(const std::string& item) {
    if (dirty_.count(item)) return;
    dirty_.insert(item);
    if (!processing_.count(item)) queue_.push_back(item);
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  std::unordered_set<std::string> dirty_;
  std::unordered_set<std::string> processing_;
  std::priority_queue<Waiting, std::vector<Waiting>, std::greater<Waiting>>
      waiting_;
  std::unordered_map<std::string, int> failures_;
  std::unordered_map<std::string, uint64_t> pending_retry_;
  uint64_t seq_ = 0;
  int active_getters_ = 0;
  bool shutdown_ = false;
  double base_delay_;
  double max_delay_;
};

}  // namespace

extern "C" {

void* wq_new(double base_delay, double max_delay) {
  return new WorkQueue(base_delay, max_delay);
}
void wq_free(void* q) {
  // Wake and wait out any thread blocked in wq_get (which runs without
  // the Python GIL) before destroying the mutex/condvar under it.
  auto* wq = static_cast<WorkQueue*>(q);
  wq->ShutdownAndDrain();
  delete wq;
}
void wq_add(void* q, const char* item) {
  static_cast<WorkQueue*>(q)->Add(item);
}
void wq_add_after(void* q, const char* item, double delay) {
  static_cast<WorkQueue*>(q)->AddAfter(item, delay);
}
void wq_add_rate_limited(void* q, const char* item) {
  static_cast<WorkQueue*>(q)->AddRateLimited(item);
}
int wq_get(void* q, double timeout, char* buf, int buflen) {
  std::string out;
  int rc = static_cast<WorkQueue*>(q)->Get(timeout, &out);
  if (rc == 1) {
    if (static_cast<int>(out.size()) >= buflen) {
      // Caller buffer too small: requeue so the item is not lost (Add
      // marks it dirty while processing; Done then re-queues it).
      static_cast<WorkQueue*>(q)->Add(out);
      static_cast<WorkQueue*>(q)->Done(out);
      return -2;
    }
    std::memcpy(buf, out.c_str(), out.size() + 1);
  }
  return rc;
}
void wq_done(void* q, const char* item) {
  static_cast<WorkQueue*>(q)->Done(item);
}
void wq_forget(void* q, const char* item) {
  static_cast<WorkQueue*>(q)->Forget(item);
}
int wq_is_dirty(void* q, const char* item) {
  return static_cast<WorkQueue*>(q)->IsDirty(item);
}
int wq_num_requeues(void* q, const char* item) {
  return static_cast<WorkQueue*>(q)->NumRequeues(item);
}
int wq_len(void* q) { return static_cast<WorkQueue*>(q)->Len(); }
void wq_shutdown(void* q) { static_cast<WorkQueue*>(q)->Shutdown(); }

}  // extern "C"
