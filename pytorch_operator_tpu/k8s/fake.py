"""In-memory fake Kubernetes API server.

The reference tests multi-node behavior without a cluster by injecting
state into informer indexers and recording side effects through fake
controls (SURVEY.md §4 tier 2).  This module goes one step further and
provides a small but faithful API-server simulation — namespaced stores
with resourceVersions, label-selector lists, watch fan-out, owner-reference
garbage collection — so the same controller code paths run against either
the real REST client or this fake.

Objects are stored as plain dicts in the camelCase wire format
(equivalent of ``unstructured.Unstructured`` in the reference's dynamic
informer, pkg/common/util/v1/unstructured/informer.go:25-63).
"""

from __future__ import annotations

import copy
import threading
import time
import uuid
from collections import deque, namedtuple
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ..analysis import ownership as _ownership
from ..analysis.witness import make_lock, make_rlock
from .errors import AlreadyExistsError, ConflictError, InvalidError, NotFoundError
from .objects import match_labels

WatchEvent = Tuple[str, dict]  # ("ADDED"|"MODIFIED"|"DELETED", object)

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

#: One relist answer (``FakeResourceStore.list_changes`` /
#: ``RestResourceStore.list_changes``): ``windowed=True`` means *items*
#: holds only the objects changed since the requested resourceVersion
#: and *deleted* the objects removed since it (a delta the informer
#: applies over its store); ``windowed=False`` is a plain full LIST
#: (the requested RV fell out of the watch-cache window, or none was
#: given).  ``resource_version`` is the listing's high-water mark —
#: the RV the next delta request should pass.
ListChanges = namedtuple(
    "ListChanges", ("windowed", "items", "deleted", "resource_version"))


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _match_selector(selector: Optional[Dict[str, str]], obj: dict) -> bool:
    if not selector:
        return True
    labels = (obj.get("metadata") or {}).get("labels") or {}
    return match_labels(selector, labels)


def _copy_obj(obj):
    """Deep copy for wire-format objects (dict/list/scalar trees).

    ``copy.deepcopy`` pays memo-dict bookkeeping on every node; wire
    objects are plain JSON shapes, so a direct recursive copy is ~5x
    cheaper — and this is the fake tier's hottest operation (every
    store mutation copies for the watch fan-out, every LIST copies the
    result set; at kubemark scale that is hundreds of thousands of
    copies per scenario).  Anything non-JSON a test smuggled into a
    stored object falls back to ``copy.deepcopy`` unchanged."""
    t = type(obj)
    if t is dict:
        return {k: _copy_obj(v) for k, v in obj.items()}
    if t is list:
        return [_copy_obj(v) for v in obj]
    if t is str or t is int or t is float or t is bool or obj is None:
        return obj
    return copy.deepcopy(obj)


class FakeResourceStore:
    """One namespaced resource collection (e.g. all Pods)."""

    def __init__(self, cluster: "FakeCluster", kind: str):
        self._cluster = cluster
        self.kind = kind
        self._objects: Dict[Tuple[str, str], dict] = {}
        self._listeners: List[Callable[[str, dict], None]] = []
        # Label index (kubemark scale): for each label key in
        # ``cluster.index_labels``, value -> set of object keys.  A LIST
        # whose selector pins an indexed label then scans only that
        # bucket — the controller's per-job pod/service LIST drops from
        # O(collection) to O(objects of that job), which is what makes
        # a 50k-pod fleet reconcilable in Python.  Buckets hold KEYS
        # only (objects resolve through ``_objects``), so value-stable
        # rewrites (status, GC owner-ref surgery) need no index work.
        self._index_labels: Tuple[str, ...] = tuple(
            getattr(cluster, "index_labels", ()) or ())
        self._label_index: Dict[str, Dict[str, set]] = {
            k: {} for k in self._index_labels}
        # Watch cache (ROADMAP direction 2, first slice): a bounded
        # window of recent mutations so a LIST carrying the caller's
        # last-seen resourceVersion can be answered as a DELTA instead
        # of the full collection.  Entries are (rv, event_type, obj);
        # _cache_floor is the highest rv already evicted — a request
        # below it cannot be answered from the window.
        self._watch_cache: deque = deque()
        self._cache_floor = 0

    # -- internal helpers --------------------------------------------------
    def _key(self, namespace: str, name: str) -> Tuple[str, str]:
        return (namespace or "default", name)

    def __len__(self) -> int:
        with self._cluster.lock:
            return len(self._objects)

    # -- label index (called with the cluster lock held) -------------------
    def _index_add(self, key: Tuple[str, str], obj: dict) -> None:
        if not self._index_labels:
            return
        labels = (obj.get("metadata") or {}).get("labels") or {}
        for lk in self._index_labels:
            value = labels.get(lk)
            if value is not None:
                self._label_index[lk].setdefault(value, set()).add(key)

    def _index_remove(self, key: Tuple[str, str], obj: dict) -> None:
        if not self._index_labels:
            return
        labels = (obj.get("metadata") or {}).get("labels") or {}
        for lk in self._index_labels:
            value = labels.get(lk)
            bucket = self._label_index[lk].get(value)
            if bucket is not None:
                bucket.discard(key)
                if not bucket:
                    del self._label_index[lk][value]

    def _index_replace(self, key: Tuple[str, str], old_obj: dict,
                       new_obj: dict) -> None:
        if not self._index_labels:
            return
        old_labels = (old_obj.get("metadata") or {}).get("labels") or {}
        new_labels = (new_obj.get("metadata") or {}).get("labels") or {}
        for lk in self._index_labels:
            old_v, new_v = old_labels.get(lk), new_labels.get(lk)
            if old_v == new_v:
                continue
            if old_v is not None:
                bucket = self._label_index[lk].get(old_v)
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self._label_index[lk][old_v]
            if new_v is not None:
                self._label_index[lk].setdefault(new_v, set()).add(key)

    def _indexed_keys(
            self, label_selector: Optional[Dict[str, str]]):
        """The smallest index bucket an exact-equality selector pins, or
        None when no indexed label participates (caller full-scans)."""
        if not label_selector or not self._index_labels:
            return None
        best = None
        for lk in self._index_labels:
            value = label_selector.get(lk)
            if not isinstance(value, str):
                continue
            bucket = self._label_index[lk].get(value, set())
            if best is None or len(bucket) < len(best):
                best = bucket
        return best

    def _notify(self, event_type: str, obj: dict) -> None:
        self._record_event(event_type, obj)
        listeners = list(self._listeners)
        if not listeners:
            return
        # ONE copy shared by every listener (informer, kubelet, index
        # wrappers): watch consumers treat delivered objects as
        # read-only by contract — the informer stores them in its cache
        # and hands them to handlers as immutable state — so a per-
        # listener copy only taxed the fan-out (measurably, at kubemark
        # scale: two listeners on the pod store doubled the fake tier's
        # hottest allocation).  The copy still isolates listeners from
        # the STORE's object, which later mutations replace wholesale.
        shared = _copy_obj(obj)
        det = _ownership._detector
        if det is None:
            for listener in listeners:
                listener(event_type, shared)
            return
        # detector armed: sample the shared copy (it is exactly the
        # object every listener aliases) and attribute each delivery so
        # a detection can name the listener that last received it
        meta = obj.get("metadata") or {}
        key = (f"{meta.get('namespace', 'default')}/"
               f"{meta.get('name', '')}"
               f"@{meta.get('resourceVersion', '')}")
        det.record(f"fake.{self.kind}", key, shared)
        for listener in listeners:
            det.note_delivery(f"fake.{self.kind}", key,
                              _ownership.handler_name(listener))
            listener(event_type, shared)

    def _record_event(self, event_type: str, obj: dict) -> None:
        # called with the cluster lock held (every mutation notifies
        # under it), so the window and floor advance atomically
        try:
            rv = int((obj.get("metadata") or {}).get("resourceVersion"))
        except (TypeError, ValueError):
            return
        # stored BY REFERENCE, deliberately: every store mutation
        # REPLACES the stored dict (update/patch/set_status build a new
        # object; GC below is copy-on-write), so a cached reference is
        # immutable once recorded — a deepcopy per mutation here would
        # tax every fake-cluster test in the suite.  changes_since
        # deep-copies on the way OUT.
        self._watch_cache.append((rv, event_type, obj))
        window = self._cluster.watch_cache_window
        while len(self._watch_cache) > window:
            evicted_rv, _, _ = self._watch_cache.popleft()
            self._cache_floor = max(self._cache_floor, evicted_rv)

    # -- windowed relist ---------------------------------------------------
    def changes_since(self, resource_version) -> Optional[tuple]:
        """``(changed_objects, deleted_objects, current_rv)`` covering
        everything after ``resource_version``, or None when the RV has
        fallen out of the watch-cache window (caller must full-LIST).
        Each key appears at most once, at its latest state — a delete
        followed by a recreate shows up as a change, not both."""
        try:
            rv = int(resource_version)
        except (TypeError, ValueError):
            return None
        with self._cluster.lock:
            if rv < self._cache_floor:
                return None
            latest: Dict[Tuple[str, str], Tuple[str, dict]] = {}
            for event_rv, event_type, obj in self._watch_cache:
                if event_rv <= rv:
                    continue
                meta = obj.get("metadata") or {}
                key = (meta.get("namespace", "default"),
                       meta.get("name", ""))
                latest[key] = (event_type, obj)
            changed = [_copy_obj(obj) for et, obj in latest.values()
                       if et != DELETED]
            deleted = [_copy_obj(obj) for et, obj in latest.values()
                       if et == DELETED]
            return changed, deleted, self._cluster.current_rv()

    def list_changes(self, since_rv) -> ListChanges:
        """Informer-facing relist: a windowed delta when ``since_rv``
        is still inside the watch cache, a full LIST (with the fresh
        high-water RV) otherwise."""
        delta = self.changes_since(since_rv)
        if delta is not None:
            changed, deleted, rv = delta
            return ListChanges(True, changed, deleted, rv)
        with self._cluster.lock:
            rv = self._cluster.current_rv()
        return ListChanges(False, self.list(), [], rv)

    # -- watch -------------------------------------------------------------
    def add_listener(self, fn: Callable[[str, dict], None]) -> None:
        """Register a watch callback invoked for every store mutation."""
        self._listeners.append(fn)

    def remove_listener(self, fn: Callable[[str, dict], None]) -> None:
        if fn in self._listeners:
            self._listeners.remove(fn)

    # -- CRUD --------------------------------------------------------------
    def create(self, namespace: str, obj: dict) -> dict:
        self._cluster.maybe_fault("create", self.kind)
        self._cluster.count_verb("create", self.kind)
        with self._cluster.lock:
            obj = _copy_obj(obj)
            meta = obj.setdefault("metadata", {})
            if namespace and meta.get("namespace") and meta["namespace"] != namespace:
                raise InvalidError(
                    f'namespace mismatch: request {namespace!r} vs object {meta["namespace"]!r}'
                )
            meta.setdefault("namespace", namespace or "default")
            if not meta.get("name") and meta.get("generateName"):
                meta["name"] = meta["generateName"] + uuid.uuid4().hex[:5]
            if not meta.get("name"):
                raise InvalidError(f"{self.kind}: metadata.name or generateName required")
            key = self._key(meta["namespace"], meta["name"])
            if key in self._objects:
                raise AlreadyExistsError(f'{self.kind} "{meta["name"]}" already exists')
            meta["uid"] = meta.get("uid") or str(uuid.uuid4())
            meta["resourceVersion"] = str(self._cluster.next_rv())
            meta.setdefault("creationTimestamp", _now_iso())
            self._objects[key] = obj
            self._index_add(key, obj)
            self._notify(ADDED, obj)
            return _copy_obj(obj)

    def get(self, namespace: str, name: str) -> dict:
        self._cluster.maybe_fault("get", self.kind)
        self._cluster.count_verb("get", self.kind)
        with self._cluster.lock:
            key = self._key(namespace, name)
            if key not in self._objects:
                raise NotFoundError(f'{self.kind} "{name}" not found')
            return _copy_obj(self._objects[key])

    def list(
        self,
        namespace: Optional[str] = None,
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[dict]:
        self._cluster.maybe_fault("list", self.kind)
        self._cluster.count_verb("list", self.kind)
        with self._cluster.lock:
            out = []
            indexed = self._indexed_keys(label_selector)
            if indexed is not None:
                # the bucket narrows the scan; the full selector (and
                # namespace) still decide membership authoritatively
                for key in sorted(indexed):
                    obj = self._objects.get(key)
                    if obj is None:
                        continue
                    if namespace and key[0] != namespace:
                        continue
                    if _match_selector(label_selector, obj):
                        out.append(_copy_obj(obj))
                return out
            for (ns, _), obj in sorted(self._objects.items()):
                if namespace and ns != namespace:
                    continue
                if _match_selector(label_selector, obj):
                    out.append(_copy_obj(obj))
            return out

    def update(self, obj: dict, subresource: Optional[str] = None) -> dict:
        """Replace an object; enforces resourceVersion optimistic locking."""
        self._cluster.maybe_fault("update", self.kind)
        self._cluster.count_verb("update", self.kind)
        with self._cluster.lock:
            obj = _copy_obj(obj)
            meta = obj.get("metadata") or {}
            key = self._key(meta.get("namespace", "default"), meta.get("name", ""))
            existing = self._objects.get(key)
            if existing is None:
                raise NotFoundError(f'{self.kind} "{meta.get("name")}" not found')
            sent_rv = meta.get("resourceVersion")
            if sent_rv and sent_rv != existing["metadata"]["resourceVersion"]:
                raise ConflictError(
                    f'{self.kind} "{meta.get("name")}": resourceVersion conflict'
                )
            if subresource == "status":
                # Status updates only replace .status.
                new_obj = _copy_obj(existing)
                new_obj["status"] = obj.get("status", {})
            else:
                new_obj = obj
                # Server-managed metadata survives updates.
                new_obj["metadata"]["uid"] = existing["metadata"]["uid"]
                new_obj["metadata"]["creationTimestamp"] = existing["metadata"].get(
                    "creationTimestamp"
                )
                if "status" not in new_obj and "status" in existing:
                    new_obj["status"] = existing["status"]
            new_obj["metadata"]["resourceVersion"] = str(self._cluster.next_rv())
            self._objects[key] = new_obj
            self._index_replace(key, existing, new_obj)
            self._notify(MODIFIED, new_obj)
            return _copy_obj(new_obj)

    def patch(self, namespace: str, name: str, patch: dict, subresource: Optional[str] = None) -> dict:
        """JSON-merge-patch: dicts merge recursively, nulls delete, lists
        replace.  A ``metadata.resourceVersion`` in the patch body acts as
        an optimistic-concurrency precondition exactly as on a real API
        server — mismatch raises ConflictError (409) — and through the
        status subresource only ``.status`` may change (the rv
        precondition is honored, everything else outside status is
        ignored), so the sim and http tiers exercise the same
        merge-patch + conflict-retry path the controller ships."""
        self._cluster.maybe_fault("patch", self.kind)
        self._cluster.count_verb("patch", self.kind)
        with self._cluster.lock:
            key = self._key(namespace, name)
            existing = self._objects.get(key)
            if existing is None:
                raise NotFoundError(f'{self.kind} "{name}" not found')
            sent_rv = (patch.get("metadata") or {}).get("resourceVersion")
            if sent_rv and sent_rv != existing["metadata"]["resourceVersion"]:
                raise ConflictError(
                    f'{self.kind} "{name}": resourceVersion conflict'
                )
            new_obj = _copy_obj(existing)
            if subresource == "status":
                body = patch["status"] if "status" in patch else {
                    k: v for k, v in patch.items() if k != "metadata"}
                patch = {"status": body}
            _merge(new_obj, patch)
            new_obj["metadata"]["resourceVersion"] = str(self._cluster.next_rv())
            self._objects[key] = new_obj
            self._index_replace(key, existing, new_obj)
            self._notify(MODIFIED, new_obj)
            return _copy_obj(new_obj)

    def delete(self, namespace: str, name: str) -> None:
        self._cluster.maybe_fault("delete", self.kind)
        self._cluster.count_verb("delete", self.kind)
        with self._cluster.lock:
            key = self._key(namespace, name)
            obj = self._objects.pop(key, None)
            if obj is None:
                raise NotFoundError(f'{self.kind} "{name}" not found')
            self._index_remove(key, obj)
            # a real apiserver mints a fresh resourceVersion for the
            # DELETED watch event; without it the watch cache could not
            # place the delete after the object's last modification and
            # windowed relists would silently resurrect deleted objects
            obj["metadata"]["resourceVersion"] = str(self._cluster.next_rv())
            self._notify(DELETED, obj)
        self._cluster._collect_garbage(obj)

    def set_status(self, namespace: str, name: str, status: dict) -> dict:
        """Test helper: overwrite .status directly (as a kubelet would).
        Counted as a ``status`` verb — at kubemark scale the kubelet's
        phase writes dominate apiserver load and must show in the
        accounting."""
        self._cluster.count_verb("status", self.kind)
        with self._cluster.lock:
            key = self._key(namespace, name)
            existing = self._objects.get(key)
            if existing is None:
                raise NotFoundError(f'{self.kind} "{name}" not found')
            new_obj = _copy_obj(existing)
            new_obj["status"] = status
            new_obj["metadata"]["resourceVersion"] = str(self._cluster.next_rv())
            self._objects[key] = new_obj
            self._notify(MODIFIED, new_obj)
            return _copy_obj(new_obj)


def _merge(dst: dict, patch: dict) -> None:
    for k, v in patch.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        elif v is None:
            dst.pop(k, None)
        else:
            dst[k] = _copy_obj(v)


class FakeCluster:
    """The whole fake API server: one store per resource kind.

    Kinds are addressed by their lowercase plural, matching REST paths:
    ``pods``, ``services``, ``events``, ``pytorchjobs``, ``podgroups``,
    ``endpoints``, ``leases``, ``nodes``.

    Nodes are cluster-scoped on a real API server; the fake keeps them
    in the same namespaced store machinery under the ``default``
    namespace (every accessor passes ``namespace=None``/``"default"``),
    which preserves the store interface the informers ride.
    """

    KINDS = {
        "pods": "Pod",
        "services": "Service",
        "endpoints": "Endpoints",
        "events": "Event",
        "pytorchjobs": "PyTorchJob",
        "podgroups": "PodGroup",
        "leases": "Lease",
        "nodes": "Node",
    }

    def __init__(self, fault_plan=None, watch_cache_window: int = 2048,
                 index_labels: Iterable[str] = ()):
        self.lock = make_rlock("fake.cluster")
        self._rv = 0
        # label keys every store indexes for LIST (see
        # FakeResourceStore._indexed_keys) — the kubemark tier passes
        # the job-name label so per-job pod/service lists stay O(gang)
        # at 50k pods; empty (the default) keeps the plain full scan.
        self.index_labels: Tuple[str, ...] = tuple(index_labels or ())
        # per-verb request accounting ("verb Kind" -> count): the sim
        # tier's equivalent of the stub server's response counters —
        # deterministic under the virtual clock, which is what lets the
        # --scale bench assert same-seed runs produce identical load.
        self._verb_counts: Dict[str, int] = {}
        self._verb_lock = make_lock("fake.verb-counts")
        # per-store watch-cache depth (see FakeResourceStore.changes_since):
        # how many recent mutations stay answerable as a windowed relist
        self.watch_cache_window = max(0, int(watch_cache_window))
        # k8s/faults.FaultPlan (assignable after construction): CRUD
        # calls consult it and raise the classified transient errors —
        # the sim tier's apiserver chaos.  "after" faults and watch
        # resets are http-tier-only (the fake's listeners are
        # synchronous calls; there is no response framing to tear).
        self.fault_plan = fault_plan
        self.stores: Dict[str, FakeResourceStore] = {
            plural: FakeResourceStore(self, kind) for plural, kind in self.KINDS.items()
        }

    def count_verb(self, verb: str, kind: str) -> None:
        key = f"{verb} {kind}"
        with self._verb_lock:
            self._verb_counts[key] = self._verb_counts.get(key, 0) + 1

    def verb_snapshot(self) -> Dict[str, int]:
        """Copy of the per-verb request counts (sorted for stable
        JSON/diff output)."""
        with self._verb_lock:
            return dict(sorted(self._verb_counts.items()))

    def next_rv(self) -> int:
        self._rv += 1
        return self._rv

    def current_rv(self) -> int:
        """The cluster-wide resourceVersion high-water mark (RVs are a
        single monotonic sequence, as on a real apiserver)."""
        return self._rv

    def maybe_fault(self, verb: str, resource: str) -> None:
        """Execute one CRUD call's injected fault (latency and/or a
        raised transient error).  Called BEFORE the store mutates and
        outside the cluster lock, so injected latency cannot serialize
        unrelated stores and an injected error never half-applies."""
        plan = self.fault_plan
        if plan is None:
            return
        if plan.error_when == "after":
            # loud, not silent: the torn-response (commit-then-fail)
            # case needs response framing to tear — only the stub
            # server models that.  Downgrading to a before-fault here
            # would run a DIFFERENT scenario than the test asked for
            # while its snapshot still claimed the error was injected.
            raise ValueError(
                "FaultPlan(error_when='after') is http-tier-only "
                "(StubApiServer); FakeCluster CRUD has no response to "
                "tear after the commit")
        fault = plan.on_request(verb, resource)
        if fault.delay:
            time.sleep(fault.delay)
        if fault.error is not None:
            raise fault.error

    def resource(self, plural: str) -> FakeResourceStore:
        """Store for ``plural``.  Unknown plurals raise (KeyError →
        the stub server's 404), matching a real API server with no such
        CRD installed; install new kinds explicitly via register()."""
        return self.stores[plural]

    def register(self, plural: str, kind: str) -> FakeResourceStore:
        """Install a new resource kind — the fake-server analogue of
        applying a CRD, so a second operator (a different job type over
        the generic runtime) can run against the same fake cluster."""
        store = self.stores.get(plural)
        if store is None:
            store = FakeResourceStore(self, kind)
            self.stores[plural] = store
        return store

    @property
    def pods(self) -> FakeResourceStore:
        return self.stores["pods"]

    @property
    def services(self) -> FakeResourceStore:
        return self.stores["services"]

    @property
    def events(self) -> FakeResourceStore:
        return self.stores["events"]

    @property
    def jobs(self) -> FakeResourceStore:
        return self.stores["pytorchjobs"]

    @property
    def podgroups(self) -> FakeResourceStore:
        return self.stores["podgroups"]

    @property
    def nodes(self) -> FakeResourceStore:
        return self.stores["nodes"]

    # -- owner-reference garbage collection --------------------------------
    def _collect_garbage(self, deleted_owner: dict) -> None:
        """Cascade-delete objects owned (with controller ref) by the object.

        Mirrors the kube-controller-manager GC that the reference e2e test
        relies on (test/e2e/v1/default/defaults.go:169-187).
        """
        owner_uid = (deleted_owner.get("metadata") or {}).get("uid")
        if not owner_uid:
            return
        for store in self.stores.values():
            doomed: List[Tuple[str, str]] = []
            with self.lock:
                for (ns, name), obj in list(store._objects.items()):
                    meta = obj.get("metadata") or {}
                    refs = meta.get("ownerReferences") or []
                    if not any(r.get("uid") == owner_uid for r in refs):
                        continue
                    # Real GC semantics: drop the dangling reference; the
                    # object is only deleted once no owners remain.
                    remaining = [r for r in refs if r.get("uid") != owner_uid]
                    if remaining:
                        # copy-on-write, never in place: past versions of
                        # a stored object may be referenced by the watch
                        # cache, which must keep the state AT its event
                        new_obj = _copy_obj(obj)
                        new_obj["metadata"]["ownerReferences"] = remaining
                        new_obj["metadata"]["resourceVersion"] = str(
                            self.next_rv())
                        store._objects[(ns, name)] = new_obj
                    else:
                        doomed.append((ns, name))
            for ns, name in doomed:
                try:
                    store.delete(ns, name)
                except NotFoundError:
                    pass
