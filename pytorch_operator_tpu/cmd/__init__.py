"""Operator process entry points (the reference's cmd/pytorch-operator.v1)."""
