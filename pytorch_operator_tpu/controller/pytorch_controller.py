"""The TPU-native PyTorchJob controller.

First-party equivalent of the reference's
pkg/controller.v1/pytorch/controller.go: event handlers feed a
rate-limited workqueue; worker threads run ``sync_job``; expectations gate
re-syncs; reconcile enforces backoff limits and active deadlines, drives
per-replica pod/service reconciliation and the status machine, and
persists status when it changed.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import List, Optional

from ..admission import (
    KIND_GROW,
    KIND_RESTART,
    AdmissionController,
    QuotaPolicy,
)
from ..analysis.witness import make_lock
from ..api.v1 import constants
from ..api.v1.defaults import set_defaults
from ..api.v1.types import PyTorchJob
from ..api.v1.validation import ValidationError, validate_spec
from ..disruption.handler import DisruptionHandlingMixin
from ..k8s import serde
from ..k8s.errors import (
    ApiError,
    CircuitOpenError,
    ConflictError,
    NotFoundError,
)
from ..k8s.resilience import RetryPolicy
from ..metrics import default_registry
from ..runtime.expectations import (
    expectation_pods_key,
    expectation_services_key,
)
from ..runtime import tracing
from ..runtime.informer import Informer, split_meta_namespace_key
from ..runtime.journal import EventJournal, StageClock
from ..runtime.lifecycle import JobLifecycleTracker
from ..runtime.job_controller import JobController, JobControllerConfig
from ..runtime.logger import logger_for_job, logger_for_key
from ..runtime.recorder import EVENT_TYPE_NORMAL, EVENT_TYPE_WARNING
from ..runtime.sharding import (
    SHARD_LEASE_PREFIX,
    EpochFencedSource,
    ShardManager,
    ring_epoch_of,
    ring_lease_name,
    shard_of,
    sharded_source,
)
from ..runtime.workqueue import WorkQueue, WorkQueueMetrics
from . import status as status_machine
from .job import (
    JobLifecycleMixin,
    get_total_effective_replicas,
    get_total_replicas,
    get_total_failed_replicas,
    parse_time,
)
from .pod import PodReconcilerMixin
from .service import ServiceReconcilerMixin


class PyTorchController(
    JobLifecycleMixin, PodReconcilerMixin, ServiceReconcilerMixin,
    DisruptionHandlingMixin, JobController
):
    def __init__(
        self,
        cluster,
        config: Optional[JobControllerConfig] = None,
        recorder=None,
        registry=None,
        tracer=None,
    ):
        super().__init__(cluster, config, recorder,
                         registry=registry or default_registry)
        self.logger = logging.getLogger(constants.CONTROLLER_NAME)
        # Per-reconcile spans (expectations-check / pod diff / creates /
        # status patch) land in this tracer's ring buffer; the operator
        # process serves them from /debug/traces.  The default tracer
        # keeps a modest ring and never logs slow reconciles — the CLI
        # passes one configured from --trace-buffer-size /
        # --slow-reconcile-threshold.
        self.tracer = tracer or tracing.Tracer(
            clock=self.mono_clock,
            wall=self.config.clock)
        # Reference parity: the unstructured job informer resyncs every 30s
        # (informer.go:24), factories every --resyc-period (options.go:24).
        # When resync is disabled (0, the unit-test default) the job
        # informer follows suit so tests stay deterministic.
        factory_resync = self.config.resync_period_seconds
        job_cap = self.config.informer_job_resync
        job_resync = (min(job_cap, factory_resync)
                      if factory_resync > 0 and job_cap > 0 else 0.0)
        # key -> UID of the incarnation whose sync last ran; lets sync_job
        # detect expectations raised by a dead incarnation (see sync_job)
        self._synced_uid: dict = {}
        self.job_informer = Informer(cluster.jobs, resync_period=job_resync,
                                     coalesce=self._coalesce_job_event,
                                     name="pytorchjobs",
                                     registry=registry or default_registry,
                                     clock=self.mono_clock,
                                     propagation=self.propagation,
                                     budget=self.timebudget)
        self.job_informer.add_event_handler(
            on_add=self.add_job, on_update=self.update_job, on_delete=self._job_deleted
        )
        registry = registry or default_registry
        self.jobs_created_counter = registry.counter(
            "pytorch_operator_jobs_created_total", "Counts number of PyTorch jobs created"
        )
        self.jobs_deleted_counter = registry.counter(
            "pytorch_operator_jobs_deleted_total", "Counts number of PyTorch jobs deleted"
        )
        self.jobs_successful_counter = registry.counter(
            "pytorch_operator_jobs_successful_total", "Counts number of PyTorch jobs successful"
        )
        self.jobs_failed_counter = registry.counter(
            "pytorch_operator_jobs_failed_total", "Counts number of PyTorch jobs failed"
        )
        self.jobs_restarted_counter = registry.counter(
            "pytorch_operator_jobs_restarted_total", "Counts number of PyTorch jobs restarted"
        )
        # Status merge-patches carry a resourceVersion precondition; 409s
        # are retried once with a fresh base.  Counting them makes
        # multi-writer contention visible instead of silently paying the
        # extra GET (ROADMAP conflict-telemetry item).
        self.status_conflicts_counter = registry.counter(
            "pytorch_operator_status_patch_conflicts_total",
            "Counts resourceVersion conflicts (409) hit while patching "
            "job status; each costs one base re-read and retry",
        )
        # Conflict retries ride the same RetryPolicy machinery as the
        # REST client's transient retries (k8s/resilience.py) — the 409
        # loop differs only in its hooks: refetch-resourceVersion-and-
        # re-diff instead of backoff (conflicts are contention, not
        # outage; sleeping would just widen the stale window).
        self.status_retry = RetryPolicy(max_attempts=2)
        # One sync_job pass, labeled by how it ended: success (forget),
        # error (requeued with backoff), requeue (retry without an
        # error, e.g. an unparseable key).  The per-result split is what
        # makes a hot-looping job visible: its error series climbs while
        # success stays flat.
        self.sync_duration = registry.histogram_vec(
            "pytorch_operator_reconcile_duration_seconds",
            "Wall time of one sync_job pass, by result",
            ("result",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0),
        )
        # Flight recorder: bounded structured journal of control-plane
        # EVENTS (lease transitions, ring flips, admission verdicts,
        # disruption detections), served from /debug/events.  Created
        # before the disruption watcher and the ShardManager so both
        # (and every elector the manager mints) write here.  Same clock
        # pair as the tracer/lifecycle: deterministic under the
        # simulator.
        self.journal = EventJournal(
            capacity=self.config.journal_capacity,
            clock=self.mono_clock,
            wall=self.config.clock,
            replica_id=self.config.replica_id or "")
        self.journal.dropped_counter = registry.counter(
            "pytorch_operator_journal_dropped_total",
            "Flight-recorder events evicted from the bounded "
            "/debug/events ring before being read (journal loss under "
            "load)")
        # stage-timestamp ledger for the shard-acquisition path, keyed
        # by shard Lease name: CAS-acquired seeds it, informer-sync and
        # first-reconcile observe their deltas from it
        self._stage_clock = StageClock(clock=self.mono_clock)
        self.handoff_stage_duration = registry.histogram_vec(
            "pytorch_operator_shard_handoff_stage_seconds",
            "Seconds from shard-Lease CAS acquisition to each later "
            "handoff stage on this replica (informer sync, first "
            "reconcile)",
            ("stage",),
            buckets=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                     2.5, 5.0, 10.0, 30.0),
        )
        # Disruption subsystem (metrics always registered; the watcher
        # only when --enable-disruption-handling built a node informer).
        self.init_disruption_handling(registry)
        # Active-active sharded control plane (--shard-count > 1): no
        # leader election — every replica owns as many shard Leases as
        # fairness allows and runs informers + a workqueue per owned
        # shard.  The global job/pod/service informers above are never
        # STARTED in sharded mode; the admission informer (all jobs, no
        # selector) only stamps the shard label on new jobs whose hash
        # lands in an owned shard and never enqueues.
        self.shard_manager = None
        self._admission_informer = None
        self._stop_event = None
        self._shard_workers = 1
        self.replica_id = self.config.replica_id or ""
        if self.config.shard_count > 1:
            import uuid as _uuid

            self.replica_id = (self.config.replica_id
                               or f"replica-{_uuid.uuid4().hex[:8]}")
            self.journal.replica_id = self.replica_id  # uuid minted above
            registry.gauge(
                "pytorch_operator_owned_shards",
                "Shard Leases this replica currently holds "
                "(sums to --shard-count across live replicas)",
            ).set_function(lambda: len(self.owned_shards()))
            self._shard_jobs_gauge = registry.gauge_vec(
                "pytorch_operator_shard_jobs",
                "PyTorchJobs in this replica's per-shard informer cache "
                "(0 for shards it does not own)",
                ("shard",))
            self._admission_informer = Informer(cluster.jobs,
                                                clock=self.mono_clock)
            self._admission_informer.add_event_handler(
                on_add=self._admit_job,
                on_update=lambda _old, new: self._admit_job(new))
            self.shard_manager = ShardManager(
                cluster.resource("leases"), self.replica_id,
                self.config.shard_count,
                lease_duration=self.config.shard_lease_duration,
                renew_interval=self.config.shard_renew_interval,
                on_acquired=self._on_shard_acquired,
                on_released=self._on_shard_released,
                on_acquired_next=self._on_next_shard_acquired,
                on_released_next=self._on_next_shard_released,
                on_ring_flipped=self._on_ring_flipped,
                migration_sweep=self._run_migration_sweep,
                load_provider=self._shard_loads,
                clock=self.config.clock or time.monotonic,
                journal=self.journal,
                budget=self.timebudget)
            # live-reshard observability: the 0/1 migration-window gauge
            # plus the ring epoch itself, so a scrape can tell WHICH
            # ring a replica is reconciling for while the window is open
            registry.gauge(
                "pytorch_operator_resharding_in_progress",
                "1 while a live shard-count migration is in flight on "
                "this replica (old and new rings coexist), 0 otherwise",
            ).set_function(
                lambda: 1 if self.resharding_in_progress() else 0)
            registry.gauge(
                "pytorch_operator_ring_epoch",
                "Current shard-ring epoch this replica reconciles for "
                "(bumps by one at every completed live reshard)",
            ).set_function(lambda: (self.shard_manager.ring_epoch
                                    if self.shard_manager else 0))
        # Fleet observability: per-job lifecycle timelines (milestones
        # plus restart/resize/reshard segments) recorded from the
        # reconcile path, served from /debug/jobs, exported as the
        # phase-duration histogram.  Clocked exactly like the tracer so
        # timelines captured under the simulator are deterministic.
        self.lifecycle = JobLifecycleTracker(
            registry=registry,
            clock=self.mono_clock,
            wall=self.config.clock,
            max_jobs=self.config.job_timeline_max_jobs,
            replica_id=self.replica_id)
        # Multi-tenant admission (--enable-admission): per-namespace
        # quota ledger + fair-share DRR release queue, offered every
        # non-terminal job by the gate in reconcile before any
        # pod/service work.  None (the default) keeps the gate
        # pass-through and never writes a Queued condition.  In sharded
        # mode each shard owner runs its own ledger over the jobs it
        # owns, rebuilt lazily from Queued conditions after a handover
        # (_on_shard_released forgets; the new owner's LIST re-offers).
        self.admission = None
        # job key -> last journaled admission verdict: the gate runs on
        # every sync, the flight recorder wants TRANSITIONS
        self._admission_verdicts: dict = {}
        if self.config.enable_admission:
            self.admission = AdmissionController(
                QuotaPolicy(default_jobs=self.config.quota_jobs,
                            default_chips=self.config.quota_chips,
                            overrides=self.config.quota_overrides),
                cluster_max_jobs=self.config.cluster_max_jobs,
                cluster_max_chips=self.config.cluster_max_chips,
                clock=self.config.clock or time.time,
                registry=registry,
                preempt=self._admission_preempt,
                on_release=self._admission_released)
        # trace-loss accounting: ring evictions in the tracer become a
        # counter, so /debug/traces under-reporting is a scrapeable fact
        self.tracer.dropped_counter = registry.counter(
            "pytorch_operator_traces_dropped_total",
            "Completed reconcile traces evicted from the bounded "
            "/debug/traces ring before being read (trace loss under "
            "load)")
        # Handlers are attributes so tier-2 tests can stub the status write
        # (reference controller_test.go:214-217).
        self.update_status_handler = self._update_job_status
        self.delete_job_handler = self._delete_job

    # -- gang policy -------------------------------------------------------
    def gang_scheduling_enabled(self, job: PyTorchJob) -> bool:
        """Gang semantics apply when the flag is set OR the job requests
        TPU chips (tpu_env.job_requests_tpu — slices are all-or-nothing)."""
        if self.config.enable_gang_scheduling:
            return True
        from .tpu_env import job_requests_tpu

        return self.config.tpu_auto_gang and job_requests_tpu(job)

    # -- plumbing ----------------------------------------------------------
    def _coalesce_job_event(self, key: str, old: dict, new: dict,
                            queue=None) -> bool:
        """Informer burst coalescing for the job informer: a MODIFIED
        event for a key that is already dirty in the workqueue updates
        the store but skips the handler dispatch — the pending sync reads
        the fresh store, so the dispatch could only re-enqueue a key the
        queue would dedup anyway.  Events that change .spec or the
        deletionTimestamp are never coalesced: update_job reschedules the
        ActiveDeadlineSeconds wake-up on spec changes, and that timer
        must not be lost to a burst.  ``queue`` is the shard queue when
        a per-shard informer consults the hook."""
        if old.get("spec") != new.get("spec"):
            return False
        if (old.get("metadata") or {}).get("deletionTimestamp") != (
                (new.get("metadata") or {}).get("deletionTimestamp")):
            return False
        return (queue or self.work_queue).is_dirty(key)

    # -- sharding ----------------------------------------------------------
    def owned_shards(self):
        if self.shard_manager is None:
            return set()
        return self.shard_manager.owned_shards()

    def resharding_in_progress(self) -> bool:
        return (self.shard_manager is not None
                and self.shard_manager.resharding_in_progress())

    def _ring_epochs(self):
        mgr = self.shard_manager
        if mgr is None:
            return 0, None
        return mgr.ring_epoch, mgr.next_ring_epoch

    def _ring_target(self):
        """(shard_count, epoch) newly stamped jobs are assigned to: the
        TARGET ring while a migration is in flight — stamping straight
        into the new ring is what makes the sweep converge — the
        current ring otherwise."""
        mgr = self.shard_manager
        if mgr is None:
            return 1, 0
        if mgr.next_shard_count is not None:
            return mgr.next_shard_count, int(mgr.next_ring_epoch or 0)
        return mgr.shard_count, mgr.ring_epoch

    def _target_owned(self):
        """Shards this replica owns ON THE TARGET RING (next-ring
        leases during a migration, current otherwise) — the admission
        ownership gate."""
        mgr = self.shard_manager
        if mgr is None:
            return set()
        if mgr.next_shard_count is not None:
            return mgr.owned_next_shards()
        return mgr.owned_shards()

    @staticmethod
    def _ring_labels(shard: int, epoch: int):
        """The label pair identifying (shard, ring): epoch 0 is label
        absence (legacy objects parse unchanged), epochs >= 1 carry the
        ring-epoch label next to the shard index."""
        labels = {constants.LABEL_SHARD: str(shard)}
        if epoch > 0:
            labels[constants.LABEL_RING_EPOCH] = str(epoch)
        return labels

    @staticmethod
    def _needs_stamp(obj: dict, epoch: int) -> bool:
        labels = (obj.get("metadata") or {}).get("labels") or {}
        return (constants.LABEL_SHARD not in labels
                or ring_epoch_of(obj) != epoch)

    def _admit_job(self, obj: dict) -> None:
        """Admission stamping: a job without a shard label — or still
        carrying a retired ring's stamp — is assigned
        ``shard_of(namespace, uid)`` under the TARGET ring, by the
        replica that OWNS that target shard (every replica computes the
        same index, so exactly one stamps; a lost race is a no-op merge
        patch).  The label then routes the job into the owner's
        shard-filtered informers, which is where reconciliation
        begins."""
        meta = obj.get("metadata") or {}
        count, epoch = self._ring_target()
        if not self._needs_stamp(obj, epoch):
            return
        shard = shard_of(meta.get("namespace", "default"),
                         meta.get("uid", ""), count)
        if shard not in self._target_owned():
            return
        body: dict = {"metadata": {"labels": self._ring_labels(shard,
                                                               epoch)}}
        # cross-replica join key: the ADMITTING replica's context rides
        # the job as an annotation, stamped once — re-stamps onto later
        # rings keep the original admission context intact
        annotations = meta.get("annotations") or {}
        if constants.ANNOTATION_TRACE_CONTEXT not in annotations:
            body["metadata"]["annotations"] = {
                constants.ANNOTATION_TRACE_CONTEXT: json.dumps(
                    {"replica": self.replica_id, "shard": shard,
                     "epoch": epoch}, sort_keys=True)}
        restamp = constants.LABEL_SHARD in (meta.get("labels") or {})
        try:
            self.cluster.jobs.patch(
                meta.get("namespace", "default"), meta.get("name", ""),
                body)
        except ApiError:
            return  # job gone / apiserver blip: the next event retries
        key = f"{meta.get('namespace', 'default')}/{meta.get('name', '')}"
        if restamp:
            # moving rings: the job's key may sit ownerless until the
            # new ring's owner picks it up — an annotated segment, not
            # a milestone (the segment closes at the next owned sync)
            self.lifecycle.begin_segment(
                key, "reshard", uid=meta.get("uid", ""),
                attrs={"shard": shard, "epoch": epoch})
        else:
            self.lifecycle.record(
                key, "shard_stamped", uid=meta.get("uid", ""),
                attrs={"shard": shard, "epoch": epoch})
        self._stamp_existing_children(meta, shard, epoch)

    def _stamp_existing_children(self, job_meta: dict, shard: int,
                                 epoch: int = 0) -> None:
        """Migration path: a job admitted BEFORE sharding was enabled
        (or re-stamped onto a new ring) already has children carrying
        no — or the old ring's — shard labels, which the shard-filtered
        pod/service informers would never see — their status
        transitions would stop re-enqueuing the job.  Stamp the ring
        labels onto every existing child with its job (new children
        inherit them at creation; for freshly admitted jobs this LIST
        finds nothing)."""
        namespace = job_meta.get("namespace", "default")
        selector = self.gen_labels(job_meta.get("name", ""))
        labels = self._ring_labels(shard, epoch)
        patch = {"metadata": {"labels": labels}}
        for client in (self.cluster.pods, self.cluster.services):
            try:
                children = client.list(namespace=namespace,
                                       label_selector=selector)
            except ApiError:
                continue
            for child in children:
                child_labels = ((child.get("metadata") or {}).get(
                    "labels") or {})
                if all(child_labels.get(k) == v
                       for k, v in labels.items()):
                    continue
                child_meta = child.get("metadata") or {}
                try:
                    client.patch(namespace, child_meta.get("name", ""),
                                 patch)
                except ApiError:
                    pass  # child raced deletion / blip: resync heals

    def _stamp_pending_jobs(self, shard: Optional[int] = None) -> None:
        """Label sweep on shard acquisition: jobs admitted while their
        shard had no owner (or whose owner died before stamping, or
        that still carry a retired ring's labels after a missed sweep
        window) sit in the admission informer's store — re-admit
        everything; ``_admit_job``'s target-ring hash and ownership
        gate make each call stamp exactly the jobs that land in a shard
        this replica owns."""
        informer = self._admission_informer
        if informer is None:
            return
        _count, epoch = self._ring_target()
        for obj in informer.store.list():
            if self._needs_stamp(obj, epoch):
                self._admit_job(obj)

    #: bounded re-stamp batch per migration-sweep call: keeps one sweep
    #: pass short relative to the migration Lease's renew interval, so
    #: an aborted sweep loses at most one batch of progress (the next
    #: fence holder resumes idempotently)
    MIGRATION_SWEEP_BATCH = 50

    def _run_migration_sweep(self, old_count: int, new_count: int,
                             new_epoch: int) -> bool:
        """The fenced re-stamp sweep (runs ONLY on the migration-Lease
        holder, from the shard manager's tick): move every job still
        missing the target ring's labels onto it, children included,
        exactly as admission stamping does.  Bounded and idempotent —
        returns True only when a full pass over the admission store
        found nothing left to move."""
        informer = self._admission_informer
        if informer is None or not informer.has_synced():
            return False  # can't prove completeness from an unsynced cache
        stamped = 0
        for obj in informer.store.list():
            if not self._needs_stamp(obj, new_epoch):
                continue
            meta = obj.get("metadata") or {}
            shard = shard_of(meta.get("namespace", "default"),
                             meta.get("uid", ""), new_count)
            try:
                self.cluster.jobs.patch(
                    meta.get("namespace", "default"),
                    meta.get("name", ""),
                    {"metadata": {"labels": self._ring_labels(
                        shard, new_epoch)}})
            except NotFoundError:
                continue  # deleted mid-sweep: nothing to migrate
            except ApiError:
                return False  # blip: resume next tick (idempotent)
            self.lifecycle.begin_segment(
                f"{meta.get('namespace', 'default')}/"
                f"{meta.get('name', '')}",
                "reshard", uid=meta.get("uid", ""),
                attrs={"shard": shard, "epoch": new_epoch})
            self._stamp_existing_children(meta, shard, new_epoch)
            stamped += 1
            if stamped >= self.MIGRATION_SWEEP_BATCH:
                self.journal.record("reshard_sweep", epoch=new_epoch,
                                    stamped=stamped, done=False)
                return False  # bounded batch; resume next tick
        if stamped:
            # full pass with work done: the NEXT clean pass flips
            self.journal.record("reshard_sweep", epoch=new_epoch,
                                stamped=stamped, done=False)
        return stamped == 0

    def _on_shard_acquired(self, shard: int) -> None:
        epoch = self.shard_manager.ring_epoch if self.shard_manager else 0
        runtime = _ShardRuntime(self, shard, workers=self._shard_workers,
                                epoch=epoch)
        with self._shard_lock:
            self._shard_runtimes[shard] = runtime
        # per-shard nodeName index registered BEFORE the informer
        # starts, so the initial LIST replay populates it — the union
        # is how sharded disruption handling resolves a disrupted
        # node's pods without cluster-wide LISTs
        if self._pod_index_union is not None:
            from ..disruption.watcher import PodNodeIndex

            runtime.pod_index = PodNodeIndex(runtime.pod_informer)
            self._pod_index_union.add_index(shard, runtime.pod_index)
        # registered BEFORE informers start: the very first ADDED must
        # already route into this shard's queue
        runtime.start(self._stop_event or threading.Event())
        self._shard_jobs_gauge.labels(shard=str(shard)).set_function(
            lambda s=shard: self._shard_store_size(s))
        self.logger.info("replica %s acquired shard %d (epoch %d)",
                         self.replica_id, shard, epoch)
        self._stamp_pending_jobs(shard)
        # disruptions that struck while this shard had NO owner were
        # dropped by every replica's ownership gate — replay current
        # node state so the newly-owned jobs get their proactive
        # restart (live-resolved, so already-handled gangs don't match)
        if self.disruption_watcher is not None:
            self.disruption_watcher.replay_flagged()

    def _on_shard_released(self, shard: int) -> None:
        with self._shard_lock:
            runtime = self._shard_runtimes.pop(shard, None)
        if self._pod_index_union is not None:
            self._pod_index_union.remove_index(shard)
        if runtime is not None:
            if self.admission is not None:
                # the shard's jobs move to another owner whose ledger
                # rebuilds from their Queued conditions — keeping ours
                # would double-count their quota on a later reacquire
                self.admission.forget_keys(
                    runtime.job_informer.store.keys())
            runtime.stop()
            self.logger.info("replica %s released shard %d",
                             self.replica_id, shard)

    def _on_next_shard_acquired(self, shard: int) -> None:
        """Acquired a shard of the TARGET ring mid-migration: run its
        runtime alongside the old ring's (fresh ListWatches fenced on
        the new epoch's selector), keyed into the next-ring table until
        the flip promotes it."""
        mgr = self.shard_manager
        epoch = int(mgr.next_ring_epoch or 0) if mgr else 0
        runtime = _ShardRuntime(self, shard, workers=self._shard_workers,
                                epoch=epoch)
        with self._shard_lock:
            self._next_shard_runtimes[shard] = runtime
        if self._pod_index_union is not None:
            from ..disruption.watcher import PodNodeIndex

            runtime.pod_index = PodNodeIndex(runtime.pod_informer)
            self._pod_index_union.add_index(f"e{epoch}:{shard}",
                                            runtime.pod_index)
        runtime.start(self._stop_event or threading.Event())
        self.logger.info(
            "replica %s acquired next-ring shard %d (epoch %d)",
            self.replica_id, shard, epoch)
        self._stamp_pending_jobs(shard)

    def _on_next_shard_released(self, shard: int) -> None:
        with self._shard_lock:
            runtime = self._next_shard_runtimes.pop(shard, None)
        if runtime is not None:
            if self._pod_index_union is not None:
                self._pod_index_union.remove_index(
                    f"e{runtime.epoch}:{shard}")
            runtime.stop()
            self.logger.info("replica %s released next-ring shard %d",
                             self.replica_id, shard)

    def _on_ring_flipped(self, epoch: int, count: int) -> None:
        """The migration's commit point (old-ring runtimes are already
        torn down — the manager releases old shards first): promote
        every next-ring runtime into the live routing table and adopt
        the new geometry."""
        with self._shard_lock:
            promoted = dict(self._next_shard_runtimes)
            self._next_shard_runtimes.clear()
            self._shard_runtimes.update(promoted)
        self.config.shard_count = count
        for shard, runtime in promoted.items():
            if (self._pod_index_union is not None
                    and runtime.pod_index is not None):
                self._pod_index_union.remove_index(f"e{epoch}:{shard}")
                self._pod_index_union.add_index(shard, runtime.pod_index)
            self._shard_jobs_gauge.labels(shard=str(shard)).set_function(
                lambda s=shard: self._shard_store_size(s))
        self.logger.info(
            "replica %s flipped to ring epoch %d (%d shards, "
            "%d runtimes promoted)",
            self.replica_id, epoch, count, len(promoted))

    def _shard_loads(self):
        """{shard: workqueue depth} across owned runtimes — the
        heartbeat Lease's load payload (autoscaler input)."""
        with self._shard_lock:
            runtimes = dict(self._shard_runtimes)
        return {shard: float(len(runtime.queue))
                for shard, runtime in runtimes.items()}

    def unsynced_shards(self) -> List[str]:
        """Shard runtimes still replaying their initial LIST, as
        display keys (``"2"`` current ring, ``"e2:1"`` next ring) —
        the degraded-readiness detail."""
        with self._shard_lock:
            current = dict(self._shard_runtimes)
            nxt = dict(self._next_shard_runtimes)
        out = [str(shard) for shard, rt in sorted(current.items())
               if not rt.synced()]
        out += [f"e{rt.epoch}:{shard}" for shard, rt in sorted(nxt.items())
                if not rt.synced()]
        return out

    def base_informers_synced(self) -> bool:
        """The non-negotiable half of sharded readiness: admission and
        node informers.  Per-shard sync state is reported as DEGRADED
        (200) instead — see ``unsynced_shards`` — because shard
        acquisition is routine (rebalances, reshards) and flapping the
        whole replica unready on every handoff would eject it from
        service just when it picked up work."""
        if self.shard_manager is None:
            return self.informers_synced()
        informers = []
        if self._admission_informer is not None:
            informers.append(self._admission_informer)
        if self.node_informer is not None:
            informers.append(self.node_informer)
        return all(i.has_synced() for i in informers)

    def _shard_store_size(self, shard: int) -> int:
        with self._shard_lock:
            runtime = self._shard_runtimes.get(shard)
        if runtime is None:
            return 0
        return len(runtime.job_informer.store.keys())

    def _job_from_unstructured(self, obj: dict) -> PyTorchJob:
        """informer.go:83-104: convert + validate."""
        job = PyTorchJob.from_dict(obj)
        validate_spec(job.spec)
        return job

    def _get_job_from_cache(self, namespace: str, name: str) -> Optional[dict]:
        key = f"{namespace}/{name}"
        obj = self.job_informer.store.get_by_key(key)
        if obj is None:
            for runtime in self._shard_runtime_snapshot():
                obj = runtime.job_informer.store.get_by_key(key)
                if obj is not None:
                    break
        return obj

    def _job_deleted(self, obj: dict) -> None:
        # Clear the dead incarnation's expectations HERE, in the DELETED
        # callback, not only in the sync-time cache-miss branch: a
        # delete followed by an immediate recreate under the same key
        # can re-populate the cache before any worker observes the miss,
        # leaving stale unfulfilled expectations to gate the new job
        # until the 5-minute TTL.  Safe against the new incarnation's
        # own expectations: the informer dispatches events in order, so
        # the recreate's ADDED (and any sync that can see it in the
        # cache) strictly follows this callback.  Surfaced by the churn
        # scenario (pytorch_operator_tpu/k8s/churn.py).
        #
        # Residual race (informer thread vs sync workers): a worker
        # already mid-reconcile of the OLD incarnation can call
        # expect_creations after this clear, re-raising a stale
        # expectation.  That case is closed at sync time — the next sync
        # of the key compares the cached object's UID against the one
        # whose sync raised the expectations (_synced_uid) and clears
        # again on mismatch; the workqueue's one-worker-per-key rule
        # makes that check race-free.
        meta = obj.get("metadata") or {}
        key = f"{meta.get('namespace', '')}/{meta.get('name', '')}"
        for rtype in constants.VALID_REPLICA_TYPES:
            self.expectations.delete_expectations(
                expectation_pods_key(key, rtype))
            self.expectations.delete_expectations(
                expectation_services_key(key, rtype))
        self.enqueue_job(obj)

    def _update_job_status(self, job: PyTorchJob) -> None:
        """Persist the status delta as a JSON-merge-patch against the
        status subresource instead of PUTting the whole object
        (controller.go:336's UpdateStatus round-trips the full job; the
        churn bench showed those bodies dominating status-write cost).

        The diff base is the informer-cached object — the same copy this
        sync parsed — and the patch carries that copy's resourceVersion
        as an optimistic precondition, so a concurrent writer can't be
        silently clobbered by the wholesale ``conditions`` list replace.
        On a 409 the base is re-read (informer cache first; a live GET
        when the cache hasn't caught up yet) and the patch retried once;
        a second conflict propagates so the sync requeues with backoff.
        """
        namespace = job.metadata.namespace
        name = job.metadata.name
        with tracing.span("status-patch", job=f"{namespace}/{name}"):
            self._patch_job_status(job, namespace, name)

    def _patch_job_status(self, job: PyTorchJob, namespace: str,
                          name: str) -> None:
        # serialize only .status — this is the hottest write path, and
        # to_dict(job) would re-serde the full pod templates per patch
        new_status = serde.to_dict(job.status)
        base = {"cached": self._get_job_from_cache(namespace, name)}

        def patch_once():
            old_status = (base["cached"] or {}).get("status") or {}
            diff = status_machine.status_merge_diff(old_status, new_status)
            if not diff:
                return
            body: dict = {"status": diff}
            rv = ((base["cached"] or {}).get("metadata") or {}).get(
                "resourceVersion")
            if rv:
                body["metadata"] = {"resourceVersion": rv}
            try:
                self.cluster.jobs.patch(
                    namespace, name, body, subresource="status")
            except ConflictError:
                self.status_conflicts_counter.inc()
                raise
            self.propagation.note_commit(f"{namespace}/{name}")

        def refetch_base(_err, _attempt):
            # conflict: re-read the authoritative base so the next
            # attempt re-diffs against (and preconditions on) the
            # winner's resourceVersion
            rv = ((base["cached"] or {}).get("metadata") or {}).get(
                "resourceVersion")
            fresh = self._get_job_from_cache(namespace, name)
            fresh_rv = ((fresh or {}).get("metadata") or {}).get(
                "resourceVersion")
            if fresh is not None and fresh_rv != rv:
                base["cached"] = fresh
            else:
                # cache hasn't observed the conflicting write yet:
                # one live read gets the authoritative base
                base["cached"] = self.cluster.jobs.get(namespace, name)

        try:
            self.status_retry.run(
                patch_once,
                retryable=lambda e: isinstance(e, ConflictError),
                on_retry=refetch_base, backoff=False)
        except NotFoundError:
            return  # job deleted under us; nothing to persist

    # -- disruption hooks --------------------------------------------------
    def update_pod(self, old_pod: dict, new_pod: dict) -> None:
        """Pod informer hook: detection source 2 (DisruptionTarget
        conditions) rides the normal update stream; the base bookkeeping
        runs unchanged."""
        if self.disruption_handling_enabled():
            self.note_pod_disruption(new_pod)
        super().update_pod(old_pod, new_pod)

    # -- lifecycle ---------------------------------------------------------
    def start_informers(self) -> None:
        self.job_informer.start()
        self.pod_informer.start()
        self.service_informer.start()
        if self.node_informer is not None:
            self.node_informer.start()

    def informers_synced(self) -> bool:
        """True once every informer completed its initial LIST — the
        readiness condition /readyz reports (a controller reconciling
        from an unsynced cache would delete pods it simply hasn't seen
        yet).  Sharded: the admission informer plus every OWNED shard's
        informer set (a replica owning nothing is vacuously synced)."""
        if self.shard_manager is not None:
            informers = []
            if self._admission_informer is not None:
                informers.append(self._admission_informer)
            if self.node_informer is not None:
                informers.append(self.node_informer)
            return (all(i.has_synced() for i in informers)
                    and all(rt.synced()
                            for rt in self._shard_runtime_snapshot()))
        informers = [self.job_informer, self.pod_informer,
                     self.service_informer]
        if self.node_informer is not None:
            informers.append(self.node_informer)
        return all(i.has_synced() for i in informers)

    def timebudget_snapshot(self) -> dict:
        """/debug/timebudget payload: this replica's time-bucket
        accounting plus the propagation ledger's recent per-event stage
        decompositions.  Byte-deterministic under the simulator."""
        snap = self.timebudget.snapshot()
        snap["propagation"] = self.propagation.snapshot()
        return snap

    def run(self, threadiness: int = 1, stop_event: Optional[threading.Event] = None):
        """controller.go:185-213.  Sharded mode starts the admission
        informer + shard manager instead of the global informers and
        worker pool; each acquired shard brings its own informers,
        workqueue and workers (``ceil(threadiness / shard_count)``
        each, so a replica owning every shard fields ~threadiness
        workers total)."""
        stop_event = stop_event or threading.Event()
        if self.shard_manager is not None:
            self._stop_event = stop_event
            self._shard_workers = max(
                1, -(-threadiness // self.config.shard_count))
            self._admission_informer.start()
            if self.node_informer is not None:
                self.node_informer.start()
            self.shard_manager.start(stop_event)
            return []
        self.start_informers()
        workers = []
        for _ in range(threadiness):
            t = threading.Thread(target=self._run_worker, args=(stop_event,), daemon=True)
            t.start()
            workers.append(t)
        return workers

    def _run_worker(self, stop_event: threading.Event) -> None:
        while not stop_event.is_set():
            if not self.process_next_work_item(
                    timeout=self.config.worker_poll_interval):
                return

    def process_next_work_item(self, timeout: Optional[float] = None,
                               queue=None, runtime=None) -> bool:
        """controller.go:222-274.  ``queue`` selects a shard's
        workqueue (sharded workers pass their own); default is the
        controller-wide queue.  ``runtime`` is the calling shard
        runtime, if any — its first completed pass stamps the
        first-reconcile handoff stage."""
        queue = queue if queue is not None else self.work_queue
        with self.timebudget.measure("queue_idle"):
            key, shutdown = queue.get(timeout=timeout)
        if shutdown:
            return False
        if key is None:
            return True
        try:
            start = self.mono_clock()
            self.propagation.note_reconcile_start(key)
            epoch = (self.shard_manager.ring_epoch
                     if self.shard_manager is not None else 0)
            # replica + ring epoch on the ROOT span: the fleet collector
            # stitches one job's traces across replicas by these attrs
            with self.timebudget.measure("reconcile"), \
                    self.tracer.trace("reconcile", key=key,
                                      replica=self.replica_id,
                                      ring_epoch=epoch) as tspan:
                forget, err = self.sync_job(key)
                result = ("error" if err is not None
                          else "success" if forget else "requeue")
                tspan.set_attr("result", result)
            # close this key's propagation record: the patch the sync
            # issued (if any) already stamped the commit
            self.propagation.complete(key, result=result)
            # exemplar: the duration sample remembers which trace filled
            # its bucket, so a slow bucket on an OpenMetrics scrape
            # resolves directly to its /debug/traces entry
            self.sync_duration.labels(result=result).observe(
                self.mono_clock() - start,
                exemplar={"trace_id": tspan.trace_id})
            self.lifecycle.record(key, "first_reconcile",
                                  trace_id=tspan.trace_id)
            self.lifecycle.note_sync(key, trace_id=tspan.trace_id,
                                     result=result, ring_epoch=epoch)
            if runtime is not None:
                runtime.note_first_reconcile(key=key, result=result)
            if result == "success":
                # a re-stamped job's first owned sync under the new
                # ring ends its ownerless window
                self.lifecycle.end_segment(key, "reshard")
            if err is None and forget:
                queue.forget(key)
            elif isinstance(err, CircuitOpenError):
                # the apiserver breaker is open: pace this key at the
                # breaker's half-open cadence instead of rate-limited —
                # every fail-fast would otherwise count as a backoff
                # strike, and the per-key exponential would overshoot
                # the apiserver's recovery by multiples of the outage
                logger_for_key(self.logger, key).warning(
                    "apiserver circuit open; requeueing %s in %.2fs",
                    key, err.retry_in or 1.0)
                queue.forget(key)
                queue.add_after(key, max(0.05, err.retry_in
                                         or 1.0))
            elif err is not None:
                logger_for_key(self.logger, key).warning(
                    "reconcile error for %s: %s", key, err)
                queue.add_rate_limited(key)
        finally:
            queue.done(key)
        return True

    # -- sync --------------------------------------------------------------
    def sync_job(self, key: str):
        """controller.go:290-334. Returns (forget, error)."""
        start = self.mono_clock()
        try:
            namespace, name = split_meta_namespace_key(key)
        except ValueError as e:
            return False, e
        if not namespace or not name:
            return False, ValueError(
                f"invalid job key {key!r}: either namespace or name is missing"
            )
        obj = self._get_job_from_cache(namespace, name)
        if obj is None:
            logger_for_key(self.logger, key).info(
                "PyTorchJob has been deleted: %s", key)
            self.jobs_deleted_counter.inc()
            self._synced_uid.pop(key, None)
            # a disruption noted for a now-deleted job must not linger
            # (nor fire against a same-key recreate)
            with self._disruption_lock:
                self._pending_disruptions.pop(key, None)
            self.clear_elastic_state(key)
            if self.admission is not None:
                # quota freed by the deletion may unblock queued tenants
                self.admission.note_deleted(key)
            self._admission_verdicts.pop(key, None)
            for rtype in constants.VALID_REPLICA_TYPES:
                self.expectations.delete_expectations(expectation_pods_key(key, rtype))
                self.expectations.delete_expectations(expectation_services_key(key, rtype))
            return True, None
        try:
            job = self._job_from_unstructured(obj)
        except ValidationError as e:
            logger_for_key(self.logger, key).error(
                "Failed to convert the PyTorchJob: %s", e)
            # A job can also become invalid via an update after a valid
            # admission — mark it Failed here too, then stop reconciling.
            self.mark_job_invalid(obj, e)
            return True, None

        set_defaults(job)
        # Delete-recreate UID fence: expectations raised by a worker
        # that was still reconciling the old incarnation when
        # _job_deleted's clear ran would gate the new incarnation until
        # the TTL.  The workqueue processes a key on one worker at a
        # time, so by the time this sync observes the NEW UID in the
        # cache, the old incarnation's reconcile (and any expectation it
        # could raise) has finished — clearing here is authoritative.
        uid = job.metadata.uid or ""
        prev_uid = self._synced_uid.get(key)
        if prev_uid is not None and prev_uid != uid:
            for rtype in constants.VALID_REPLICA_TYPES:
                self.expectations.delete_expectations(expectation_pods_key(key, rtype))
                self.expectations.delete_expectations(expectation_services_key(key, rtype))
        self._synced_uid[key] = uid
        with tracing.span("expectations-check"):
            job_needs_sync = self.satisfied_expectations(job)

        err = None
        if job_needs_sync and not job.metadata.deletion_timestamp:
            try:
                self.reconcile(job, obj)
            except Exception as e:  # reconcile errors requeue the job
                err = e
        logger_for_key(self.logger, key).debug(
            "Finished syncing job %s (%.3fs)", key, self.mono_clock() - start
        )
        if err is not None:
            return False, err
        return True, None

    # -- multi-tenant admission ---------------------------------------------
    def _disruption_machinery_enabled(self) -> bool:
        """The disruption/elastic state machines also run when admission
        is on: priority preemption drains victims through them (and the
        elastic target must bind for shrunken victims) even without
        --enable-disruption-handling's node watchers."""
        return (self.config.enable_disruption_handling
                or self.admission is not None)

    def _admission_gate(self, job: PyTorchJob, pods: List[dict]) -> bool:
        """Offer the job to the admission queue and mirror the verdict
        into its Queued condition — the queue's ONLY durable state, so
        a new shard owner (or a restarted operator) rebuilds exactly
        this from the job object.  Returns True when this sync may
        proceed to create/reconcile."""
        job_key = job.key
        uid = job.metadata.uid or ""
        name = job.metadata.name
        admitted = self.admission.offer(job, has_pods=bool(pods))
        waiting = self.admission.waiting_kind(job_key)

        def _journal_verdict(verdict: str) -> None:
            if self._admission_verdicts.get(job_key) != verdict:
                self._admission_verdicts[job_key] = verdict
                self.journal.record(
                    "admission_verdict", job=job_key, verdict=verdict,
                    namespace=job.metadata.namespace or "default")

        if admitted and waiting is None:
            cond = status_machine.get_condition(job.status,
                                                constants.JOB_QUEUED)
            if cond is not None and cond.status == "True":
                status_machine.clear_condition(
                    job.status, constants.JOB_QUEUED,
                    constants.ADMISSION_ADMITTED_REASON,
                    f"PyTorchJob {name} admitted by the fair-share queue")
            self.lifecycle.record(job_key, "admitted", uid=uid,
                                  trace_id=tracing.current_trace_id())
            _journal_verdict("admitted")
            return True
        if admitted and waiting == KIND_GROW:
            # elastic preemption victim: keeps running at its shrunken
            # floor while the grow-back entry waits in the queue — the
            # condition stays True so a handover rebuild restores the
            # grow claim (Queued=True + pods == shrunken victim)
            status_machine.update_job_conditions(
                job.status, constants.JOB_QUEUED,
                constants.ADMISSION_PREEMPTED_REASON,
                f"PyTorchJob {name} shrank for a higher-priority job; "
                f"its grow-back waits in the admission queue")
            _journal_verdict("preempted_grow_queued")
            return True
        reason = (constants.ADMISSION_PREEMPTED_REASON
                  if waiting == KIND_RESTART
                  else constants.ADMISSION_QUEUED_REASON)
        status_machine.update_job_conditions(
            job.status, constants.JOB_QUEUED, reason,
            f"PyTorchJob {name} is queued by the fair-share admission "
            f"queue (namespace quota / cluster headroom)")
        self.lifecycle.record(job_key, "queued", uid=uid,
                              trace_id=tracing.current_trace_id())
        _journal_verdict("preempted" if waiting == KIND_RESTART
                         else "queued")
        return False

    def _admission_preempt(self, victim_key: str,
                           waiter_key: str) -> Optional[str]:
        """Admission-queue callback: drain ``victim_key`` to free quota
        for the higher-priority ``waiter_key``.  Elastic victims shrink
        to minReplicas through the checkpoint-drain path; gang
        non-elastic victims take the legacy full restart, with their
        recreation gated until the queue re-releases them.  Returns the
        drain mode applied, or None to refuse (the queue tries the next
        candidate)."""
        try:
            namespace, name = split_meta_namespace_key(victim_key)
        except ValueError:
            return None
        obj = self._get_job_from_cache(namespace, name)
        if obj is None:
            return None
        try:
            victim = self._job_from_unstructured(obj)
        except ValidationError:
            return None
        set_defaults(victim)
        if status_machine.is_succeeded(victim.status) or \
                status_machine.is_failed(victim.status):
            return None
        if not self.gang_scheduling_enabled(victim):
            # a non-gang job loses only single pods to a restart;
            # preempting it frees no coherent slice
            return None
        annotations = victim.metadata.annotations or {}
        if annotations.get(constants.ANNOTATION_DISRUPTION_HANDLING) == \
                constants.DISRUPTION_HANDLING_DISABLED:
            return None
        uid = victim.metadata.uid or ""
        source = f"admission:{waiter_key}"
        # Elastic shrink when the drain would actually begin (mirrors
        # _begin_elastic_drain's refusals): room above the floor and
        # resize budget left.  Doom the highest-named workers — stable
        # and index-dense, so the survivors keep contiguous ranks.
        doomed: List[str] = []
        policy = victim.spec.elastic_policy
        if policy is not None:
            target = self.elastic_worker_target(victim) or 0
            floor = policy.min_replicas or 1
            if target > floor and (victim.status.elastic_resizes or 0) \
                    < self._elastic_budget(victim):
                workers = sorted(
                    (p.get("metadata") or {}).get("name", "")
                    for p in self.get_pods_for_job(obj)
                    if ((p.get("metadata") or {}).get("labels") or {}).get(
                        constants.LABEL_REPLICA_TYPE)
                    == constants.REPLICA_TYPE_WORKER.lower())
                doomed = workers[floor:]
        if doomed:
            for pod_name in doomed:
                self._note_disruption(
                    victim_key, constants.PRIORITY_PREEMPTION_REASON,
                    source, uid=uid, pod=pod_name)
            return "elastic"
        if (victim.status.preemption_restarts or 0) >= \
                self._preemption_budget(victim):
            # out of proactive-restart budget: killing the gang now
            # would strand it (maybe_handle_disruption would refuse and
            # the gate would still block its pods) — refuse instead
            return None
        self._note_disruption(
            victim_key, constants.PRIORITY_PREEMPTION_REASON,
            source, uid=uid)
        return "restart"

    def _admission_released(self, key: str, kind: str) -> None:
        """Admission-queue callback (queue lock released): wake the
        job's sync.  A grow-back release also re-arms the elastic grow
        note — the CapacityWatcher only fires on node edges, and an
        admission grant is not one, so without the nudge the victim
        would stay shrunken until an unrelated node event."""
        if kind == KIND_GROW:
            with self._disruption_lock:
                uid = self._shrunken_jobs.get(key, "")
                self._pending_grows.setdefault(
                    key, {"node": "admission-grant", "uid": uid})
        self._queue_for_key(key).add(key)

    def _admission_grow_allowed(self, job: PyTorchJob) -> bool:
        """DisruptionHandlingMixin hook: an admission-preempted elastic
        victim holds at its floor while its grow-back entry waits in
        the fair-share queue — the chips it shed belong to the waiter,
        and a capacity-edge grow would silently claw them back."""
        if self.admission is None:
            return True
        return self.admission.grow_allowed(job.key)

    def satisfied_expectations(self, job: PyTorchJob) -> bool:
        """controller.go:497-516."""
        satisfied = False
        job_key = job.key
        for rtype in job.spec.pytorch_replica_specs:
            satisfied = satisfied or self.expectations.satisfied(
                expectation_pods_key(job_key, rtype)
            )
            satisfied = satisfied or self.expectations.satisfied(
                expectation_services_key(job_key, rtype)
            )
        return satisfied

    # -- reconcile ---------------------------------------------------------
    def reconcile(self, job: PyTorchJob, job_dict: dict) -> None:
        """controller.go:336-492."""
        job_key = job.key
        old_status = serde.deep_copy(job.status)
        # computed once per sync: job_requests_tpu serializes every
        # replica template, so don't re-ask at each branch / created pod
        gang = self.gang_scheduling_enabled(job)

        with tracing.span("pod-diff") as dspan:
            pods = self.get_pods_for_job(job_dict)
            services = self.get_services_for_job(job_dict)
            dspan.set_attr("pods", len(pods))
            dspan.set_attr("services", len(services))

        # Lifecycle milestones from this sync's observed pod state (all
        # idempotent; the tracker also closes restart/resize segments
        # once the gang is whole again).  An open Resizing condition
        # opens the resize segment regardless of which subsystem set it.
        uid = job.metadata.uid or ""
        self.lifecycle.pods_observed(
            job_key,
            created=len(pods),
            bound=sum(1 for p in pods
                      if (p.get("spec") or {}).get("nodeName")),
            # Running-or-beyond: a pod that already Succeeded HAS run,
            # and a fast pod finishing before the last one starts must
            # not keep all_running from ever firing
            running=sum(1 for p in pods
                        if (p.get("status") or {}).get("phase")
                        in ("Running", "Succeeded")),
            total=get_total_replicas(job),
            uid=uid,
            trace_id=tracing.current_trace_id())
        if any(c.type == constants.JOB_RESIZING and c.status == "True"
               for c in job.status.conditions):
            self.lifecycle.begin_segment(job_key, "resize", uid=uid)
        else:
            self.lifecycle.end_segment(job_key, "resize")

        # Terminal: clean up and freeze status.
        if status_machine.is_succeeded(job.status) or status_machine.is_failed(job.status):
            self.delete_pods_and_services(job, job_dict, pods, services)
            self.cleanup_job(job)
            # a terminal job keeps its key until deletion: drop its
            # elastic notes NOW (shrunken registration, grow capacity
            # claim) or its claim starves other shrunken jobs' grows
            # and every capacity event keeps waking it pointlessly
            self.clear_elastic_state(job_key)
            if self.admission is not None:
                # freed quota may unblock queued tenants immediately
                self.admission.note_terminal(job_key)
            if gang:
                self.delete_pod_group(job_dict)
            if status_machine.is_succeeded(job.status):
                for rtype in job.status.replica_statuses:
                    rs = job.status.replica_statuses[rtype]
                    rs.succeeded += rs.active
                    rs.active = 0
            if job.status != old_status:
                self.update_status_handler(job)
            return

        # Proactive disruption handling: an impending preemption noted by
        # the watcher consumes this sync for ONE gang restart (batched
        # pod delete + TPUPreempted Restarting condition) — or, for
        # elastic jobs, begins a checkpoint-drain-shrink — instead of the
        # per-replica reconcile below; the deletion expectations then
        # gate re-syncs until the informer has observed every delete, and
        # the following sync recreates the full gang (or reconciles the
        # surviving slice).
        if self._disruption_machinery_enabled() and \
                self.maybe_handle_disruption(job, job_dict, pods):
            if job.status != old_status:
                self.update_status_handler(job)
            return

        # Elastic continuation: a pending drain consumes the sync
        # (waiting for checkpoint acks or issuing the shrink deletes); a
        # pending grow / resize completion updates status and falls
        # through so this very sync reconciles toward the new target.
        if self._disruption_machinery_enabled() and \
                self.maybe_continue_elastic(job, job_dict, pods):
            if job.status != old_status:
                self.update_status_handler(job)
            return

        # Multi-tenant admission gate: every non-terminal job is offered
        # to the fair-share queue before any pod/service work.  A job
        # the queue has not released parks here with a Queued condition
        # — its backoff and active-deadline clocks deliberately never
        # start ticking — until a release callback re-enqueues its key.
        # Placed AFTER the disruption/elastic blocks so a preemption
        # victim's drain note is consumed first, and the ledger is
        # rebuilt lazily from the condition after a shard handover.
        if self.admission is not None and \
                not self._admission_gate(job, pods):
            if job.status != old_status:
                self.update_status_handler(job)
            return

        previous_retry = self._queue_for_key(job_key).num_requeues(job_key)
        active = sum(
            1
            for p in pods
            if (p.get("status") or {}).get("phase") in ("Running", "Pending")
        )
        failed = sum(
            1 for p in pods if (p.get("status") or {}).get("phase") == "Failed"
        )
        # the elastic target only binds while disruption handling is on:
        # reconcile_pods below gates elastic_target the same way, and a
        # disagreement (operator restarted with the flag off while
        # status.desiredReplicas persists shrunken) would pin minMember
        # and the active-vs-total compare at the stale shrunken size
        # while the full gang is recreated
        total = (get_total_effective_replicas(job)
                 if self._disruption_machinery_enabled()
                 else get_total_replicas(job))
        prev_failed = get_total_failed_replicas(job)

        job_exceeds_limit = False
        failure_message = ""
        if job.spec.backoff_limit is not None:
            job_has_new_failure = failed > prev_failed
            exceeds_backoff_limit = (
                job_has_new_failure
                and active != total
                and previous_retry + 1 > job.spec.backoff_limit
            )
            if exceeds_backoff_limit or self.past_backoff_limit(job, pods):
                job_exceeds_limit = True
                failure_message = (
                    f"PyTorchJob {job.metadata.name} has failed because it has"
                    " reached the specified backoff limit"
                )
        if not job_exceeds_limit and self.past_active_deadline(job):
            job_exceeds_limit = True
            failure_message = (
                f"PyTorchJob {job.metadata.name} has failed because it was"
                " active longer than specified deadline"
            )

        if job_exceeds_limit:
            self.delete_pods_and_services(job, job_dict, pods, services)
            self.cleanup_job(job)
            if gang:
                self.delete_pod_group(job_dict)
            self.recorder.event(
                job_dict, EVENT_TYPE_NORMAL, status_machine.JOB_FAILED_REASON, failure_message
            )
            if job.status.completion_time is None:
                job.status.completion_time = status_machine.now_iso()
            status_machine.update_job_conditions(
                job.status, constants.JOB_FAILED, status_machine.JOB_FAILED_REASON,
                failure_message,
            )
            self.jobs_failed_counter.inc()
            self.lifecycle.record(job_key, "failed", uid=uid,
                                  trace_id=tracing.current_trace_id(),
                                  attrs={"reason": "limit"})
        else:
            if gang:
                # gang minMember tracks the ELASTIC target: a shrunken
                # 6-worker slice must not wait on 8 members
                self.sync_pod_group(job_dict, total)
            for rtype, spec in job.spec.pytorch_replica_specs.items():
                elastic_target = None
                if rtype == constants.REPLICA_TYPE_WORKER and \
                        self._disruption_machinery_enabled():
                    elastic_target = self.elastic_worker_target(job)
                self.reconcile_pods(job, job_dict, pods, rtype, spec,
                                    gang_enabled=gang,
                                    elastic_target=elastic_target)
                # TPU deviation: services for EVERY replica type (the
                # reference skips non-Master, controller.go:474-477) — all
                # hosts need DNS for TPU_WORKER_HOSTNAMES.
                self.reconcile_services(job, job_dict, services, rtype, spec)

        if job.status != old_status:
            self.update_status_handler(job)

    # -- status (status.go:63-146) -----------------------------------------
    def update_status_single(
        self, job: PyTorchJob, job_dict: dict, rtype: str, replicas: int, restart: bool
    ) -> None:
        rs = job.status.replica_statuses.get(rtype)
        expected = replicas - (rs.succeeded if rs else 0)
        running = rs.active if rs else 0
        failed = rs.failed if rs else 0

        if job.status.start_time is None:
            job.status.start_time = status_machine.now_iso()
            if job.spec.active_deadline_seconds is not None:
                logger_for_job(self.logger, job).info(
                    "Job with ActiveDeadlineSeconds will sync after %s seconds",
                    job.spec.active_deadline_seconds,
                )
                self._queue_for_key(job.key).add_after(
                    job.key, job.spec.active_deadline_seconds)

        if constants.REPLICA_TYPE_MASTER not in job.spec.pytorch_replica_specs:
            raise ValueError("invalid config: Job must contain master replica spec")

        if rtype == constants.REPLICA_TYPE_MASTER:
            if running > 0:
                msg = f"PyTorchJob {job.metadata.name} is running."
                status_machine.update_job_conditions(
                    job.status, constants.JOB_RUNNING, status_machine.JOB_RUNNING_REASON, msg
                )
            if expected == 0:
                msg = f"PyTorchJob {job.metadata.name} is successfully completed."
                self.recorder.event(
                    job_dict, EVENT_TYPE_NORMAL, status_machine.JOB_SUCCEEDED_REASON, msg
                )
                if job.status.completion_time is None:
                    job.status.completion_time = status_machine.now_iso()
                status_machine.update_job_conditions(
                    job.status, constants.JOB_SUCCEEDED, status_machine.JOB_SUCCEEDED_REASON, msg
                )
                self.jobs_successful_counter.inc()
                self.lifecycle.record(
                    job.key, "succeeded", uid=job.metadata.uid or "",
                    trace_id=tracing.current_trace_id())

        if failed > 0:
            if restart:
                msg = (
                    f"PyTorchJob {job.metadata.name} is restarting because"
                    f" {failed} {rtype} replica(s) failed."
                )
                self.recorder.event(
                    job_dict, EVENT_TYPE_WARNING, status_machine.JOB_RESTARTING_REASON, msg
                )
                status_machine.update_job_conditions(
                    job.status, constants.JOB_RESTARTING, status_machine.JOB_RESTARTING_REASON, msg
                )
                self.jobs_failed_counter.inc()
                self.jobs_restarted_counter.inc()
                self.lifecycle.begin_segment(
                    job.key, "restart", uid=job.metadata.uid or "",
                    attrs={"replica_type": rtype, "failed": failed})
            else:
                msg = (
                    f"PyTorchJob {job.metadata.name} is failed because"
                    f" {failed} {rtype} replica(s) failed."
                )
                self.recorder.event(
                    job_dict, EVENT_TYPE_NORMAL, status_machine.JOB_FAILED_REASON, msg
                )
                if job.status.completion_time is None:
                    job.status.completion_time = status_machine.now_iso()
                status_machine.update_job_conditions(
                    job.status, constants.JOB_FAILED, status_machine.JOB_FAILED_REASON, msg
                )
                self.jobs_failed_counter.inc()
                self.lifecycle.record(
                    job.key, "failed", uid=job.metadata.uid or "",
                    trace_id=tracing.current_trace_id(),
                    attrs={"replica_type": rtype, "failed": failed})

    # -- limits (controller.go:518-569) ------------------------------------
    def past_backoff_limit(self, job: PyTorchJob, pods: List[dict]) -> bool:
        if job.spec.backoff_limit is None:
            return False
        result = 0
        for rtype, spec in job.spec.pytorch_replica_specs.items():
            if spec.restart_policy not in (
                constants.RESTART_POLICY_ON_FAILURE,
                constants.RESTART_POLICY_ALWAYS,
            ):
                continue
            for pod in self.filter_pods_for_replica_type(pods, rtype.lower()):
                phase = (pod.get("status") or {}).get("phase")
                if phase not in ("Running", "Pending"):
                    continue
                pod_status = pod.get("status") or {}
                for cs in (pod_status.get("initContainerStatuses") or []) + (
                    pod_status.get("containerStatuses") or []
                ):
                    result += cs.get("restartCount", 0)
        if job.spec.backoff_limit == 0:
            return result > 0
        return result >= job.spec.backoff_limit

    def past_active_deadline(self, job: PyTorchJob) -> bool:
        if job.spec.active_deadline_seconds is None or job.status.start_time is None:
            return False
        start = parse_time(job.status.start_time)
        if start is None:
            return False
        # lint: wall-clock-ok deadline is anchored to the wire-format RFC3339 status.startTime, which lives in the wall-clock epoch domain; a monotonic source cannot be compared against it
        return time.time() - start >= job.spec.active_deadline_seconds


class _ShardRuntime:
    """Everything one OWNED shard needs on this replica: a job/pod/
    service informer trio whose list+watch is confined to the shard's
    label selector (a FRESH ListWatch per acquisition — the handoff
    fence: live lists precede any create, so a rebalance mid-churn
    cannot double-create), its own workqueue (client-go metric families
    labeled ``pytorchjob-shard<i>``), and its worker threads.  Built by
    ``PyTorchController._on_shard_acquired`` from the shard manager's
    tick thread; torn down on release/shutdown."""

    def __init__(self, controller: PyTorchController, shard: int,
                 workers: int = 1, epoch: int = 0):
        self.shard = shard
        self.epoch = int(epoch)
        self.controller = controller
        self.pod_index = None  # set by the acquire hooks
        # the shard Lease this runtime serves: the stage-clock /
        # flight-recorder key that lets fleetview join this replica's
        # sync/first-reconcile stamps to the Lease's acquire event
        mgr = controller.shard_manager
        self.lease_name = ring_lease_name(
            mgr.lease_prefix if mgr is not None else SHARD_LEASE_PREFIX,
            shard, self.epoch)
        # handoff stage latches: informer syncs count down (the three
        # start() calls run sequentially on the manager's tick thread);
        # first reconcile races across worker threads, hence the lock
        self._unsynced_informers = 3
        self._first_reconcile_done = False
        self._stage_lock = make_lock(
            f"shard-runtime.stages.{self.lease_name}")
        self.queue = WorkQueue(clock=controller.mono_clock)
        # epoch >= 1 rings qualify the queue name: during a migration a
        # next-ring runtime for shard i coexists with the old ring's,
        # and the registry is get-or-create — a shared name would let
        # two live queues fight over one depth gauge
        queue_name = (f"pytorchjob-shard{shard}" if self.epoch == 0
                      else f"pytorchjob-e{self.epoch}-shard{shard}")
        self.queue.set_metrics(WorkQueueMetrics(
            controller.registry, queue_name,
            clock=controller.mono_clock))
        self.queue.set_propagation(controller.propagation)
        cluster = controller.cluster
        self._sources = [sharded_source(cluster, plural, shard, epoch)
                         for plural in ("pytorchjobs", "pods", "services")]
        # epoch membrane: the shard-label selector alone cannot exclude
        # a LATER ring's objects that happen to hash to the same index
        # (epoch-0 selectors are equality-only), so every source is
        # fenced on this runtime's exact epoch before the informer sees
        # it — the double-enqueue half of the migration fence
        jobs_src, pods_src, services_src = [
            EpochFencedSource(src, epoch) for src in self._sources]
        self.job_informer = Informer(
            jobs_src,
            coalesce=lambda key, old, new:
                controller._coalesce_job_event(key, old, new,
                                               queue=self.queue),
            clock=controller.mono_clock,
            on_synced=self._informer_synced,
            propagation=controller.propagation,
            budget=controller.timebudget)
        self.job_informer.add_event_handler(
            on_add=controller.add_job, on_update=controller.update_job,
            on_delete=controller._job_deleted)
        self.pod_informer = Informer(pods_src, clock=controller.mono_clock,
                                     on_synced=self._informer_synced)
        self.pod_informer.add_event_handler(
            on_add=controller.add_pod, on_update=controller.update_pod,
            on_delete=controller.delete_pod)
        self.service_informer = Informer(services_src,
                                         clock=controller.mono_clock,
                                         on_synced=self._informer_synced)
        self.service_informer.add_event_handler(
            on_add=controller.add_service,
            on_delete=controller.delete_service)
        self.workers = max(1, int(workers))
        self._threads: List[threading.Thread] = []

    def start(self, stop_event: threading.Event) -> None:
        # CAS-acquired stage stamp: every later stage (informer sync,
        # first reconcile) is observed as a delta from this mark
        self.controller._stage_clock.mark(self.lease_name, "acquired")
        # shard_sync self-time nests inside the manager's lease_tick
        # span (acquisition runs on the tick thread) and subtracts
        # itself out of it, keeping the two buckets disjoint
        with self.controller.timebudget.measure("shard_sync"):
            for informer in (self.job_informer, self.pod_informer,
                             self.service_informer):
                informer.start()
        for n in range(self.workers):
            t = threading.Thread(
                target=self._work, args=(stop_event,), daemon=True,
                name=f"shard{self.shard}-worker{n}")
            t.start()
            self._threads.append(t)

    def _informer_synced(self) -> None:
        """One of the trio finished its initial LIST replay; the third
        completes the ListWatch-synced handoff stage."""
        with self._stage_lock:
            self._unsynced_informers -= 1
            if self._unsynced_informers != 0:
                return
        controller = self.controller
        dt = controller._stage_clock.since(self.lease_name, "acquired")
        if dt is not None:
            controller.handoff_stage_duration.labels(
                stage="acquire_to_sync").observe(dt)
        controller.journal.record(
            "shard_synced", lease=self.lease_name, shard=self.shard,
            epoch=self.epoch, since_acquire_s=dt if dt is not None else 0.0)

    def note_first_reconcile(self, key: str = "",
                             result: str = "") -> None:
        """First completed sync pass on this runtime's queue: the last
        handoff stage — from here the shard is actually being served."""
        with self._stage_lock:
            if self._first_reconcile_done:
                return
            self._first_reconcile_done = True
        controller = self.controller
        dt = controller._stage_clock.since(self.lease_name, "acquired")
        if dt is not None:
            controller.handoff_stage_duration.labels(
                stage="acquire_to_first_reconcile").observe(dt)
        controller.journal.record(
            "shard_first_reconcile", lease=self.lease_name,
            shard=self.shard, epoch=self.epoch, job=key, result=result,
            since_acquire_s=dt if dt is not None else 0.0)

    def _work(self, stop_event: threading.Event) -> None:
        while not stop_event.is_set():
            if not self.controller.process_next_work_item(
                    timeout=self.controller.config.worker_poll_interval,
                    queue=self.queue, runtime=self):
                return

    def synced(self) -> bool:
        return all(i.has_synced() for i in (
            self.job_informer, self.pod_informer, self.service_informer))

    def stop(self) -> None:
        for informer in (self.job_informer, self.pod_informer,
                         self.service_informer):
            informer.stop()
        release = getattr(self.controller.cluster, "release_filtered",
                          None)
        for source in self._sources:
            if release is not None:
                release(source)  # stop watch AND drop the tracking ref
            else:
                stop_watch = getattr(source, "stop_watch", None)
                if stop_watch is not None:
                    stop_watch()
        self.controller._stage_clock.clear(self.lease_name)
        self.queue.shutdown()
