"""SDK production path over real sockets (round-3 verdict missing #1).

The reference SDK's whole point is driving a live API server over HTTP
(py_torch_job_client.py:65-70 creates through CustomObjectsApi; :319-393
reads pod logs).  Here PyTorchJobClient runs through its first-party
RestCluster backend against the stub API server — every SDK call is a
real HTTP exchange over a real TCP socket (native C++ transport when
available, Python http.client otherwise), while the controller and fake
kubelet drive the job to Succeeded.  Mirrors the reference SDK e2e
(sdk/python/test/test_e2e.py:33-81: create -> wait_for_job -> assert
succeeded -> get logs -> delete).
"""

from __future__ import annotations

import threading
import time

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.k8s.errors import NotFoundError
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster
from pytorch_operator_tpu.k8s.stub_server import StubApiServer
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import JobControllerConfig
from pytorch_operator_tpu.sdk import PyTorchJobClient

from testutil import new_job


@pytest.fixture
def world():
    """Stub API server + controller + kubelet, all over real HTTP."""
    stub = StubApiServer().start()
    kubelet = FakeKubelet(stub.cluster)
    kubelet.start()
    ctl_cluster = RestCluster(KubeConfig("127.0.0.1", stub.port))
    ctl = PyTorchController(ctl_cluster, config=JobControllerConfig(),
                            registry=Registry())
    stop = threading.Event()
    workers = ctl.run(threadiness=2, stop_event=stop)
    try:
        yield stub
    finally:
        stop.set()
        ctl.work_queue.shutdown()
        for w in workers:  # drain in-flight reconciles before the stub
            w.join(timeout=5)  # dies, so teardown can't log bogus I/O
        kubelet.stop()
        ctl_cluster.close()
        stub.stop()


@pytest.fixture
def client(world):
    """The SDK under test: its own RestCluster — separate sockets from
    the controller's — exactly the production backend shape."""
    sdk_cluster = RestCluster(KubeConfig("127.0.0.1", world.port))
    yield PyTorchJobClient(cluster=sdk_cluster)
    sdk_cluster.close()


class TestSdkOverRealSockets:
    def test_create_wait_logs_delete(self, client):
        job = new_job(workers=1, name="sdk-http-job")
        created = client.create(job.to_dict())
        assert created["metadata"]["name"] == "sdk-http-job"

        got = client.wait_for_job("sdk-http-job", namespace="default",
                                  timeout_seconds=30, polling_interval=0.1)
        assert client.is_job_succeeded("sdk-http-job", namespace="default")
        conds = got["status"]["conditions"]
        assert any(c["type"] == constants.JOB_SUCCEEDED for c in conds)

        names = client.get_pod_names("sdk-http-job", namespace="default")
        assert "sdk-http-job-master-0" in names
        logs = client.get_logs("sdk-http-job", namespace="default")
        # the kubelet writes the reference e2e success signal into logs
        assert any("accuracy=" in text for text in logs.values())

        client.delete("sdk-http-job", namespace="default")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                client.get("sdk-http-job", namespace="default")
            except NotFoundError:
                break
            time.sleep(0.05)
        else:
            pytest.fail("job not deleted over HTTP")

    def test_get_logs_follow_tails_live_over_http(self, client, world):
        """stream_logs rides the chunked ?follow=true stream
        (round-5 verdict item 3): lines arrive over the wire WHILE the
        pod is running — the SDK sees them before the terminal phase is
        written, proving a live tail rather than a read-at-end."""
        from pytorch_operator_tpu.sdk import utils as sdk_utils

        pod_name = "tailhttp-job-master-0"
        world.cluster.pods.create("default", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod_name, "namespace": "default",
                         "labels": sdk_utils.get_labels("tailhttp-job",
                                                        master=True)},
        })
        # the world kubelet walks fresh pods to Succeeded; wait it out,
        # then take over the pod so this test controls log/phase writes
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            phase = (world.cluster.pods.get("default", pod_name)
                     .get("status") or {}).get("phase")
            if phase == "Succeeded":
                break
            time.sleep(0.01)
        world.cluster.pods.set_status("default", pod_name,
                                      {"phase": "Running"})
        world.cluster.pods.patch("default", pod_name, {
            "metadata": {"annotations": {"fake.kubelet/logs": ""}}})

        text = {"v": ""}
        terminal_at = [None]

        def writer():
            for i in range(3):
                time.sleep(0.15)
                text["v"] += f"step {i}: loss=0.{9 - i}\n"
                world.cluster.pods.patch("default", pod_name, {
                    "metadata": {"annotations":
                                 {"fake.kubelet/logs": text["v"]}}})
            text["v"] += "accuracy=0.9876\n"
            world.cluster.pods.patch("default", pod_name, {
                "metadata": {"annotations":
                             {"fake.kubelet/logs": text["v"]}}})
            world.cluster.pods.set_status("default", pod_name,
                                          {"phase": "Succeeded"})
            terminal_at[0] = time.monotonic()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        got = []
        for pod, line in client.stream_logs("tailhttp-job",
                                            namespace="default"):
            got.append((time.monotonic(), pod, line))
        t.join(timeout=10)
        lines = [l for _, _, l in got]
        assert lines == ["step 0: loss=0.9", "step 1: loss=0.8",
                         "step 2: loss=0.7", "accuracy=0.9876"], lines
        # live-tail proof: the first line crossed the socket before the
        # writer marked the pod terminal
        assert terminal_at[0] is not None
        assert got[0][0] < terminal_at[0], (got[0][0], terminal_at[0])

    def test_follow_preserves_blank_lines_over_http(self, client, world):
        """The HTTP transport must not eat blank log lines (the native
        watch framing skips keep-alive blanks; the log path therefore
        rides http.client + the shared iter_log_lines splitter)."""
        from pytorch_operator_tpu.sdk import utils as sdk_utils

        pod_name = "blankhttp-job-master-0"
        world.cluster.pods.create("default", {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod_name, "namespace": "default",
                         "labels": sdk_utils.get_labels("blankhttp-job",
                                                        master=True)}})
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if (world.cluster.pods.get("default", pod_name)
                    .get("status") or {}).get("phase") == "Succeeded":
                break
            time.sleep(0.01)
        world.cluster.pods.patch("default", pod_name, {
            "metadata": {"annotations":
                         {"fake.kubelet/logs": "a\n\nb\n"}}})
        lines = [l for _, l in client.stream_logs(
            "blankhttp-job", namespace="default")]
        assert lines == ["a", "", "b"], lines

    def test_watch_streams_conditions_over_http(self, client, capsys):
        """get(watch=True) rides the server-side watch stream (GAP-safe
        event path in sdk/watch.py), not a poll loop: the watch is
        opened BEFORE the job exists, so every printed row must have
        arrived as a watch event over the chunked HTTP stream."""
        result = {}

        def run_watch():
            try:
                client.get("watch-http-job", namespace="default",
                           watch=True, timeout_seconds=30)
                result["ok"] = True
            except Exception as e:  # pragma: no cover - surfaced below
                result["error"] = e

        t = threading.Thread(target=run_watch, daemon=True)
        t.start()
        time.sleep(0.3)  # let the stream open first
        client.create(new_job(workers=0, name="watch-http-job").to_dict())
        t.join(timeout=30)
        assert not t.is_alive(), "watch did not terminate"
        assert result.get("ok"), result.get("error")
        out = capsys.readouterr().out
        assert "NAME" in out and "watch-http-job" in out
        assert "Succeeded" in out
