"""The cluster-tier workflow is checked code, not prose: the gke mode's
DRYRUN plan must print without cloud credentials, reference only files
that exist, and parse under bash -n (and shellcheck when available)."""

from __future__ import annotations

import os
import re
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKFLOW = os.path.join(REPO, "scripts", "e2e-workflow.sh")


def _plan_lines() -> list[str]:
    proc = subprocess.run(
        ["bash", WORKFLOW], cwd=REPO,
        env={**os.environ, "MODE": "gke", "DRYRUN": "1"},
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    return [line[len("PLAN: "):] for line in proc.stdout.splitlines()
            if line.startswith("PLAN: ")]


def test_gke_plan_references_only_existing_files():
    lines = _plan_lines()
    assert lines, "dry run printed no plan"
    referenced = set()
    for line in lines:
        # repo-relative paths the plan expects to exist
        referenced.update(re.findall(r"(?:scripts|manifests|tests)/[\w./-]+",
                                     line))
    assert referenced, "plan references no repo files (suspicious)"
    missing = [p for p in sorted(referenced)
               if not os.path.exists(os.path.join(REPO, p))]
    assert not missing, f"plan references missing files: {missing}"


def test_gke_plan_covers_reference_pipeline_stages():
    """workflows.libsonnet:196-268 stage parity: build -> cluster ->
    deploy -> e2e (defaults, cleanpodpolicy, sdk) -> teardown."""
    plan = "\n".join(_plan_lines())
    for needle in ("build-image.sh", "clusters create", "node-pools create",
                   "crd.yaml", "rollout status", "run-defaults.sh",
                   "run-cleanpodpolicy-all.sh", "test_sdk.py",
                   "clusters delete"):
        assert needle in plan, f"plan lost the {needle!r} stage"


def test_all_shell_scripts_parse():
    scripts = []
    for root, _dirs, files in os.walk(os.path.join(REPO, "scripts")):
        scripts += [os.path.join(root, f) for f in files
                    if f.endswith(".sh")]
    assert scripts
    for path in scripts:
        proc = subprocess.run(["bash", "-n", path], capture_output=True,
                              text=True)
        assert proc.returncode == 0, f"{path}: {proc.stderr}"


def _run_lint(*argv):
    return subprocess.run(
        ["python", os.path.join(REPO, "scripts", "lint.py"), *argv],
        cwd=REPO, capture_output=True, text=True, timeout=120)


def test_lint_driver_exit_codes(tmp_path):
    """The CI contract for scripts/lint.py: clean=0, findings=1,
    pragma'd=0 (and the pragma must carry a reason to count)."""
    clean = tmp_path / "clean.py"
    clean.write_text("import hashlib\nx = hashlib.blake2b(b'k')\n")
    proc = _run_lint(str(clean))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout

    # builtin-hash is tree-wide scoped, so it fires even on a tmp file
    dirty = tmp_path / "dirty.py"
    dirty.write_text("x = hash('k') % 8\n")
    proc = _run_lint(str(dirty))
    assert proc.returncode == 1
    assert "[builtin-hash]" in proc.stdout

    waived = tmp_path / "waived.py"
    waived.write_text(
        "x = hash('k') % 8  # lint: builtin-hash-ok process-local memo\n")
    proc = _run_lint(str(waived))
    assert proc.returncode == 0
    assert "waived: process-local memo" in proc.stdout

    # a reasonless waiver does NOT count as clean
    bad_waiver = tmp_path / "bad_waiver.py"
    bad_waiver.write_text("x = hash('k')  # lint: builtin-hash-ok\n")
    assert _run_lint(str(bad_waiver)).returncode == 1

    # an unused waiver is itself a gate failure (engine finding): a
    # pragma that stops matching anything must be deleted, not rot
    stale = tmp_path / "stale.py"
    stale.write_text("x = 1  # lint: builtin-hash-ok nothing here\n")
    proc = _run_lint(str(stale))
    assert proc.returncode == 1
    assert "unused-waiver" in proc.stdout

    # usage error: missing path
    assert _run_lint(str(tmp_path / "no_such.py")).returncode == 2


def test_lint_tree_gate_and_rule_catalog():
    """The repo itself must pass its own gate (exit 0 over the default
    roots), and --list-rules documents the pragma vocabulary."""
    proc = _run_lint("--quiet")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout

    proc = _run_lint("--list-rules")
    assert proc.returncode == 0
    for key in ("wall-clock", "builtin-hash", "unseeded-random",
                "blocking-in-lock", "swallowed-except", "cache-mutation",
                "flag-docs-drift"):
        assert key in proc.stdout


def test_flag_docs_drift_check_both_directions(tmp_path):
    """The flags-vs-docs drift check mirrors the metric doc-drift test:
    an operator flag missing from developer_guide.md AND a guide flag
    defined nowhere in the tree are both findings; a documented,
    defined flag is neither."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_driver_under_test", os.path.join(REPO, "scripts", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)

    cmd = tmp_path / "pytorch_operator_tpu" / "cmd"
    cmd.mkdir(parents=True)
    (cmd / "operator.py").write_text(
        "import argparse\n"
        "p = argparse.ArgumentParser()\n"
        'p.add_argument("--known-flag")\n'
        'p.add_argument("--undocumented-flag")\n')
    (tmp_path / "developer_guide.md").write_text(
        "Run with `--known-flag` or the removed `--ghost-flag`.\n")

    findings = lint._flag_docs_findings(str(tmp_path))
    assert all(f.rule == "flag-docs-drift" for f in findings)
    msgs = [f.message for f in findings]
    assert any("--undocumented-flag" in m and "not documented" in m
               for m in msgs)
    assert any("--ghost-flag" in m and "not defined" in m for m in msgs)
    assert not any("--known-flag" in m for m in msgs)
    # absent guide or operator file: the check degrades to no findings
    assert lint._flag_docs_findings(str(tmp_path / "nope")) == []


def test_storm_tier_smoke(monkeypatch):
    """The event-storm bench tier (round-5 verdict item 5) must run:
    active watch streams receive generated events while jobs complete,
    and the delivered-event counter proves the streams were genuinely
    active, not parked."""
    import sys

    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    # run_storm's _set_variant writes PYTORCH_OPERATOR_NATIVE; restore
    # it so later tests keep the default native-when-available selection
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE",
                       os.environ.get("PYTORCH_OPERATOR_NATIVE", ""))
    import bench_control_plane as bcp

    r = bcp.run_storm(3, 1, "python", n_streams=4, event_hz=20,
                      threadiness=2)
    assert r["first_pod"]["n"] == 3
    assert r["succeeded"]["n"] == 3
    assert r["storm_delivered"] > 0, "no events delivered — streams idle"
    assert r["storm_streams"] == 4 and r["threadiness"] == 2


@pytest.mark.skipif(shutil.which("shellcheck") is None,
                    reason="shellcheck not installed")
def test_shellcheck_clean():
    scripts = []
    for root, _dirs, files in os.walk(os.path.join(REPO, "scripts")):
        scripts += [os.path.join(root, f) for f in files
                    if f.endswith(".sh")]
    proc = subprocess.run(["shellcheck", "--severity=warning", *scripts],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout


def test_bench_trend_skipped_rounds_are_not_regressions(tmp_path,
                                                        monkeypatch):
    """ROADMAP item: a `"skipped": true` bench round carries no
    throughput signal — the trend driver must report it as skipped and
    never as a regression, and must compare across it."""
    import json as _json

    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_trend

    def round_file(n, parsed, rc=0):
        path = tmp_path / f"BENCH_r{n:02d}.json"
        path.write_text(_json.dumps(
            {"n": n, "cmd": "python bench.py", "rc": rc, "tail": "",
             "parsed": parsed}))
        return str(path)

    measured_1 = round_file(1, {"value": 1000.0, "unit": "img/s"})
    crashed_2 = round_file(2, None, rc=1)  # legacy crash round
    skipped_3 = round_file(3, {"skipped": True, "reason": "no TPU"})
    measured_4 = round_file(4, {"value": 950.0, "unit": "img/s"})

    rounds = [bench_trend.classify(bench_trend.load_round(p))
              for p in (measured_1, crashed_2, skipped_3, measured_4)]
    assert [r["status"] for r in rounds] == [
        "measured", "failed", "skipped", "measured"]

    # 950 vs 1000 is inside the 20% tolerance; the skipped/failed rounds
    # in between are excluded, not read as zeros
    verdict = bench_trend.trend(rounds, tolerance=0.2)
    assert verdict["comparable"] and not verdict["regressed"]
    assert verdict["reference"]["n"] == 1 and verdict["latest"]["n"] == 4

    # a genuine drop beyond tolerance IS a regression
    bad = rounds[:-1] + [bench_trend.classify(bench_trend.load_round(
        round_file(5, {"value": 100.0, "unit": "img/s"})))]
    assert bench_trend.trend(bad, tolerance=0.2)["regressed"]

    # latest round skipped: explicitly not comparable, not regressed
    tail_skipped = rounds + [bench_trend.classify(bench_trend.load_round(
        round_file(6, {"skipped": True, "reason": "no TPU"})))]
    verdict = bench_trend.trend(tail_skipped)
    assert not verdict["regressed"] and not verdict["comparable"]
    assert "skipped" in verdict["note"]

    # CLI: exit 0 on the healthy set, table mentions the skip reason
    assert bench_trend.main([measured_1, skipped_3, measured_4]) == 0


def test_bench_trend_reads_step_profiler_jsonl(tmp_path, monkeypatch):
    """ISSUE 4 satellite: a StepProfiler JSONL step log enters the
    trend as a measured round (mean tokens/sec over steady-state
    steps); a log with no steady-state signal classifies as skipped,
    never as a regression."""
    import json as _json

    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_trend

    def step_log(n, records):
        path = tmp_path / f"STEPS_r{n:02d}.jsonl"
        path.write_text("".join(_json.dumps(r) + "\n" for r in records))
        return str(path)

    measured = step_log(1, [
        {"compile": True, "step": 1, "step_time_s": 2.0},
        {"compile": False, "step": 2, "step_time_s": 0.5,
         "tokens_per_sec": 2000.0},
        {"compile": False, "step": 3, "step_time_s": 0.5,
         "tokens_per_sec": 2200.0},
    ])
    r = bench_trend.classify(bench_trend.load_round(measured))
    assert r["status"] == "measured"
    assert r["value"] == pytest.approx(2100.0)
    assert r["unit"] == "tok/s"
    assert r["n"] == 1

    compile_only = step_log(2, [
        {"compile": True, "step": 1, "step_time_s": 2.0}])
    r2 = bench_trend.classify(bench_trend.load_round(compile_only))
    assert r2["status"] == "skipped"

    # data-plane rounds ride the same verdict logic as bench rounds:
    # measured r1 vs measured r3 across the skipped r2
    faster = step_log(3, [
        {"compile": False, "step": 2, "step_time_s": 0.4,
         "tokens_per_sec": 2500.0}])
    rounds = [bench_trend.classify(bench_trend.load_round(p))
              for p in (measured, compile_only, faster)]
    verdict = bench_trend.trend(rounds, tolerance=0.2)
    assert verdict["comparable"] and not verdict["regressed"]
    assert verdict["latest"]["value"] == pytest.approx(2500.0)

    # an unreadable log is a failed round, not a crash
    r3 = bench_trend.classify(
        bench_trend.load_round(str(tmp_path / "missing_r04.jsonl")))
    assert r3["status"] == "failed"

    # CLI end to end over jsonl rounds
    assert bench_trend.main([measured, compile_only, faster]) == 0


def test_bench_churn_pods_smoke(monkeypatch):
    """ISSUE 4 satellite: the pod-informer MODIFIED-burst measurement
    must run — status bursts are delivered (never actually coalesced:
    behavior unchanged) and classified into a coalescible fraction."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE",
                       os.environ.get("PYTORCH_OPERATOR_NATIVE", ""))
    import bench_control_plane as bcp

    res = bcp.run_churn_pods(jobs=3, workers=1, bursts=5, threadiness=2,
                             timeout=60.0)
    assert res["converged"], res
    assert res["pods"] == 6
    # every burst patch was delivered as a MODIFIED (plus lifecycle
    # transitions observed on the way to Running)
    assert res["modified"] >= res["burst_events"] == 30
    # delivered >= probe-observed (a MODIFIED arriving before its
    # pod's ADDED was applied — the kubelet's nested bind patch — is
    # re-typed to ADDED by the informer and counts as neither)
    assert res["informer_delivered_modified"] >= res["modified"]
    assert 0 <= res["coalescible"] <= res["modified"]
    frac = res["coalescible_fraction"]
    assert frac is not None and 0.0 <= frac <= 1.0


def test_bench_chaos_apiserver_tier_smoke(monkeypatch, tmp_path):
    """ISSUE 5: the apiserver fault tier must run end to end — the
    resilient client converges under the committed fault plan with zero
    duplicate creates and exact pod counts, retries are counted, and
    the markdown section updater rewrites only its delimited region."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE",
                       os.environ.get("PYTORCH_OPERATOR_NATIVE", ""))
    import bench_control_plane as bcp

    res = bcp.run_chaos_apiserver(jobs=2, workers=1, resilient=True,
                                  timeout=90.0)
    assert res["converged"], res
    assert res["duplicate_create_conflicts"] == 0
    assert res["pods_match_expected"], res
    assert res["rest_retries"] + res["faults_injected"]["throttled"] > 0

    # the section updater: replaces its own delimited region, touches
    # nothing else, and appends when the section is absent
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nbody stays\n")
    section = "\n".join([bcp.CHAOS_APISERVER_BEGIN, "v1",
                         bcp.CHAOS_APISERVER_END])
    bcp.update_md_section(str(md), bcp.CHAOS_APISERVER_BEGIN,
                          bcp.CHAOS_APISERVER_END, section)
    text = md.read_text()
    assert "body stays" in text and "v1" in text
    bcp.update_md_section(str(md), bcp.CHAOS_APISERVER_BEGIN,
                          bcp.CHAOS_APISERVER_END,
                          section.replace("v1", "v2"))
    text = md.read_text()
    assert "v2" in text and "v1" not in text
    assert text.count(bcp.CHAOS_APISERVER_BEGIN) == 1

    # the verdict renderer runs on real results (content sanity only)
    fake_ab = {"chaos_apiserver_resilient": res,
               "chaos_apiserver_single_shot": res}
    out = bcp.render_chaos_apiserver_md(fake_ab, 2, 1)
    assert "Chaos-apiserver verdict" in out


def test_bench_skips_when_backend_dies_after_probe(monkeypatch, capsys):
    """ISSUE 6 satellite (ROADMAP direction 5 tail): a backend-init
    UNAVAILABLE/RuntimeError escaping from jax.devices() AFTER the
    probe passed must classify the round as skipped (BENCH_r05 recorded
    rc=1 on a down TPU backend, poisoning the trend)."""
    import json as _json
    import sys as _sys

    monkeypatch.syspath_prepend(REPO)
    import bench
    import jax

    class FakeTpuDevice:
        platform = "tpu"
        device_kind = "fake v5e"

    calls = {"n": 0}

    def flaky_devices(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            return [FakeTpuDevice()]  # the probe sees a live TPU
        raise RuntimeError(
            "Unable to initialize backend 'tpu': UNAVAILABLE: TPU "
            "backend setup/compile error (Unavailable).")

    monkeypatch.setattr(jax, "devices", flaky_devices)
    bench.main()  # must NOT raise
    out = capsys.readouterr().out.strip().splitlines()
    record = _json.loads(out[-1])
    assert record["skipped"] is True
    assert "UNAVAILABLE" in record["reason"]
    assert "value" not in record

    # a genuine measurement bug still crashes loudly (rc=1 is correct)
    def broken_devices(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            return [FakeTpuDevice()]
        raise RuntimeError("shape mismatch in measured code")

    calls["n"] = 0
    monkeypatch.setattr(jax, "devices", broken_devices)
    with pytest.raises(RuntimeError, match="shape mismatch"):
        bench.main()

    # a genuine bug whose message merely CONTAINS an infra marker must
    # also crash: the liveness re-probe sees a healthy backend (the
    # call AFTER the failing one succeeds) and re-raises instead of
    # recording a skipped round that would hide the regression
    def marker_bug_devices(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError(
                "XlaRuntimeError: DEADLINE_EXCEEDED: collective permute "
                "timed out (regression in measured code)")
        return [FakeTpuDevice()]

    calls["n"] = 0
    monkeypatch.setattr(jax, "devices", marker_bug_devices)
    with pytest.raises(RuntimeError, match="collective permute"):
        bench.main()
    # and a probe that fails outright (both TPU and cpu fallback) is
    # the existing skip path, now robust to non-RuntimeError raises too
    monkeypatch.setattr(
        jax, "devices",
        lambda *a, **k: (_ for _ in ()).throw(Exception("plugin gone")))
    bench.main()
    record = _json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert record["skipped"] is True


def test_bench_elastic_tier_smoke(monkeypatch, tmp_path):
    """ISSUE 6: the elastic A/B tier must run end to end — the elastic
    variant shrinks (checkpointing every doomed pod), grows back and
    converges with zero duplicate creates; the section updater rewrites
    only its delimited region."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE",
                       os.environ.get("PYTORCH_OPERATOR_NATIVE", ""))
    import bench_control_plane as bcp

    res = bcp.run_elastic(jobs=1, workers=3, kill=1, elastic=True,
                          timeout=60.0)
    assert res["converged"], res
    assert res["duplicate_creates"] == 0
    assert res["resizes"]["shrink"] == 1
    assert res["resizes"]["grow"] == 1
    assert res["pods_state_lost"] == 0
    assert res["pods_checkpointed"] == 1
    # master + the two surviving workers never stopped training
    assert res["pods_kept_running"] == 3
    assert res["recovery_wall_s"] > 0

    legacy = bcp.run_elastic(jobs=1, workers=3, kill=1, elastic=False,
                             timeout=60.0)
    assert legacy["converged"], legacy
    # the dip is REAL for both variants (freeze_capacity): the rigid
    # gang cannot be whole before capacity returns, while the elastic
    # gang was already training at reduced size during the dip
    assert legacy["recovery_wall_s"] >= legacy["dip_s"]
    assert res["recovery_wall_s"] < legacy["recovery_wall_s"]
    assert legacy["gang_restarts"] == 1
    assert legacy["pods_state_lost"] == 4  # the whole gang, no acks
    assert legacy["pods_kept_running"] == 0

    # the markdown section updater only touches its own region
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched\n")
    ab = {"elastic": res, "elastic_legacy": legacy}
    bcp.update_md_section(str(md), bcp.ELASTIC_BEGIN, bcp.ELASTIC_END,
                          bcp.render_elastic_md(ab, 1, 3, 1))
    text = md.read_text()
    assert "untouched" in text
    assert "Elastic verdict" in text
    assert text.count(bcp.ELASTIC_BEGIN) == 1


def test_bench_shards_tier_smoke(monkeypatch, tmp_path):
    """ISSUE 7: the sharded-control-plane tier must run end to end —
    a 2-replica fleet splits the shard Leases and the per-replica verb
    load, a mid-storm hard kill is survived with the dead replica's
    shards re-acquired and zero duplicate-create 409s, and the section
    updater rewrites only its delimited region."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE",
                       os.environ.get("PYTORCH_OPERATOR_NATIVE", ""))
    import bench_control_plane as bcp

    res = bcp.run_shards(jobs=6, workers=1, shard_count=2, replicas=2,
                         kill=True, timeout=60.0, threadiness=2)
    assert res["converged"], res
    assert res["duplicate_create_conflicts"] == 0
    assert res["pods_match_expected"], res
    assert res["shards_reacquired"], res
    # both replicas carried apiserver load before the kill
    totals = [v["total"] for v in res["per_replica_verbs"].values()]
    assert len(totals) == 2 and all(t > 0 for t in totals)

    single = bcp.run_shards(jobs=6, workers=1, shard_count=1, replicas=1,
                            timeout=60.0, threadiness=2)
    assert single["converged"], single
    assert single["duplicate_create_conflicts"] == 0

    # the renderer + section updater only touch their own region
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched\n")
    ab = {"shards_single": single, "shards_multi": res,
          "shards_multi_kill": res}
    bcp.update_md_section(str(md), bcp.SHARDS_BEGIN, bcp.SHARDS_END,
                          bcp.render_shards_md(ab, 6, 1, 2, 2))
    text = md.read_text()
    assert "untouched" in text
    assert "Shards verdict" in text
    assert text.count(bcp.SHARDS_BEGIN) == 1


def test_bench_multicore_updater_rewrites_only_its_markers(monkeypatch,
                                                          tmp_path):
    """ISSUE 12: the --multicore renderer + section updater must
    rewrite ONLY the multicore-delimited region — sibling tiers'
    sections and prose outside the markers stay byte-identical.  (The
    N-subprocess tier itself runs under @pytest.mark.slow in
    tests/test_multicore.py; this smoke keeps the updater honest
    without booting interpreters.)"""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_control_plane as bcp

    def fake_round(replicas, kill=False):
        per = {f"mc-r{r}": {"reconciles": 10.0 + r,
                            "rest_requests": 50.0,
                            "autoscale_recommended_replicas": 1.0}
               for r in range(replicas)}
        if kill:
            per["mc-r0"] = {"killed": True}
        return {"variant": "multicore_kill" if kill else "multicore",
                "jobs": 4, "workers": 1, "shard_count": 2,
                "replicas": replicas, "threadiness": 2,
                "expected_pods": 8, "cpu_count": 1,
                "post_conflicts_startup": 0, "converged": True,
                "convergence_wall_s": 5.0 / replicas,
                "pods_final": 8, "pods_match_expected": True,
                "duplicate_create_conflicts": 0,
                "per_replica_metrics": per,
                "reconciles_total": 20.0,
                "reconcile_rate_per_s": 4.0 * replicas,
                "shards_reacquired": kill or None}

    res = {"multicore_1": fake_round(1), "multicore_2": fake_round(2),
           "multicore_kill": fake_round(2, kill=True)}
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched prose\n"
                  + bcp.SHARDS_BEGIN + "\nsibling tier\n"
                  + bcp.SHARDS_END + "\n")
    section = bcp.render_multicore_md(res, 4, 1, (1, 2))
    bcp.update_md_section(str(md), bcp.MULTICORE_BEGIN,
                          bcp.MULTICORE_END, section)
    text = md.read_text()
    assert "untouched prose" in text and "sibling tier" in text
    assert text.count(bcp.MULTICORE_BEGIN) == 1
    assert text.count(bcp.SHARDS_BEGIN) == 1
    assert "Process-per-replica control plane" in text
    # updating again replaces, never duplicates — and leaves siblings
    bcp.update_md_section(str(md), bcp.MULTICORE_BEGIN,
                          bcp.MULTICORE_END, section)
    text = md.read_text()
    assert text.count(bcp.MULTICORE_BEGIN) == 1
    assert "sibling tier" in text
    # the honest verdict rides the section: a 2x wall drop at 2
    # replicas clears the bar only when the reading says so
    assert "**Reading:**" in text


def test_bench_chaos_tier_smoke(monkeypatch):
    """The --chaos tier (ROADMAP item) must run end to end: proactive
    variant fires gang restarts and populates the restart-latency
    histogram; both variants reconverge."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE",
                       os.environ.get("PYTORCH_OPERATOR_NATIVE", ""))
    import bench_control_plane as bcp

    res = bcp.run_chaos(jobs=2, workers=1, proactive=True, timeout=60.0)
    assert res["converged"], res
    assert res["gang_restarts"] == 2
    assert res["restart_latency"]["count"] == 2
    assert res["recovery_wall_s"] > 0


def test_bench_scale_tier_smoke(monkeypatch, tmp_path):
    """ISSUE 8: the cluster-scale simulator tier must run end to end at
    a small size — same-seed runs byte-identical, alt-seed run
    different, and the section updater rewriting only its delimited
    region of the bench markdown."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_control_plane as bcp

    res = bcp.run_scale_tier(jobs=20, workers=2, nodes=6, seed=7,
                             alt_seed=8, arrival_s=40.0,
                             max_virtual_s=3600.0)
    assert res["converged"], res
    assert res["deterministic"], "same-seed fingerprints diverged"
    assert res["seed_sensitive"], "alt seed produced an identical run"
    first = res["runs"][0]
    assert first["pods_total"] == first["expected_pods"] == 60
    assert first["verb_counts"]["create Pod"] == 60
    assert first["virtual_wall_s"] > 0

    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched\n<!-- shards:begin -->old"
                  "<!-- shards:end -->\n")
    bcp.update_md_section(str(md), bcp.SCALE_BEGIN, bcp.SCALE_END,
                          bcp.render_scale_md(res, 20, 2, 6, 7, 8))
    text = md.read_text()
    assert "untouched" in text
    assert "<!-- shards:begin -->old<!-- shards:end -->" in text
    assert "Scale verdict" in text
    assert text.count(bcp.SCALE_BEGIN) == 1
    # re-running the updater replaces, never appends
    bcp.update_md_section(str(md), bcp.SCALE_BEGIN, bcp.SCALE_END,
                          bcp.render_scale_md(res, 20, 2, 6, 7, 8))
    assert md.read_text().count(bcp.SCALE_BEGIN) == 1


def test_bench_fleetview_updater_rewrites_only_its_markers(monkeypatch,
                                                           tmp_path):
    """ISSUE 15: the --fleetview renderer + section updater must
    rewrite ONLY the fleetview-delimited region — sibling sections and
    prose outside the markers stay byte-identical.  (The subprocess
    stitching round itself runs under @pytest.mark.slow in
    tests/test_fleetview.py.)"""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_control_plane as bcp

    def fake_round(mode):
        return {"variant": f"fleetview_{mode}", "jobs": 8, "workers": 3,
                "shard_count": 2, "replicas": 2, "threadiness": 2,
                "converged": True, "convergence_wall_s": 30.0,
                "acted_at_s": 12.0, "replicas_scraped": 2,
                "stitched_jobs": 4,
                "max_handoff_gap_s": 9.5 if mode == "sigkill" else 2.0,
                "handoffs": [{"job": "default/fv-job-0", "gap_s": 9.5,
                              "from_replica": "fv-r0",
                              "to_replica": "fv-r1",
                              "from_epoch": 0, "to_epoch": 1}],
                "phases": {"first_reconcile":
                           {"n": 8, "p50_ms": 120.0, "p99_ms": 900.0}},
                "trace_drops": {"fv-r0": 0, "fv-r1": 0}}

    res = {"fleetview_sigkill": fake_round("sigkill"),
           "fleetview_reshard": fake_round("reshard")}
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched prose\n"
                  + bcp.MULTICORE_BEGIN + "\nsibling tier\n"
                  + bcp.MULTICORE_END + "\n")
    section = bcp.render_fleetview_md(res, 8, 3, 2)
    bcp.update_md_section(str(md), bcp.FLEETVIEW_BEGIN,
                          bcp.FLEETVIEW_END, section)
    text = md.read_text()
    assert "untouched prose" in text and "sibling tier" in text
    assert text.count(bcp.FLEETVIEW_BEGIN) == 1
    assert text.count(bcp.MULTICORE_BEGIN) == 1
    assert "handoff gap" in text
    # re-running replaces, never duplicates — siblings stay intact
    bcp.update_md_section(str(md), bcp.FLEETVIEW_BEGIN,
                          bcp.FLEETVIEW_END, section)
    text = md.read_text()
    assert text.count(bcp.FLEETVIEW_BEGIN) == 1
    assert "sibling tier" in text
    assert "**Reading.**" in text


def test_bench_handoff_updater_rewrites_only_its_markers(monkeypatch,
                                                         tmp_path):
    """ISSUE 18: the --handoff-profile renderer + section updater must
    rewrite ONLY the handoff-delimited region — sibling sections
    (fleetview included: the tier it refines) and prose outside the
    markers stay byte-identical, and re-running replaces rather than
    duplicates.  (The subprocess rounds run under @pytest.mark.slow in
    tests/test_handoff_profile.py; the tier via run-tests.sh
    --handoff-profile.)"""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_control_plane as bcp

    def fake_round(mode):
        crash = mode == "sigkill"
        return {"variant": f"fleetview_{mode}", "jobs": 8, "workers": 3,
                "shard_count": 2, "replicas": 2, "converged": True,
                "convergence_wall_s": 30.0, "acted_at_s": 12.0,
                "max_handoff_gap_s": 9.5 if crash else 2.0,
                "max_handoff_window_s": 5.8 if crash else 0.6,
                "window_within_bound": True, "journal_dropped": 0,
                "handoff_windows": [{
                    "lease": "pytorch-operator-shard-0", "epoch": 0,
                    "kind": "crash" if crash else "reshard",
                    "to_replica": "fv-r1", "start_wall": 100.0,
                    "acquired_wall": 105.2,
                    "stages": {"detection": 5.0 if crash else 0.0,
                               "acquisition": 0.2,
                               "informer_sync": 0.3,
                               "first_reconcile": 0.3},
                    "window_s": 5.8 if crash else 0.6}],
                "slo": {"objectives": [
                    {"objective": "handoff_first_reconcile",
                     "bad": 0.0, "total": 2.0, "burn_rate": 0.0,
                     "ok": True}], "ok": True}}

    res = {"handoff_sigkill": fake_round("sigkill"),
           "handoff_reshard": fake_round("reshard")}
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched prose\n"
                  + bcp.FLEETVIEW_BEGIN + "\nsync-gap sibling tier\n"
                  + bcp.FLEETVIEW_END + "\n")
    section = bcp.render_handoff_md(res, 8, 3, 2)
    bcp.update_md_section(str(md), bcp.HANDOFF_BEGIN,
                          bcp.HANDOFF_END, section)
    text = md.read_text()
    assert "untouched prose" in text
    assert "sync-gap sibling tier" in text
    assert text.count(bcp.HANDOFF_BEGIN) == 1
    assert text.count(bcp.FLEETVIEW_BEGIN) == 1
    assert "window <= bound: yes" in text
    assert "| detection s |" in text.replace("acquisition s ", "")
    # re-running replaces, never duplicates — siblings stay intact
    bcp.update_md_section(str(md), bcp.HANDOFF_BEGIN,
                          bcp.HANDOFF_END, section)
    text = md.read_text()
    assert text.count(bcp.HANDOFF_BEGIN) == 1
    assert "sync-gap sibling tier" in text
    assert "**Reading.**" in text


def test_run_tests_sh_advertises_the_handoff_knob():
    """scripts/run-tests.sh must accept --handoff-profile and name it
    in the supported-arguments error line (the CI entry point for the
    slow tier)."""
    with open(os.path.join(REPO, "scripts", "run-tests.sh")) as f:
        sh = f.read()
    assert "--handoff-profile) RUN_HANDOFF=1 ;;" in sh
    assert "--handoff-profile" in [
        line for line in sh.splitlines() if "supported:" in line][0]
    assert "tests/test_handoff_profile.py" in sh


def test_bench_tenancy_updater_rewrites_only_its_markers(monkeypatch,
                                                         tmp_path):
    """ISSUE 17: the --tenancy renderer + section updater must rewrite
    ONLY the tenancy-delimited region — sibling sections and prose
    outside the markers stay byte-identical, and re-running replaces
    rather than duplicates.  (The fairness scenario itself runs in
    tests/test_admission.py; the slow tier via run-tests.sh --tenancy.)"""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_control_plane as bcp

    def fake_run():
        stats = {"submitted": 3, "succeeded": 3, "admitted": 3,
                 "wait_p50_s": 12.0, "wait_p99_s": 139.9,
                 "wait_max_s": 141.2}
        return {"namespaces": 4, "jobs_per_namespace": 3,
                "hostile_namespace": "tenant-hostile", "hostile_jobs": 30,
                "jobs_total": 42, "quota_jobs": 2, "cluster_max_jobs": 5,
                "seed": 7, "converged": True, "succeeded": 42,
                "virtual_wall_s": 1247.852, "real_wall_s": 3.1,
                "speedup_virtual_over_real": 402.5,
                "verb_counts": {"create": 42},
                "per_namespace": {f"tenant-00{i}": dict(stats)
                                  for i in range(4)},
                "hostile": {"submitted": 30, "succeeded": 30,
                            "admitted": 30, "wait_p50_s": 580.0,
                            "wait_p99_s": 1166.9, "wait_max_s": 1201.0},
                "compliant_wait_p99_max_s": 139.9,
                "compliant_wait_p99_median_s": 120.0,
                "hostile_wait_p99_s": 1166.9}

    res = {"runs": [fake_run(), fake_run()], "deterministic": True,
           "no_tenant_starved": True, "hostile_degraded": True,
           "compliant_bounded": True, "fair": True}
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched prose\n"
                  + bcp.FLEETVIEW_BEGIN + "\nsibling tier\n"
                  + bcp.FLEETVIEW_END + "\n")
    section = bcp.render_tenancy_md(res, 7)
    bcp.update_md_section(str(md), bcp.TENANCY_BEGIN, bcp.TENANCY_END,
                          section)
    text = md.read_text()
    assert "untouched prose" in text and "sibling tier" in text
    assert text.count(bcp.TENANCY_BEGIN) == 1
    assert text.count(bcp.FLEETVIEW_BEGIN) == 1
    assert "Tenancy verdict: FAIR" in text
    assert "tenant-hostile" in text
    # the committed JSON blob drops the per-namespace bulk but keeps
    # the verdict booleans
    assert '"fair": true' in text
    assert '"per_namespace"' not in text
    # re-running replaces, never duplicates — siblings stay intact
    bcp.update_md_section(str(md), bcp.TENANCY_BEGIN, bcp.TENANCY_END,
                          section)
    text = md.read_text()
    assert text.count(bcp.TENANCY_BEGIN) == 1
    assert "sibling tier" in text


def test_bench_profile_hotpaths_emits_parseable_ranked_table(
        monkeypatch, tmp_path):
    """ISSUE 15: --profile-hotpaths (a small sim under cProfile here)
    must emit a ranked table whose rows parse back into (rank, cum s,
    tot s, calls, function) with cumulative time non-increasing."""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_control_plane as bcp

    res = bcp.run_profile_hotpaths(jobs=15, workers=2, nodes=6, seed=7,
                                   arrival_s=40.0, max_virtual=3600.0,
                                   top=10)
    assert res["converged"], res
    assert len(res["rows"]) == 10

    md = tmp_path / "BENCH.md"
    md.write_text("# header\nkeep me\n")
    bcp.update_md_section(str(md), bcp.HOTPATHS_BEGIN, bcp.HOTPATHS_END,
                          bcp.render_hotpaths_md(res))
    text = md.read_text()
    assert "keep me" in text
    rows = re.findall(
        r"^\| (\d+) \| ([0-9.]+) \| ([0-9.]+) \| (\d+) \| `(.+)` \|$",
        text, re.M)
    assert len(rows) == 10, text
    assert [int(r[0]) for r in rows] == list(range(1, 11))
    cums = [float(r[1]) for r in rows]
    assert cums == sorted(cums, reverse=True)
    # the hot paths are real code locations (file:line:function)
    assert all(re.search(r":\d+:", r[4]) for r in rows), rows
    # the profiled run covers the operator package itself
    assert any("pytorch_operator_tpu/" in r[4] for r in rows), rows


def test_bench_latency_updater_rewrites_only_its_markers(monkeypatch,
                                                         tmp_path):
    """ISSUE 19: the --latency-budget renderer + section updater must
    rewrite ONLY the latency-delimited region — sibling sections and
    prose outside the markers stay byte-identical, and re-running
    replaces rather than duplicates.  (The subprocess round runs under
    @pytest.mark.slow in tests/test_propagation.py; the tier via
    run-tests.sh --latency-budget.)"""
    monkeypatch.syspath_prepend(os.path.join(REPO, "scripts"))
    import bench_control_plane as bcp

    def stages(scale):
        return {s: {"count": 12, "sum_s": round(0.01 * scale, 6),
                    "mean_ms": round(0.8 * scale, 3)}
                for s in ("apiserver_to_informer", "informer_to_enqueue",
                          "enqueue_to_get", "get_to_reconcile_start",
                          "reconcile_start_to_commit",
                          "watch_to_reconcile_start")}

    res = {
        "latency_inproc": {
            "variant": "inproc", "jobs": 12, "workers": 3,
            "resync_s": 30.0, "poll_s": 0.5, "wall_s": 1.1,
            "converged": True,
            "succeeded": {"median_ms": 80.0, "p95_ms": 95.0, "n": 12},
            "stages": stages(1),
            "timebudget": {
                "uptime_s": 2.0, "accounted_s": 7.9, "coverage": 0.98,
                "buckets": {"reconcile": {"seconds": 0.35, "spans": 140},
                            "queue_idle": {"seconds": 7.5, "spans": 150}},
                "threads": []},
            "propagation": {"completed": 72, "open": 0, "folded": 24}},
        "latency_subproc": {
            "variant": "subproc", "jobs": 12, "workers": 3,
            "replicas": 2, "shard_count": 2, "threadiness": 2,
            "resync_s": 30.0, "poll_s": 0.5, "converged": True,
            "wall_s": 60.0, "replicas_scraped": 2,
            "stages": stages(100),
            "timebudget": {
                "replicas": [
                    {"replica": "lb-r0", "url": "http://a",
                     "uptime_s": 62.0, "accounted_s": 123.0,
                     "coverage": 1.0,
                     "buckets": {"reconcile": 46.0, "queue_idle": 14.0}},
                    {"replica": "lb-r1", "url": "http://b",
                     "uptime_s": 63.0, "accounted_s": 124.0,
                     "coverage": 1.0,
                     "buckets": {"reconcile": 61.0, "queue_idle": 0.1}}],
                "buckets": {"reconcile": 107.0, "queue_idle": 14.1},
                "propagation": {"completed": 24, "open": 0,
                                "folded": 26}},
            "duplicate_create_conflicts": 0},
        "latency_determinism": {
            "variant": "determinism", "jobs": 24, "workers": 2,
            "seed": 7, "converged": True, "virtual_wall_s": 64.2,
            "completed": 159, "fingerprint_match": True},
    }
    md = tmp_path / "BENCH.md"
    md.write_text("# header\nuntouched prose\n"
                  + bcp.HANDOFF_BEGIN + "\nhandoff sibling tier\n"
                  + bcp.HANDOFF_END + "\n")
    section = bcp.render_latency_md(res, 12, 3, 2)
    bcp.update_md_section(str(md), bcp.LATENCY_BEGIN,
                          bcp.LATENCY_END, section)
    text = md.read_text()
    assert "untouched prose" in text
    assert "handoff sibling tier" in text
    assert text.count(bcp.LATENCY_BEGIN) == 1
    assert text.count(bcp.HANDOFF_BEGIN) == 1
    assert "| `watch_to_reconcile_start` | 12 | 0.8 | 12 | 80.0 |" \
        in text
    assert "| `reconcile` | 0.35 | 107.0 |" in text
    assert "fingerprint match = True" in text
    # re-running replaces, never duplicates — siblings stay intact
    bcp.update_md_section(str(md), bcp.LATENCY_BEGIN,
                          bcp.LATENCY_END, section)
    text = md.read_text()
    assert text.count(bcp.LATENCY_BEGIN) == 1
    assert "handoff sibling tier" in text
    assert "**Reading.**" in text


def test_run_tests_sh_advertises_the_latency_knob():
    """scripts/run-tests.sh must accept --latency-budget and name it
    in the supported-arguments error line (the CI entry point for the
    slow propagation tier)."""
    with open(os.path.join(REPO, "scripts", "run-tests.sh")) as f:
        sh = f.read()
    assert "--latency-budget) RUN_LATENCY=1 ;;" in sh
    assert "--latency-budget" in [
        line for line in sh.splitlines() if "supported:" in line][0]
    assert "tests/test_propagation.py" in sh
