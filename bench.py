"""Benchmark: dist-MNIST training throughput (images/sec/chip).

Prints exactly one JSON line on stdout:
  {"metric": ..., "value": N, "unit": "images/sec/chip", "vs_baseline": N}

Baseline: the reference's README envelope for the same workload —
dist-MNIST, 10 epochs (600k images) in "5-10 minutes" on its CI cluster
(reference README.md:37; sample run 5m53s, README.md:56-119).  Best case
600000 img / 300 s = 2000 images/sec for the whole job; we report
per-chip throughput against that number, so vs_baseline > 1 means one
TPU chip outruns the reference's whole multi-pod job.

The model is the reference example's CNN (examples/mnist/mnist.py:25-42)
re-expressed for the MXU (NHWC lax.conv, batched), trained with the same
SGD(lr=0.01, momentum=0.5) (mnist.py:106) in bfloat16 — the
TPU-appropriate dtype (the MXU's native input width; the reference's
CUDA example trains f32 because 2018-era V100 torch had no bf16).
bf16 is not a shortcut on quality: the same CNN trained in bf16 still
reaches >=98% accuracy (tests/test_models.py::test_learns_synthetic_digits
parametrized over dtype), and it lifts measured throughput +15% over
the best recorded f32 run (1.82M vs 1.58M img/s; the same-session
f32 A/B read 1.42M, a +28% gap — shared-chip conditions vary run to
run, so the conservative +15% vs the f32 record is the honest claim).

The timed batch is the repo's synthetic digit dataset
(data/mnist.synthetic — the same generator the accuracy test trains
to >=98% on), NOT random noise, so the timed loop demonstrably LEARNS:
the reported final loss falls well under 0.5 at identical per-step
cost (same shapes/dtype).  Synthetic data keeps the bench hermetic
(this environment has no dataset egress); the real-data path in
examples/mnist/train_mnist.py reaches the >=98% accuracy target the
e2e flow asserts.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

BASELINE_IMAGES_PER_SEC = 2000.0


def _probe_tpu():
    """Return the first device when a TPU backend is live, else None plus
    a reason string.

    BENCH_r05: on a box with no reachable TPU, ``jax.devices()`` raises
    and the bench exited rc=1 with a traceback.  Probe first — and when
    the accelerator init fails, re-probe under ``JAX_PLATFORMS=cpu`` so
    a missing TPU is distinguished from a broken jax install — then let
    the caller emit a machine-readable ``"skipped": true`` record.
    ``PYTORCH_OPERATOR_BENCH_CPU=1`` opts into timing the CPU anyway
    (the vs_baseline ratio is meaningless there, but the loop runs).
    """
    import jax

    try:
        dev = jax.devices()[0]
        err = None
    except Exception as e:  # UNAVAILABLE tunnels etc. aren't always RuntimeError
        dev, err = None, str(e)
        os.environ["JAX_PLATFORMS"] = "cpu"
        _clear_backend_cache()
        try:
            dev = jax.devices("cpu")[0]
        except Exception:
            return None, f"no usable jax backend (cpu fallback failed): {err}"
    if dev.platform != "tpu" and os.environ.get(
            "PYTORCH_OPERATOR_BENCH_CPU") != "1":
        return None, (err or f"no TPU backend; first device is "
                             f"{dev.platform} ({dev.device_kind})")
    return dev, None


def _emit_skipped(reason: str) -> None:
    print(f"[bench] skipped: {reason}", file=sys.stderr)
    print(json.dumps({
        "metric": "dist-MNIST training throughput",
        "unit": "images/sec/chip",
        "skipped": True,
        "reason": reason,
    }))


def _is_backend_init_error(e: BaseException) -> bool:
    """A RuntimeError that smells like PJRT backend init dying (the
    BENCH_r05 shape: jax.devices() raising UNAVAILABLE through a downed
    TPU tunnel) rather than a bug in the measured code."""
    msg = str(e)
    return any(marker in msg for marker in (
        "UNAVAILABLE",
        "Unable to initialize backend",
        "TPU backend",
        "DEADLINE_EXCEEDED",
        "backend setup",
    ))


def _clear_backend_cache() -> bool:
    """Drop jax's cached PJRT clients so the next ``jax.devices()``
    really re-initializes.  ``jax.extend`` is NOT exposed by a plain
    ``import jax`` (the bare attribute access raises AttributeError on
    this jax) — it must be imported explicitly."""
    try:
        from jax.extend import backend

        backend.clear_backends()
        return True
    except Exception:
        return False


def _backend_alive_on_reprobe() -> bool:
    """Confirm an infra-looking measurement error really is infra: drop
    the cached PJRT client and re-init.  A healthy re-init means the
    backend is alive, so the error was a genuine bug in the measured
    code (the marker match alone can't tell — a real regression's
    message may contain "TPU backend" or DEADLINE_EXCEEDED); re-init
    raising — or hanging, which a dead tunnel can do — means the round
    really is skippable.  The re-init runs on a daemon thread bounded
    by GRAFT_BACKEND_PROBE_TIMEOUT (like dryrun_multichip's probe) so
    a hung tunnel can't wedge the bench."""
    import threading

    import jax

    if not _clear_backend_cache():
        # can't drop the cache -> jax.devices() would just read the
        # stale client list and "confirm" a dead backend alive; fall
        # back to trusting the marker match (the skip-leaning default
        # this satellite exists for)
        return False
    alive = []

    def _probe():
        try:
            jax.devices()
        except Exception:
            return
        alive.append(True)

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(float(os.environ.get("GRAFT_BACKEND_PROBE_TIMEOUT", "45")))
    return bool(alive)


def main() -> None:
    dev, skip_reason = _probe_tpu()
    if dev is None:
        _emit_skipped(skip_reason)
        return
    try:
        _measure(dev)
    except Exception as e:  # UNAVAILABLE isn't always RuntimeError (probe ↑)
        # ROADMAP direction 5 tail: a backend that passed the probe but
        # died before/while measuring (flaky tunnel) is a skipped round
        # — rc=1 here poisoned the BENCH_r05 trend.  Genuine measurement
        # bugs still crash loudly, including ones whose message merely
        # contains an infra marker: the re-probe sees a live backend
        # and re-raises.
        if not _is_backend_init_error(e) or _backend_alive_on_reprobe():
            raise
        _emit_skipped(f"backend died during measurement: {e}")


def _measure(dev) -> None:
    import jax

    # persistent compile cache: first bench run pays the (slow) TPU
    # compile, later runs start timing almost immediately
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_bench_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    import jax.numpy as jnp
    import optax

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from pytorch_operator_tpu.models import mnist_cnn

    # Measured-best batch (2026-07-30 v5e sweeps): f32 peaked at 2048
    # (1024 -> 1.34M, 2048 -> 1.58M, 4096 -> 1.08M); under bf16, 2048
    # and 4096 are at parity within shared-chip noise (~1.8-1.87M) —
    # 2048 kept for its lower variance.
    batch_size = 2048
    if dev.platform != "tpu":
        # explicit CPU opt-in: shrink the shape so the run finishes
        batch_size = 256
    # Long enough that the fixed per-launch cost (~tens of ms through
    # the device tunnel: dispatch round-trip + completion fetch) is <2%
    # of the timed region instead of ~50% at 50 steps — the region is
    # one device program either way, so this only amortizes measurement
    # overhead, it does not change per-step work.
    steps_timed = 400

    dev = jax.devices()[0]
    print(f"[bench] device: {dev.device_kind}", file=sys.stderr)

    from pytorch_operator_tpu.data import mnist as mnist_data

    # learnable synthetic digits (the accuracy test's generator), so the
    # timed loss visibly falls — same shapes/dtype as the old noise
    # batch, so per-step cost is identical
    imgs_np, lbls_np = mnist_data.synthetic(batch_size, seed=0)
    images = jnp.asarray(imgs_np, jnp.bfloat16)
    labels = jnp.asarray(lbls_np)

    params = mnist_cnn.init_params(jax.random.key(2), dtype=jnp.bfloat16)
    opt = optax.sgd(0.01, momentum=0.5)
    opt_state = opt.init(params)

    # The whole timed region is ONE device program (lax.scan over steps,
    # donated carry) — how a real TPU training loop runs, with no host
    # dispatch between steps.  steps_timed is a static trip count; the
    # batch is a jit argument (not a closure) so it isn't baked into the
    # executable as a constant once per trip count.
    @partial(jax.jit, donate_argnums=(0, 1), static_argnums=(4,))
    def run(params, opt_state, images, labels, n):
        from jax import lax

        def step(carry, _):
            params, opt_state = carry

            def loss_fn(p):
                return mnist_cnn.nll_loss(mnist_cnn.forward(p, images), labels)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, opt_state), losses = lax.scan(
            step, (params, opt_state), None, length=n)
        return params, opt_state, losses[-1]

    # warmup / compile
    t0 = time.perf_counter()
    params, opt_state, loss = run(params, opt_state, images, labels, 3)
    _ = float(loss)  # host round-trip: guarantees the work really ran
    print(f"[bench] compile+warmup: {time.perf_counter() - t0:.1f}s",
          file=sys.stderr)

    # compile the timed trip count too, so timing excludes compilation
    params, opt_state, loss = run(params, opt_state, images, labels,
                                  steps_timed)
    _ = float(loss)

    # Timed region ends with a host fetch of a value that depends on the
    # last step (loss), whose carry chains through every prior step, so
    # async dispatch or a lazy transfer layer can't fake completion.
    # Best of 3 rounds filters shared-chip contention spikes.
    dt = float("inf")
    for _round in range(3):
        t0 = time.perf_counter()
        params, opt_state, loss = run(params, opt_state, images, labels,
                                      steps_timed)
        final_loss = float(loss)
        dt = min(dt, time.perf_counter() - t0)

    images_per_sec = batch_size * steps_timed / dt
    print(
        f"[bench] {steps_timed} steps x {batch_size} imgs in {dt:.3f}s "
        f"(best of 3 rounds), loss after all warmup+timed rounds "
        f"{final_loss:.4f}",
        file=sys.stderr,
    )

    print(json.dumps({
        "metric": "dist-MNIST training throughput",
        "value": round(images_per_sec, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(images_per_sec / BASELINE_IMAGES_PER_SEC, 2),
    }))


if __name__ == "__main__":
    main()
