"""Distributed MNIST on TPU — the data-plane workload for PyTorchJob.

TPU-native rewrite of the reference example
(reference: examples/mnist/mnist.py): instead of
`dist.init_process_group(backend)` + DistributedDataParallel
(mnist.py:116,135-138), multi-host coordination comes from the env the
controller injects (TPU_WORKER_ID / TPU_WORKER_HOSTNAMES /
MASTER_ADDR:MASTER_PORT) via `jax.distributed.initialize`, and data
parallelism is a batch sharded over a global `jax.sharding.Mesh` — XLA
emits the gradient all-reduce over ICI.

Prints `accuracy={:.4f}` per epoch — the success signal the e2e flow
parses from logs (reference: mnist.py:64).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)


from pytorch_operator_tpu.utils import maybe_init_distributed


def main() -> int:
    parser = argparse.ArgumentParser(description="TPU MNIST")
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-step GLOBAL batch size")
    parser.add_argument("--test-batch-size", type=int, default=1000)
    parser.add_argument("--epochs", type=int, default=10)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--momentum", type=float, default=0.5)
    parser.add_argument("--seed", type=int, default=1)
    parser.add_argument("--log-interval", type=int, default=10)
    parser.add_argument("--data-dir", type=str, default=None,
                        help="dir with MNIST idx files; synthetic if absent")
    parser.add_argument("--synthetic-size", type=int, default=16384)
    parser.add_argument("--target-accuracy", type=float, default=0.0,
                        help="exit once test accuracy reaches this")
    parser.add_argument("--save-model", type=str, default=None)
    parser.add_argument("--profile-dir", type=str, default=None,
                        help="capture a jax.profiler trace of the first "
                             "epoch's steps 1..--profile-steps here "
                             "(view: tensorboard --logdir <dir>)")
    parser.add_argument("--profile-steps", type=int, default=10)
    args = parser.parse_args()

    pid, nprocs = maybe_init_distributed()

    import jax

    from pytorch_operator_tpu.utils import apply_platform_env

    apply_platform_env()

    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_operator_tpu.data import mnist as mnist_data
    from pytorch_operator_tpu.models import mnist_cnn
    from pytorch_operator_tpu.parallel.mesh import AXIS_DP

    devices = jax.devices()
    print(f"[worker {pid}/{nprocs}] devices: {len(devices)} x "
          f"{devices[0].device_kind}", flush=True)

    mesh = jax.sharding.Mesh(np.asarray(devices), (AXIS_DP,))
    data_sharding = NamedSharding(mesh, P(AXIS_DP))
    repl = NamedSharding(mesh, P())

    if args.batch_size % len(devices):
        rounded = args.batch_size + len(devices) - args.batch_size % len(devices)
        print(f"[worker {pid}] --batch-size {args.batch_size} is not divisible "
              f"by {len(devices)} devices; using {rounded}", flush=True)
        args.batch_size = rounded

    xtr, ytr = mnist_data.load(args.data_dir, split="train",
                               synthetic_size=args.synthetic_size,
                               seed=args.seed + pid)
    xte, yte = mnist_data.load(args.data_dir, split="test",
                               synthetic_size=max(args.synthetic_size // 8, 512),
                               seed=args.seed)

    params = jax.device_put(
        mnist_cnn.init_params(jax.random.key(args.seed)), repl)
    opt = optax.sgd(args.lr, momentum=args.momentum)
    opt_state = jax.device_put(opt.init(params), repl)

    @jax.jit
    def train_step(params, opt_state, x, y):
        def loss_fn(p):
            return mnist_cnn.nll_loss(mnist_cnn.forward(p, x), y)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    @jax.jit
    def eval_step(params, x, y):
        logp = mnist_cnn.forward(params, x)
        return (mnist_cnn.nll_loss(logp, y) * y.shape[0],
                jnp.sum(jnp.argmax(logp, -1) == y))

    # --profile-dir: trace steps [1, profile_steps] of epoch 1 — step 0 is
    # skipped so compilation doesn't drown the trace (SURVEY §5 tracing ask;
    # the reference delegates profiling to cAdvisor, docs/monitoring).
    profiling = False
    steps_per_epoch = len(xtr) // args.batch_size
    for epoch in range(1, args.epochs + 1):
        t0 = time.perf_counter()
        for i, (x, y) in enumerate(
            mnist_data.batches(xtr, ytr, args.batch_size, seed=epoch)
        ):
            if (args.profile_dir and args.profile_steps >= 1
                    and epoch == 1 and i == 1 and pid == 0):
                jax.profiler.start_trace(args.profile_dir)
                profiling = True
            x = jax.device_put(x, data_sharding)
            y = jax.device_put(y, data_sharding)
            params, opt_state, loss = train_step(params, opt_state, x, y)
            if profiling and i == args.profile_steps:
                jax.block_until_ready(params)
                jax.profiler.stop_trace()
                profiling = False
                print(f"profile trace written to {args.profile_dir}",
                      flush=True)
            if i % args.log_interval == 0:
                print(
                    f"Train Epoch: {epoch} [{i * args.batch_size}/{len(xtr)} "
                    f"({100. * i / steps_per_epoch:.0f}%)]\t"
                    f"loss={float(loss):.4f}", flush=True)
        jax.block_until_ready(params)
        if profiling:  # epoch shorter than --profile-steps
            jax.profiler.stop_trace()
            profiling = False
            print(f"profile trace written to {args.profile_dir}", flush=True)
        train_dt = time.perf_counter() - t0

        total_loss, total_correct = 0.0, 0
        for x, y in mnist_data.batches(xte, yte, args.test_batch_size,
                                       drop_last=False):
            l, c = eval_step(params, x, y)
            total_loss += float(l)
            total_correct += int(c)
        acc = total_correct / len(xte)
        img_per_sec = steps_per_epoch * args.batch_size / train_dt
        print(f"\nTest set: Average loss: {total_loss / len(xte):.4f}, "
              f"Accuracy: {total_correct}/{len(xte)} ({100. * acc:.0f}%); "
              f"{img_per_sec:.0f} img/s\n", flush=True)
        print(f"accuracy={acc:.4f}", flush=True)
        if args.target_accuracy and acc >= args.target_accuracy:
            print(f"reached target accuracy {args.target_accuracy}", flush=True)
            break

    if args.save_model and pid == 0:
        flat = {
            jax.tree_util.keystr(path): np.asarray(leaf)
            for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
        }
        np.savez(args.save_model, **flat)
        print(f"saved model to {args.save_model}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
