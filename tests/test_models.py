"""Data-plane model tests (CPU, tiny shapes).

Mirrors the reference's pure-function test tier (SURVEY.md §4 tier 1)
for the model zoo the reference only ships as examples.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_operator_tpu.models import llama, mnist_cnn


@pytest.fixture(scope="module")
def tiny_cfg():
    return llama.tiny()


class TestLlama:
    def test_forward_shape(self, tiny_cfg):
        params = llama.init_params(jax.random.key(0), tiny_cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = llama.forward(params, tokens, tiny_cfg)
        assert logits.shape == (2, 16, tiny_cfg.vocab_size)
        assert logits.dtype == jnp.float32
        assert np.isfinite(np.asarray(logits)).all()

    def test_causality(self, tiny_cfg):
        """Changing a future token must not change past logits."""
        params = llama.init_params(jax.random.key(0), tiny_cfg)
        t1 = jnp.zeros((1, 8), jnp.int32)
        t2 = t1.at[0, 7].set(3)
        l1 = llama.forward(params, t1, tiny_cfg)
        l2 = llama.forward(params, t2, tiny_cfg)
        np.testing.assert_allclose(
            np.asarray(l1[0, :7]), np.asarray(l2[0, :7]), rtol=1e-5
        )
        assert not np.allclose(np.asarray(l1[0, 7]), np.asarray(l2[0, 7]))

    def test_gqa_matches_mha_shapes(self):
        cfg = llama.tiny(n_heads=8, n_kv_heads=2)
        params = llama.init_params(jax.random.key(0), cfg)
        logits = llama.forward(params, jnp.zeros((1, 4), jnp.int32), cfg)
        assert logits.shape == (1, 4, cfg.vocab_size)

    def test_remat_matches(self, tiny_cfg):
        cfg_r = llama.tiny(remat=True)
        params = llama.init_params(jax.random.key(0), tiny_cfg)
        tokens = jnp.arange(16, dtype=jnp.int32)[None]
        a = llama.forward(params, tokens, tiny_cfg)
        b = llama.forward(params, tokens, cfg_r)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)

    def test_save_attn_remat_skips_flash_recompute(self):
        """remat_policy='save_attn' (VERDICT r3 item 2): the saved
        (out, lse) names must make the flash FORWARD kernel dead code in
        the remat backward — one flash_fwd pallas call in the whole grad
        jaxpr instead of full remat's two — while grads stay exact."""
        import dataclasses

        cfg0 = llama.tiny(max_seq_len=256, n_heads=4, n_kv_heads=2,
                          dim=128, use_flash=True)
        params = llama.init_params(jax.random.key(0), cfg0)
        tokens = jax.random.randint(jax.random.key(1), (2, 256), 0,
                                    cfg0.vocab_size)

        def kernel_counts(cfg):
            def loss(p):
                return jnp.mean(llama.forward(p, tokens, cfg) ** 2)

            jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
            counts: dict = {}
            seen: set = set()

            def walk(jx):
                if id(jx) in seen:
                    return
                seen.add(id(jx))
                for eqn in jx.eqns:
                    if eqn.primitive.name == "pallas_call":
                        nm = str(eqn.params["name"])
                        counts[nm] = counts.get(nm, 0) + 1
                    for v in eqn.params.values():
                        stack = [v]
                        while stack:
                            x = stack.pop()
                            if hasattr(x, "eqns"):
                                walk(x)
                            elif hasattr(x, "jaxpr"):
                                walk(x.jaxpr)
                            elif isinstance(x, (list, tuple)):
                                stack.extend(x)

            walk(jaxpr.jaxpr)
            return counts

        full = dataclasses.replace(cfg0, remat=True, remat_policy=None)
        save = dataclasses.replace(cfg0, remat=True,
                                   remat_policy="save_attn")
        c_full, c_save = kernel_counts(full), kernel_counts(save)
        assert c_full.get("flash_fwd") == 2, c_full  # primal + recompute
        assert c_save.get("flash_fwd") == 1, c_save  # recompute DCE'd

        def grads(cfg):
            def loss(p):
                return jnp.mean(llama.forward(p, tokens, cfg) ** 2)

            return jax.grad(loss)(params)

        for a, b in zip(jax.tree.leaves(grads(cfg0)),
                        jax.tree.leaves(grads(save))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)

    def test_save_attn_requires_flash(self):
        cfg = llama.tiny(remat=True, remat_policy="save_attn")
        params = llama.init_params(jax.random.key(0), cfg)
        with pytest.raises(ValueError, match="use_flash"):
            llama.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)

    def test_composite_save_tiers_exact_and_fewer_recomputes(self):
        """round-5 item 2: 'save_attn+qkv+gateup+normed' must (a) keep
        grads exactly equal to plain save_attn and (b) strictly shrink
        the backward's recompute (fewer dot_generals in the grad jaxpr
        — the saved projections/SwiGLU matmuls are no longer re-run)."""
        import dataclasses

        cfg0 = llama.tiny(max_seq_len=128, n_heads=4, n_kv_heads=2,
                          dim=64, use_flash=True)
        params = llama.init_params(jax.random.key(3), cfg0)
        tokens = jax.random.randint(jax.random.key(4), (1, 128), 0,
                                    cfg0.vocab_size)

        def grads_and_dots(cfg):
            def loss(p):
                return jnp.mean(llama.forward(p, tokens, cfg) ** 2)

            jaxpr = jax.make_jaxpr(jax.grad(loss))(params)
            n_dots = 0
            seen: set = set()

            def walk(jx):
                nonlocal n_dots
                if id(jx) in seen:
                    return
                seen.add(id(jx))
                for eqn in jx.eqns:
                    if eqn.primitive.name == "dot_general":
                        n_dots += 1
                    for v in eqn.params.values():
                        stack = [v]
                        while stack:
                            x = stack.pop()
                            if hasattr(x, "eqns"):
                                walk(x)
                            elif hasattr(x, "jaxpr"):
                                walk(x.jaxpr)
                            elif isinstance(x, (list, tuple)):
                                stack.extend(x)

            walk(jaxpr.jaxpr)
            return jax.grad(loss)(params), n_dots

        base = dataclasses.replace(cfg0, remat=True,
                                   remat_policy="save_attn")
        rich = dataclasses.replace(
            cfg0, remat=True,
            remat_policy="save_attn+qkv+gateup+normed")
        g_base, dots_base = grads_and_dots(base)
        g_rich, dots_rich = grads_and_dots(rich)
        assert dots_rich < dots_base, (dots_rich, dots_base)
        for a, b in zip(jax.tree.leaves(g_base), jax.tree.leaves(g_rich)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)

    def test_composite_save_tier_unknown_group_rejected(self):
        cfg = llama.tiny(use_flash=True, remat=True,
                         remat_policy="save_attn+bogus")
        params = llama.init_params(jax.random.key(0), cfg)
        with pytest.raises(ValueError, match="unknown save group"):
            llama.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)

    def test_auto_remat_policy_headroom_math(self):
        """The batch-adaptive selector: richest tier at short T, leaner
        tiers as saved bytes grow, never an invalid policy; fsdp/sp
        sharding restores headroom."""
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
            n_kv_heads=16, ffn_dim=5632, max_seq_len=32768,
            dtype=jnp.bfloat16, use_flash=True, remat=True)
        assert llama.n_params(cfg) == pytest.approx(888e6, rel=0.01)
        rich = llama.auto_remat_policy(cfg, 2, 4096)
        lean = llama.auto_remat_policy(cfg, 1, 32768)
        assert rich == "save_attn+qkv+gateup+normed"
        assert lean in ("save_attn", "save_attn+normed")
        # monotone: more tokens never yields a richer tier
        order = ["save_attn", "save_attn+normed", "save_attn+qkv",
                 "save_attn+gateup", "save_attn+qkv+gateup",
                 "save_attn+qkv+gateup+normed"]
        prev = len(order)
        for toks in (4096, 8192, 16384, 32768, 65536):
            tier = llama.auto_remat_policy(cfg, 1, toks)
            assert tier in order
            assert order.index(tier) <= prev
            prev = order.index(tier)
        # fsdp sharding (state + activations) restores headroom
        sharded = llama.auto_remat_policy(cfg, 8, 32768, state_shards=8,
                                          token_shards=8)
        assert order.index(sharded) >= order.index(lean)
        # sp shards TOKENS but never the optimizer state: at sp=8 the
        # replicated ~5.3 GB state must still be charged in full, so
        # the tier is leaner than the fsdp=8 case with equal tokens
        sp_only = llama.auto_remat_policy(cfg, 8, 32768, state_shards=1,
                                          token_shards=8)
        assert order.index(sp_only) <= order.index(sharded)

    @pytest.mark.parametrize("T,chunk", [(256, 128), (300, 128), (64, 2048)])
    def test_chunked_tied_ce_matches_full_head(self, T, chunk):
        """chunked_tied_ce == cross_entropy_loss(full logits) for exact,
        RAGGED (300 % 128 != 0 — must stay chunked, not collapse to one
        full-T chunk) and chunk>T cases, values and grads."""
        from pytorch_operator_tpu.parallel.train import (
            chunked_tied_ce,
            cross_entropy_loss,
        )

        cfg = llama.tiny(max_seq_len=T)
        params = llama.init_params(jax.random.key(0), cfg)
        tokens = jax.random.randint(jax.random.key(1), (2, T + 1), 0,
                                    cfg.vocab_size)

        def loss_chunked(p):
            h = llama.forward_hidden(p, tokens[:, :-1], cfg)
            return chunked_tied_ce(h, p["embed"], tokens[:, 1:], chunk)

        def loss_full(p):
            return cross_entropy_loss(llama.forward(p, tokens[:, :-1], cfg),
                                      tokens[:, 1:])

        la, ga = jax.value_and_grad(loss_chunked)(params)
        lb, gb = jax.value_and_grad(loss_full)(params)
        np.testing.assert_allclose(float(la), float(lb), rtol=1e-6)
        for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)

    def test_param_specs_cover_params(self, tiny_cfg):
        params = llama.init_params(jax.random.key(0), tiny_cfg)
        specs = llama.param_specs(tiny_cfg)
        p_struct = jax.tree.structure(params)
        s_struct = jax.tree.structure(specs, is_leaf=lambda x: x is None or hasattr(x, "index"))
        assert p_struct.num_leaves == s_struct.num_leaves

    def test_loss_decreases_single_device(self, tiny_cfg):
        opt = optax.adam(1e-2)
        params = llama.init_params(jax.random.key(0), tiny_cfg)
        opt_state = opt.init(params)
        batch = jax.random.randint(jax.random.key(1), (4, 17), 0, tiny_cfg.vocab_size)

        @jax.jit
        def step(params, opt_state, batch):
            def loss_fn(p):
                logits = llama.forward(p, batch[:, :-1], tiny_cfg)
                logp = jax.nn.log_softmax(logits, axis=-1)
                return -jnp.mean(
                    jnp.take_along_axis(logp, batch[:, 1:, None], axis=-1)
                )
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses


class TestMnistCNN:
    def test_forward_shape(self):
        params = mnist_cnn.init_params(jax.random.key(0))
        x = jnp.zeros((4, 28, 28, 1))
        out = mnist_cnn.forward(params, x)
        assert out.shape == (4, 10)
        # log_softmax rows sum to ~1 in prob space
        np.testing.assert_allclose(
            np.exp(np.asarray(out)).sum(-1), np.ones(4), rtol=1e-5
        )

    def test_overfits_tiny_batch(self):
        params = mnist_cnn.init_params(jax.random.key(0))
        opt = optax.adam(1e-3)
        opt_state = opt.init(params)
        x = jax.random.normal(jax.random.key(1), (16, 28, 28, 1))
        y = jax.random.randint(jax.random.key(2), (16,), 0, 10)

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                return mnist_cnn.nll_loss(mnist_cnn.forward(p, x), y)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        for _ in range(60):
            params, opt_state, loss = step(params, opt_state)
        acc = float(mnist_cnn.accuracy(mnist_cnn.forward(params, x), y))
        assert acc > 0.9, (acc, float(loss))

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    def test_learns_synthetic_digits(self, dtype):
        """End-to-end: CNN learns the synthetic fallback dataset.

        The bf16 case backs bench.py's dtype choice (the MXU-native
        width) with the same accuracy bar as f32 — bf16 is a TPU-first
        representation, not a quality shortcut."""
        from pytorch_operator_tpu.data import mnist as mnist_data

        xtr, ytr = mnist_data.load(None, split="train", synthetic_size=2048)
        xte, yte = mnist_data.load(None, split="test", synthetic_size=512)
        params = mnist_cnn.init_params(jax.random.key(0), dtype=dtype)
        opt = optax.sgd(0.05, momentum=0.9)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, x, y):
            def loss_fn(p):
                return mnist_cnn.nll_loss(
                    mnist_cnn.forward(p, x.astype(dtype)), y)
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        for epoch in range(5):
            for x, y in mnist_data.batches(xtr, ytr, 128, seed=epoch):
                params, opt_state, _ = step(params, opt_state, x, y)
        acc = float(mnist_cnn.accuracy(
            mnist_cnn.forward(params, xte.astype(dtype)), yte))
        assert acc > 0.98, acc
