"""Per-event propagation ledger: where does a watch event's time go?

The steady-state control-plane path — apiserver sends a watch frame,
the informer receives it, the handler enqueues the job key, a worker
gets it, reconcile starts, the status commit lands — was only visible
as aggregate histograms (queue duration, reconcile duration).  This
module stamps ONE ledger record per in-flight job event and, when the
reconcile pass that consumed it completes, decomposes the whole journey
into named stages:

  ``apiserver_to_informer``      wire + delivery (wall-clock domain:
                                 the sender stamps ``sentWall`` on the
                                 frame, the informer stamps receipt)
  ``informer_to_enqueue``        handler dispatch until workqueue add
  ``enqueue_to_get``             queue wait until a worker popped it
  ``get_to_reconcile_start``     worker bookkeeping before sync_job
  ``reconcile_start_to_commit``  sync work until the status patch ack
  ``watch_to_reconcile_start``   birth -> reconcile start (the SLO
                                 input: the sum of the first four)

Design constraints, in order:

  * **Never mutate watch objects.**  Delivered objects are shared
    read-only references (the cache mutation detector enforces it), so
    stamps live in this side-channel ledger keyed by job key and the
    cross-process birth stamp travels OUT OF BAND — a ``sentWall``
    field on the watch frame, relayed to the informer through a
    thread-local (:func:`set_event_birth`), never written into the
    object.
  * **First event wins.**  Watch events coalesce (the informer's burst
    coalescing, the workqueue's dirty dedupe), so a burst of N events
    resolves to one reconcile.  The ledger measures the OLDEST
    unprocessed event: while a record is open for a key, later events
    fold into it (counted in ``folded`` — loss of per-event resolution
    is visible, never silent).
  * **Byte-deterministic under the simulator.**  Every stamp flows
    through the injected ``clock``/``wall`` pair; with both bound to a
    VirtualClock the snapshot is identical across same-seed runs.  The
    in-process fake tier sends no ``sentWall`` (its dispatch is
    synchronous — birth IS receipt), so ``apiserver_to_informer`` is
    exactly 0.0 there, which is also the honest decomposition.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..analysis.witness import make_lock

#: Stage order is the pipeline order; renderers should preserve it.
STAGES = (
    "apiserver_to_informer",
    "informer_to_enqueue",
    "enqueue_to_get",
    "get_to_reconcile_start",
    "reconcile_start_to_commit",
    "watch_to_reconcile_start",
)

# -- birth-stamp channel ------------------------------------------------------
#
# The watch dispatcher (k8s/rest.py) sets the frame's sentWall here
# around its synchronous listener fan-out; the informer's receive hook
# reads it on the same thread.  A thread-local (not an argument) because
# the listener signature ``fn(event_type, obj)`` is a wide contract —
# every source wrapper (EpochFencedSource, LabelFilteredSource, the
# fake store) forwards it untouched, and none of them need to know
# about propagation for the stamp to survive the chain.

_birth = threading.local()


def set_event_birth(wall: Optional[float]) -> Optional[float]:
    """Install the in-flight event's birth wall-time for this thread;
    returns the prior value so dispatchers can restore it (nested
    dispatch: a handler mutating the source re-enters delivery)."""
    prior = getattr(_birth, "wall", None)
    _birth.wall = wall
    return prior


def get_event_birth() -> Optional[float]:
    """The birth wall-time of the event currently being dispatched on
    this thread, or None (in-process tiers, resync-synthesized events)."""
    return getattr(_birth, "wall", None)


class PropagationLedger:
    """Side-channel stage stamps for in-flight job events.

    One open record per job key from ``note_receive`` until the
    consuming reconcile calls ``complete``; completed records keep
    their stage decomposition in a bounded ring for
    ``/debug/timebudget``, and each stage observes into
    ``pytorch_operator_event_propagation_seconds{stage}`` when a
    registry is attached.
    """

    #: must contain the 1.0 bound the event_propagation SLO sits on
    BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
               0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

    def __init__(self, registry=None,
                 clock: Optional[Callable[[], float]] = None,
                 wall: Optional[Callable[[], float]] = None,
                 replica_id: str = "", max_records: int = 256):
        self._clock = clock or time.monotonic
        self._wall = wall or time.time
        self.replica_id = replica_id
        self._lock = make_lock("runtime.propagation")
        self._open: Dict[str, dict] = {}
        self._records: deque = deque(maxlen=max(1, int(max_records)))
        self.folded = 0
        self.completed_total = 0
        self._stage_hist = None
        if registry is not None:
            self._stage_hist = registry.histogram_vec(
                "pytorch_operator_event_propagation_seconds",
                "Per-stage latency of a job watch event's journey from "
                "apiserver send to status-commit ack (first event of a "
                "coalesced burst; later events fold into the open "
                "record)",
                ("stage",), buckets=self.BUCKETS)

    # -- stamps (pipeline order) -------------------------------------------
    def note_receive(self, key: str,
                     birth: Optional[float] = None) -> None:
        """Informer received a watch event for ``key``.  Opens the
        record; while one is already open the event folds into it."""
        now = self._clock()
        with self._lock:
            rec = self._open.get(key)
            if rec is not None:
                rec["folded"] += 1
                self.folded += 1
                return
            self._open[key] = {
                "key": key,
                "birth_wall": birth,
                "receive_wall": self._wall(),
                "receive": now,
                "folded": 0,
            }

    def note_enqueue(self, key: str) -> None:
        """The key landed in a workqueue (first landing wins)."""
        now = self._clock()
        with self._lock:
            rec = self._open.get(key)
            if rec is not None and "enqueue" not in rec:
                rec["enqueue"] = now

    def note_get(self, key: str) -> None:
        """A worker popped the key."""
        now = self._clock()
        with self._lock:
            rec = self._open.get(key)
            if rec is not None and "get" not in rec:
                rec["get"] = now

    def note_reconcile_start(self, key: str) -> None:
        now = self._clock()
        with self._lock:
            rec = self._open.get(key)
            if rec is not None and "start" not in rec:
                rec["start"] = now

    def note_commit(self, key: str) -> None:
        """A status patch for the key actually landed on the apiserver."""
        now = self._clock()
        with self._lock:
            rec = self._open.get(key)
            if rec is not None:
                rec["commit"] = now

    def complete(self, key: str, result: str = "") -> Optional[dict]:
        """Close the key's record at the end of its reconcile pass,
        derive the stage decomposition, observe the histogram series
        and retain the record for the debug surface.  No-op (returns
        None) when no record is open — pod-driven requeues never opened
        one."""
        with self._lock:
            rec = self._open.pop(key, None)
        if rec is None:
            return None
        stages: Dict[str, float] = {}
        receive = rec["receive"]
        # wall-clock domain stage: only measurable when the sender
        # stamped the frame; in-process dispatch is synchronous, 0.0
        birth = rec.get("birth_wall")
        stages["apiserver_to_informer"] = (
            max(0.0, rec["receive_wall"] - birth)
            if birth is not None else 0.0)
        prev = receive
        for stamp, stage in (("enqueue", "informer_to_enqueue"),
                             ("get", "enqueue_to_get"),
                             ("start", "get_to_reconcile_start"),
                             ("commit", "reconcile_start_to_commit")):
            at = rec.get(stamp)
            if at is None:
                break
            stages[stage] = max(0.0, at - prev)
            prev = at
        if "start" in rec:
            stages["watch_to_reconcile_start"] = (
                stages["apiserver_to_informer"]
                + max(0.0, rec["start"] - receive))
        done = {
            "key": key,
            "result": result,
            "wall": round(rec["receive_wall"], 6),
            "folded": rec["folded"],
            "stages": {s: round(stages[s], 6)
                       for s in STAGES if s in stages},
        }
        if self._stage_hist is not None:
            for stage, seconds in done["stages"].items():
                self._stage_hist.labels(stage=stage).observe(seconds)
        with self._lock:
            self._records.append(done)
            self.completed_total += 1
        return done

    # -- debug surface ------------------------------------------------------
    def snapshot(self, limit: Optional[int] = None) -> dict:
        """JSON-ready ledger state, newest record first; byte-stable
        across same-seed virtual-clock runs."""
        with self._lock:
            records = list(self._records)
            open_count = len(self._open)
            folded = self.folded
            completed = self.completed_total
        records.reverse()
        if limit is not None:
            records = records[:max(0, limit)]
        return {
            "replica": self.replica_id,
            "open": open_count,
            "completed": completed,
            "folded": folded,
            "records": records,
        }


__all__ = ["PropagationLedger", "STAGES", "set_event_birth",
           "get_event_birth"]
