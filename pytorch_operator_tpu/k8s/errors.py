"""Kubernetes-style API errors shared by the real and fake clients."""

from __future__ import annotations


class ApiError(Exception):
    code = 500

    def __init__(self, message: str = ""):
        super().__init__(message or self.__class__.__name__)
        self.message = message


class NotFoundError(ApiError):
    code = 404


class AlreadyExistsError(ApiError):
    code = 409


class ConflictError(ApiError):
    """Update rejected due to a stale resourceVersion."""

    code = 409


class InvalidError(ApiError):
    code = 422


def is_not_found(err: Exception) -> bool:
    return isinstance(err, NotFoundError)


def is_already_exists(err: Exception) -> bool:
    return isinstance(err, AlreadyExistsError)
