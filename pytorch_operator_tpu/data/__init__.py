"""Input pipelines for the example workloads."""

from pytorch_operator_tpu.data import mnist

__all__ = ["mnist"]
