"""TLS client-path tests: the production (HTTPS) surface of the REST
client, exercised against an ssl-wrapped stub API server.

Covers both transports — the native C++ one (dlopen'd OpenSSL,
native/src/tls.cc) and the Python ssl/http.client fallback — plus
KubeConfig's TLS plumbing: ssl_context(), kubeconfig cert-data
materialisation (k8s/rest.py), and in-cluster service-account config.
Certificates are minted at session setup with the openssl CLI (tests
skip if it's absent).  Reference parity: the Go binary's HTTPS
rest.Config path (cmd/pytorch-operator.v1/app/server.go:92-99).
"""

from __future__ import annotations

import base64
import os
import shutil
import ssl
import subprocess
import time

import pytest

from pytorch_operator_tpu.k8s import rest as rest_mod
from pytorch_operator_tpu.k8s.errors import NotFoundError
from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster
from pytorch_operator_tpu.k8s.stub_server import StubApiServer

pytestmark = pytest.mark.skipif(
    shutil.which("openssl") is None, reason="openssl CLI not available")


def _selfsigned(dirpath, name, cn="127.0.0.1", san="IP:127.0.0.1"):
    """One self-signed cert+key pair; returns (cert_path, key_path)."""
    cert = os.path.join(dirpath, f"{name}.crt")
    key = os.path.join(dirpath, f"{name}.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "2",
         "-subj", f"/CN={cn}", "-addext", f"subjectAltName={san}"],
        check=True, capture_output=True)
    return cert, key


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("tls"))
    server_crt, server_key = _selfsigned(d, "server")
    client_crt, client_key = _selfsigned(d, "client", cn="operator-client",
                                         san="DNS:operator-client")
    rogue_crt, _rogue_key = _selfsigned(d, "rogue")
    return {"dir": d,
            "server_crt": server_crt, "server_key": server_key,
            "client_crt": client_crt, "client_key": client_key,
            "rogue_crt": rogue_crt}


def _server_ctx(certs, require_client_cert=False):
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certs["server_crt"], certs["server_key"])
    if require_client_cert:
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(certs["client_crt"])
    return ctx


@pytest.fixture
def tls_stub(certs):
    server = StubApiServer(ssl_context=_server_ctx(certs)).start()
    yield server
    server.stop()


@pytest.fixture
def mtls_stub(certs):
    server = StubApiServer(
        ssl_context=_server_ctx(certs, require_client_cert=True)).start()
    yield server
    server.stop()


@pytest.fixture(params=["native", "python"])
def transport(request, monkeypatch):
    """Run each test over the native TLS transport and the Python ssl
    fallback.  The native tier is a hard requirement when the runtime
    libssl is present — a broken native TLS build must fail the suite,
    not silently re-test the fallback."""
    if request.param == "python":
        monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE", "0")
    else:
        from pytorch_operator_tpu import native as _native

        if not _native.native_available():
            pytest.skip("native library unavailable (no toolchain)")
        assert _native.tls_available(), (
            "libssl.so present at image-build time but the native TLS "
            "runtime failed to load")
    return request.param


def _cluster(stub, certs, **kw):
    cfg = KubeConfig("127.0.0.1", stub.port, scheme="https",
                     ca_file=kw.pop("ca_file", certs["server_crt"]), **kw)
    return RestCluster(cfg)


def pod(name, ns="default"):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"containers": [{"name": "c", "image": "i"}]}}


class TestHttpsCrud:
    def test_roundtrip(self, tls_stub, certs, transport):
        cluster = _cluster(tls_stub, certs)
        try:
            if transport == "native":
                assert cluster.client.native is not None
            else:
                assert cluster.client.native is None
            cluster.pods.create("default", pod("p1"))
            got = cluster.pods.get("default", "p1")
            assert got["metadata"]["name"] == "p1"
            cluster.pods.delete("default", "p1")
            with pytest.raises(NotFoundError):
                cluster.pods.get("default", "p1")
        finally:
            cluster.close()

    def test_watch_streams_over_tls(self, tls_stub, certs, transport):
        cluster = _cluster(tls_stub, certs)
        try:
            seen = []
            cluster.pods.add_listener(lambda et, obj: seen.append(
                (et, (obj.get("metadata") or {}).get("name"))))
            cluster.pods.create("default", pod("w1"))
            deadline = time.monotonic() + 10
            while ("ADDED", "w1") not in seen and time.monotonic() < deadline:
                time.sleep(0.05)
            assert ("ADDED", "w1") in seen
        finally:
            cluster.close()

    def test_wrong_ca_rejected(self, tls_stub, certs, transport):
        cluster = _cluster(tls_stub, certs, ca_file=certs["rogue_crt"])
        try:
            # both transports surface verification failure as OSError
            # (NativeHttpError / ssl.SSLError both subclass it)
            with pytest.raises(OSError):
                cluster.pods.get("default", "nope")
        finally:
            cluster.close()

    def test_insecure_skips_verification(self, tls_stub, certs, transport):
        cluster = _cluster(tls_stub, certs, ca_file=certs["rogue_crt"],
                           insecure=True)
        try:
            cluster.pods.create("default", pod("p2"))
            assert cluster.pods.get("default", "p2")
        finally:
            cluster.close()

    def test_bearer_token_header_sent(self, tls_stub, certs, transport):
        cluster = _cluster(tls_stub, certs, token="sekret")
        try:
            assert cluster.client._headers()["Authorization"] == \
                "Bearer sekret"
            cluster.pods.create("default", pod("p3"))
            assert cluster.pods.get("default", "p3")
        finally:
            cluster.close()


class TestMutualTls:
    def test_client_cert_accepted(self, mtls_stub, certs, transport):
        cluster = _cluster(mtls_stub, certs,
                           cert_file=certs["client_crt"],
                           key_file=certs["client_key"])
        try:
            cluster.pods.create("default", pod("m1"))
            assert cluster.pods.get("default", "m1")
        finally:
            cluster.close()

    def test_missing_client_cert_rejected(self, mtls_stub, certs, transport):
        cluster = _cluster(mtls_stub, certs)
        try:
            with pytest.raises(OSError):
                cluster.pods.get("default", "nope")
        finally:
            cluster.close()


class TestKubeConfigTls:
    def test_ssl_context_loads_material(self, certs):
        cfg = KubeConfig("127.0.0.1", 443, scheme="https",
                         ca_file=certs["server_crt"],
                         cert_file=certs["client_crt"],
                         key_file=certs["client_key"])
        ctx = cfg.ssl_context()
        assert ctx is not None
        assert ctx.verify_mode == ssl.CERT_REQUIRED
        cfg_insecure = KubeConfig("127.0.0.1", 443, scheme="https",
                                  insecure=True)
        ictx = cfg_insecure.ssl_context()
        assert ictx.verify_mode == ssl.CERT_NONE
        assert not ictx.check_hostname

    def test_kubeconfig_cert_data_materialised(self, certs, tmp_path,
                                               mtls_stub, transport):
        import yaml

        def b64(path):
            with open(path, "rb") as f:
                return base64.b64encode(f.read()).decode()

        kc = {
            "current-context": "ctx",
            "contexts": [{"name": "ctx", "context":
                          {"cluster": "c", "user": "u"}}],
            "clusters": [{"name": "c", "cluster": {
                "server": f"https://127.0.0.1:{mtls_stub.port}",
                "certificate-authority-data": b64(certs["server_crt"]),
            }}],
            "users": [{"name": "u", "user": {
                "client-certificate-data": b64(certs["client_crt"]),
                "client-key-data": b64(certs["client_key"]),
            }}],
        }
        path = tmp_path / "kubeconfig"
        path.write_text(yaml.safe_dump(kc))
        cfg = KubeConfig.from_kubeconfig(str(path))
        assert cfg.scheme == "https"
        # data keys materialise to real files with the original bytes
        with open(cfg.ca_file, "rb") as f, \
                open(certs["server_crt"], "rb") as g:
            assert f.read() == g.read()
        # and the materialised config drives a real mTLS exchange
        cluster = RestCluster(cfg)
        try:
            cluster.pods.create("default", pod("kc1"))
            assert cluster.pods.get("default", "kc1")
        finally:
            cluster.close()

    def test_in_cluster_config(self, certs, tmp_path, monkeypatch):
        sa = tmp_path / "serviceaccount"
        sa.mkdir()
        (sa / "token").write_text("sa-token\n")
        shutil.copy(certs["server_crt"], sa / "ca.crt")
        monkeypatch.setattr(rest_mod, "_SA_DIR", str(sa))
        monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
        monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "6443")
        cfg = KubeConfig.in_cluster()
        assert cfg.scheme == "https"
        assert cfg.token == "sa-token"
        assert cfg.host == "10.0.0.1" and cfg.port == 6443
        assert cfg.ssl_context() is not None
