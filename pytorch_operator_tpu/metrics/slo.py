"""SLO layer: declared objectives evaluated as burn rates over the
metrics the operator already exports.

The observability stack measures everything and judges nothing: the
fairness/capacity work (admission wait histograms), the handoff work
(stage-resolved acquisition timings) and the push gateway (reject
counters) all emit series, but "is the fleet meeting its objectives
RIGHT NOW" still requires a human with a PromQL prompt.  This module
closes that loop in-process:

  * an :class:`SloObjective` declares a target over an existing family
    — "99% of shard handoffs reach first reconcile within 5s", "99.9%
    of reconciles finish within 1s" — either as a histogram threshold
    or a counter good/bad ratio;
  * :class:`SloEvaluator` re-reads the registry's own text exposition
    (one parse per evaluation, no second bookkeeping path that could
    drift from what operators actually scrape) and reports each
    objective's **burn rate**: the fraction of events out of objective
    divided by the error budget (``1 - target``).  Burn 1.0 means the
    budget is being consumed exactly as provisioned; above it the
    objective is being missed;
  * verdicts surface twice — as ``pytorch_operator_slo_burn_rate`` /
    ``pytorch_operator_slo_ok`` gauge series on ``/metrics``, and as a
    JSON verdict document on ``/debug/slo``.

Deadlock note: every metric lock in :mod:`metrics.prometheus` is
non-reentrant, so the SLO gauges are plain ``set()`` values refreshed
by :meth:`SloEvaluator.evaluate` — NEVER ``set_function`` callbacks
(a scrape-time callback re-entering ``registry.expose`` would deadlock
on the histogram locks it is being rendered under).  The metrics
server calls ``evaluate()`` immediately before ``expose()`` instead.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

from ..runtime.fleetview import parse_histograms

_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{[^}]*\})?\s+(\S+)')


def counter_total(text: str, name: str) -> float:
    """Sum every sample of counter ``name`` (all label sets) in a
    text-0.0.4 exposition."""
    total = 0.0
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None or m.group(1) != name:
            continue
        try:
            total += float(m.group(2))
        except ValueError:
            continue
    return total


class SloObjective:
    """One declared objective.

    ``kind`` selects the evaluation:

    * ``"histogram"`` — ``target`` of ``family`` observations must fall
      at or under ``threshold`` seconds.  ``threshold`` must sit on a
      declared bucket bound (cumulative buckets cannot be interpolated
      honestly; the constructor does not check, the evaluation simply
      uses the smallest bucket >= threshold).  ``match_labels``
      restricts to series carrying those label values; ``per_label``
      names a label to slice by, with the verdict reporting the WORST
      slice (the per-tenant admission objective uses this — a fleet
      aggregate would let one starved tenant hide inside nine happy
      ones).
    * ``"ratio"`` — bad events ``bad_counter`` over total events
      ``total_counter``; the bad fraction must stay under
      ``1 - target``.
    """

    def __init__(self, name: str, description: str, *, kind: str,
                 target: float, family: str = "",
                 threshold: float = 0.0,
                 match_labels: Optional[Dict[str, str]] = None,
                 per_label: str = "",
                 bad_counter: str = "", total_counter: str = ""):
        if kind not in ("histogram", "ratio"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if not 0.0 < target < 1.0:
            raise ValueError("target must be in (0, 1) — the error "
                             "budget is 1 - target")
        self.name = name
        self.description = description
        self.kind = kind
        self.target = float(target)
        self.family = family
        self.threshold = float(threshold)
        self.match_labels = dict(match_labels or {})
        self.per_label = per_label
        self.bad_counter = bad_counter
        self.total_counter = total_counter

    # -- evaluation helpers ------------------------------------------------

    def _series_counts(self, series: dict) -> tuple:
        """(good, total) for one parsed histogram series: good = the
        cumulative count at the smallest bucket bound >= threshold."""
        total = float(series.get("count") or 0.0)
        good = 0.0
        best = None
        for le, cumulative in series.get("buckets") or []:
            try:
                bound = float(le)
            except ValueError:  # +Inf
                bound = float("inf")
            if bound >= self.threshold and (best is None or bound < best):
                best = bound
                good = float(cumulative)
        return min(good, total), total

    def counts(self, text: str) -> dict:
        """{"bad", "total", optional "worst"} for this objective over
        one exposition text."""
        if self.kind == "ratio":
            total = counter_total(text, self.total_counter)
            bad = min(counter_total(text, self.bad_counter), total)
            return {"bad": bad, "total": total}
        series_map = parse_histograms(text, (self.family,))[self.family]
        slices: Dict[str, List[float]] = {}
        for series in series_map.values():
            labels = series.get("labels") or {}
            if any(labels.get(k) != v
                   for k, v in self.match_labels.items()):
                continue
            good, total = self._series_counts(series)
            key = (labels.get(self.per_label, "")
                   if self.per_label else "")
            agg = slices.setdefault(key, [0.0, 0.0])
            agg[0] += total - good
            agg[1] += total
        if not slices:
            return {"bad": 0.0, "total": 0.0}
        if not self.per_label:
            bad, total = slices[""]
            return {"bad": bad, "total": total}
        # worst slice governs: rank by bad fraction, break ties by
        # volume then name so the verdict is deterministic
        worst = max(sorted(slices),
                    key=lambda k: ((slices[k][0] / slices[k][1])
                                   if slices[k][1] else 0.0,
                                   slices[k][1]))
        bad, total = slices[worst]
        return {"bad": bad, "total": total, "worst": worst}


def default_objectives() -> List[SloObjective]:
    """The operator's declared objectives.  Thresholds sit on declared
    bucket bounds of their families (see each family's constructor)."""
    return [
        SloObjective(
            "handoff_first_reconcile",
            "99% of shard acquisitions reach their first completed "
            "reconcile within 5s of the Lease CAS",
            kind="histogram", target=0.99,
            family="pytorch_operator_shard_handoff_stage_seconds",
            match_labels={"stage": "acquire_to_first_reconcile"},
            threshold=5.0),
        SloObjective(
            "admission_wait_per_tenant",
            "99% of each tenant's admissions wait under 300s in the "
            "fair-share queue (worst tenant governs)",
            kind="histogram", target=0.99,
            family="pytorch_operator_admission_wait_seconds",
            per_label="namespace", threshold=300.0),
        SloObjective(
            "reconcile_duration",
            "99.9% of sync_job passes finish within 1s",
            kind="histogram", target=0.999,
            family="pytorch_operator_reconcile_duration_seconds",
            threshold=1.0),
        SloObjective(
            "push_reject_rate",
            "99% of telemetry push samples are accepted by the "
            "gateway (rejects burn the budget)",
            kind="ratio", target=0.99,
            bad_counter="pytorch_operator_push_rejected_total",
            total_counter="pytorch_operator_push_samples_total"),
        SloObjective(
            "event_propagation",
            "99% of job watch events reach reconcile start within 1s "
            "of the apiserver send (the propagation ledger's "
            "watch_to_reconcile_start stage)",
            kind="histogram", target=0.99,
            family="pytorch_operator_event_propagation_seconds",
            match_labels={"stage": "watch_to_reconcile_start"},
            threshold=1.0),
    ]


class SloEvaluator:
    """Evaluates declared objectives against ``registry`` and publishes
    the verdicts.

    ``evaluate()`` is cheap (one exposition render + text parse) and
    re-entrancy-safe to call from any request thread; the metrics
    server invokes it on every ``/metrics`` and ``/debug/slo`` hit so
    the gauge series are at most one scrape stale.
    """

    def __init__(self, registry, objectives=None):
        self.registry = registry
        self.objectives = (list(objectives) if objectives is not None
                           else default_objectives())
        self._burn_gauge = registry.gauge_vec(
            "pytorch_operator_slo_burn_rate",
            "Lifetime error-budget burn rate per declared objective "
            "(bad fraction / error budget; 1.0 consumes the budget "
            "exactly, above it the objective is missed)",
            ("objective",))
        self._ok_gauge = registry.gauge_vec(
            "pytorch_operator_slo_ok",
            "1 while the objective's burn rate is within budget "
            "(<= 1.0), 0 while it is being missed",
            ("objective",))
        # objective -> (bad, total) at the previous evaluation: the
        # window burn rate judges only what happened since, so a
        # long-healed incident stops dominating the verdict
        self._last: Dict[str, tuple] = {}

    def evaluate(self) -> dict:
        """Re-read the registry and refresh gauges; returns the
        ``/debug/slo`` verdict document."""
        # NOTE: expose() is called here, OUTSIDE any metric lock; the
        # resulting set() calls below take each gauge's lock briefly
        text = self.registry.expose()
        verdicts = []
        for objective in self.objectives:
            counts = objective.counts(text)
            bad, total = counts["bad"], counts["total"]
            budget = 1.0 - objective.target
            bad_fraction = (bad / total) if total else 0.0
            burn = bad_fraction / budget
            prev_bad, prev_total = self._last.get(
                objective.name, (0.0, 0.0))
            dbad = max(0.0, bad - prev_bad)
            dtotal = max(0.0, total - prev_total)
            window_burn = ((dbad / dtotal) / budget) if dtotal else 0.0
            self._last[objective.name] = (bad, total)
            ok = burn <= 1.0
            self._burn_gauge.labels(objective=objective.name).set(burn)
            self._ok_gauge.labels(objective=objective.name).set(
                1 if ok else 0)
            verdict = {
                "objective": objective.name,
                "description": objective.description,
                "target": objective.target,
                "bad": bad,
                "total": total,
                "bad_fraction": round(bad_fraction, 9),
                "burn_rate": round(burn, 6),
                "window_burn_rate": round(window_burn, 6),
                "ok": ok,
            }
            if objective.kind == "histogram":
                verdict["threshold_s"] = objective.threshold
            if "worst" in counts:
                verdict["worst_" + objective.per_label] = counts["worst"]
            verdicts.append(verdict)
        return {
            "objectives": verdicts,
            "ok": all(v["ok"] for v in verdicts),
        }


__all__ = ["SloEvaluator", "SloObjective", "counter_total",
           "default_objectives"]
