"""CRD schema <-> dataclass drift gate (verify-codegen.sh equivalent).

The reference gates CI on hack/verify-codegen.sh (.travis.yml:13-25) so
its CRD machinery can't drift from the Go types.  Here the two
hand-maintained sides are manifests/crd.yaml's openAPIV3Schema and
api/v1/types.py; api/v1/schema.py generates the schema from the
dataclasses and these tests assert the YAML agrees.  Mutating either
side alone fails the suite:
  * add/rename a PyTorchJobSpec field  -> missing-property assertion
  * add/retype a crd.yaml property     -> assert_subschema failure
"""

import pathlib

import pytest
import yaml

from pytorch_operator_tpu.api.v1 import constants, schema, types

CRD_PATH = pathlib.Path(__file__).resolve().parent.parent / "manifests" / "crd.yaml"


@pytest.fixture(scope="module")
def crd_spec_schema():
    crd = yaml.safe_load(CRD_PATH.read_text())
    versions = crd["spec"]["versions"]
    assert len(versions) == 1 and versions[0]["name"] == "v1"
    root = versions[0]["schema"]["openAPIV3Schema"]
    return root["properties"]["spec"]


class TestSchemaDrift:
    def test_declared_spec_agrees_with_dataclasses(self, crd_spec_schema):
        generated = schema.generate(types.PyTorchJobSpec)
        schema.assert_subschema(crd_spec_schema, generated)

    def test_every_spec_field_is_declared(self, crd_spec_schema):
        # superset direction: a new dataclass field must be added to the
        # CRD validation schema too (or consciously listed here)
        generated = schema.generate(types.PyTorchJobSpec)
        declared = set(crd_spec_schema["properties"])
        # schedulingPolicy is applied by the controller (PodGroup
        # minMember), not validated at admission — the reference's
        # v1beta1 CRD leaves it unvalidated the same way.
        undeclared_ok = {"schedulingPolicy"}
        missing = set(generated["properties"]) - declared - undeclared_ok
        assert not missing, (
            f"PyTorchJobSpec fields missing from manifests/crd.yaml "
            f"openAPIV3Schema: {sorted(missing)}")

    def test_replica_spec_keys_match_value_type(self, crd_spec_schema):
        # Master/Worker subtrees in the CRD must describe ReplicaSpec's
        # wire format (the generated map's additionalProperties schema)
        generated = schema.generate(types.PyTorchJobSpec)
        value_schema = (
            generated["properties"]["pytorchReplicaSpecs"]
            ["additionalProperties"])
        declared = crd_spec_schema["properties"]["pytorchReplicaSpecs"]
        keys = set(declared["properties"])
        assert keys == {constants.REPLICA_TYPE_MASTER,
                        constants.REPLICA_TYPE_WORKER}
        for key, sub in declared["properties"].items():
            schema.assert_subschema(sub, value_schema, path=key)

    def test_schema_encodes_validation_contract(self, crd_spec_schema):
        # exactly-one-Master (validation.py mirror of validation.go:23-77)
        master = (crd_spec_schema["properties"]["pytorchReplicaSpecs"]
                  ["properties"][constants.REPLICA_TYPE_MASTER]
                  ["properties"]["replicas"])
        assert master.get("minimum") == 1 and master.get("maximum") == 1
        # CleanPodPolicy enum must match the constants the controller
        # accepts (api/v1/constants.py:41-44)
        enum = set(crd_spec_schema["properties"]["cleanPodPolicy"]["enum"])
        assert enum == {constants.CLEAN_POD_POLICY_ALL,
                        constants.CLEAN_POD_POLICY_RUNNING,
                        constants.CLEAN_POD_POLICY_NONE}

    def test_mutating_generated_side_fails(self):
        # the gate actually bites: a retyped field trips assert_subschema
        generated = schema.generate(types.PyTorchJobSpec)
        broken = {"type": "object",
                  "properties": {"backoffLimit": {"type": "string"}}}
        with pytest.raises(AssertionError):
            schema.assert_subschema(broken, generated)
        unknown = {"type": "object",
                   "properties": {"notAField": {"type": "integer"}}}
        with pytest.raises(AssertionError):
            schema.assert_subschema(unknown, generated)
