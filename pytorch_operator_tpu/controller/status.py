"""Job status condition machine.

Behavioral mirror of the reference's
pkg/controller.v1/pytorch/status.go:155-273: condition de-duplication,
Running<->Restarting mutual exclusion, Running set False on terminal
states, and the completed-status freeze (no transitions out of
Succeeded/Failed).
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..api.v1 import constants
from ..api.v1.types import JobCondition, JobStatus, ReplicaStatus

CONDITION_TRUE = "True"
CONDITION_FALSE = "False"

# Condition reasons (status.go:30-46).
JOB_CREATED_REASON = "PyTorchJobCreated"
JOB_SUCCEEDED_REASON = "PyTorchJobSucceeded"
JOB_RUNNING_REASON = "PyTorchJobRunning"
JOB_FAILED_REASON = "PyTorchJobFailed"
JOB_RESTARTING_REASON = "PyTorchJobRestarting"


def now_iso(now: Optional[float] = None) -> str:
    """RFC3339 condition timestamp; ``now`` (epoch seconds, e.g. a
    VirtualClock's ``now``) overrides the real wall clock."""
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now))


def new_condition(cond_type: str, reason: str, message: str) -> JobCondition:
    return JobCondition(
        type=cond_type,
        status=CONDITION_TRUE,
        reason=reason,
        message=message,
        last_update_time=now_iso(),
        last_transition_time=now_iso(),
    )


def get_condition(status: JobStatus, cond_type: str) -> Optional[JobCondition]:
    for condition in status.conditions:
        if condition.type == cond_type:
            return condition
    return None


def has_condition(status: JobStatus, cond_type: str) -> bool:
    return any(
        c.type == cond_type and c.status == CONDITION_TRUE for c in status.conditions
    )


def is_succeeded(status: JobStatus) -> bool:
    return has_condition(status, constants.JOB_SUCCEEDED)


def is_failed(status: JobStatus) -> bool:
    return has_condition(status, constants.JOB_FAILED)


def set_condition(status: JobStatus, condition: JobCondition) -> None:
    """status.go:226-248."""
    if is_failed(status) or is_succeeded(status):
        return
    current = get_condition(status, condition.type)
    if (
        current is not None
        and current.status == condition.status
        and current.reason == condition.reason
    ):
        return
    if current is not None and current.status == condition.status:
        condition.last_transition_time = current.last_transition_time
    status.conditions = _filter_out_condition(status.conditions, condition.type) + [
        condition
    ]


def _filter_out_condition(
    conditions: List[JobCondition], cond_type: str
) -> List[JobCondition]:
    """status.go:250-272: drops the same-type condition, enforces
    Running<->Restarting exclusivity, and falsifies Running on terminal."""
    out: List[JobCondition] = []
    for c in conditions:
        if cond_type == constants.JOB_RESTARTING and c.type == constants.JOB_RUNNING:
            continue
        if cond_type == constants.JOB_RUNNING and c.type == constants.JOB_RESTARTING:
            continue
        if c.type == cond_type:
            continue
        if (
            cond_type in (constants.JOB_FAILED, constants.JOB_SUCCEEDED)
            and c.type == constants.JOB_RUNNING
        ):
            c.status = CONDITION_FALSE
        out.append(c)
    return out


def update_job_conditions(
    status: JobStatus, cond_type: str, reason: str, message: str
) -> None:
    set_condition(status, new_condition(cond_type, reason, message))


def clear_condition(
    status: JobStatus, cond_type: str, reason: str, message: str
) -> None:
    """Set ``cond_type`` to status False (e.g. Resizing once actual
    replicas match desired again).  Rides set_condition so the
    (status, reason) dedup and the terminal-status freeze apply."""
    cond = new_condition(cond_type, reason, message)
    cond.status = CONDITION_FALSE
    set_condition(status, cond)


def initialize_replica_statuses(status: JobStatus, rtype: str) -> None:
    status.replica_statuses[rtype] = ReplicaStatus()


def update_replica_statuses(status: JobStatus, rtype: str, pod: dict) -> None:
    """Tally one pod's phase into the replica status (status.go:172-182)."""
    phase = (pod.get("status") or {}).get("phase")
    rs = status.replica_statuses.setdefault(rtype, ReplicaStatus())
    if phase == "Running":
        rs.active += 1
    elif phase == "Succeeded":
        rs.succeeded += 1
    elif phase == "Failed":
        rs.failed += 1


def apply_replica_counts(status: JobStatus, rtype: str, active: int,
                         succeeded: int, failed: int) -> None:
    """Aggregate form of update_replica_statuses for the reconcile plan
    kernel, which tallies single-occupant slices in one pass."""
    rs = status.replica_statuses.setdefault(rtype, ReplicaStatus())
    rs.active += active
    rs.succeeded += succeeded
    rs.failed += failed


def status_merge_diff(old: Optional[dict], new: Optional[dict]) -> dict:
    """JSON-merge-patch (RFC 7386) delta turning wire-format ``old`` into
    ``new``: changed/added keys carry the new value (dicts recurse, lists
    replace wholesale), keys absent from ``new`` become explicit nulls.
    The null-deletes reproduce exactly what the previous full-object
    status PUT did — unknown server-side fields were already dropped by
    the typed round-trip — while a reconcile that only flips one
    replica's count now ships a few bytes instead of the whole object.
    An empty dict means "nothing changed": skip the write entirely.
    """
    old = old or {}
    new = new or {}
    patch: dict = {}
    for key, value in new.items():
        if key not in old:
            patch[key] = value
        elif isinstance(value, dict) and isinstance(old[key], dict):
            sub = status_merge_diff(old[key], value)
            if sub:
                patch[key] = sub
        elif value != old[key]:
            patch[key] = value
    for key in old:
        if key not in new:
            patch[key] = None
    return patch
