"""Table-printing watch over a PyTorchJob until it terminates.

Reference: sdk/python/kubeflow/pytorchjob/api/py_torch_job_watch.py:29-60
(tabulated NAME/STATE/TIME stream that stops on Succeeded/Failed).

Event-driven, matching the reference's server-side stream: the SDK
subscribes to the backend job store's watch interface (``job_store()``
on the backend adapter) — for RestCluster that is the real chunked-HTTP
watch stream (k8s/rest.py add_listener, the same machinery the
informers consume, native C++ ws_next or the Python fallback), for
FakeCluster the in-memory listener bus, and for the
`kubernetes`-package backend a kubernetes.watch.Watch stream adapter
(sdk/client.py _KubeJobWatch).  A GAP event (stream error + relist
semantics) re-reads the job so no terminal transition can be missed —
including a deletion that happened during the outage, which reports as
Deleted.  Polling survives only as a last-resort fallback for backends
that expose no watch interface at all.
"""

from __future__ import annotations

import queue
import time

from pytorch_operator_tpu.k8s.errors import NotFoundError

_FMT = "{:<30.30} {:<20.20} {:<30.30}"
_TERMINAL = ("Succeeded", "Failed")


def _emit_row(name: str, job: dict, last):
    """Print the newest condition row if it changed.

    Returns (new_last, terminal): the dedup state to carry and whether
    the newest condition is terminal.  Shared by the event-driven path
    and the poll fallback so the table format, dedup rule and terminal
    set cannot diverge between the two modes.

    Stale-replay guard: an event enqueued between add_listener and the
    initial get carries state OLDER than the get's snapshot; printing
    it would emit an out-of-order row and reset the dedup state (a
    duplicate row when the newer state is re-delivered).  Transition
    times are RFC3339 UTC, so lexical comparison orders them — a row
    whose time is older than the one already printed is skipped and the
    newer dedup state kept.  Terminal detection is unaffected: terminal
    conditions are final, so even a stale terminal row means done.
    """
    conditions = ((job.get("status") or {}).get("conditions")) or []
    if not conditions:
        return last, False
    cond = conditions[-1]
    row = (cond.get("type", ""), cond.get("lastTransitionTime", ""))
    if row != last and (last is None or row[1] >= last[1]):
        print(_FMT.format(name, row[0], row[1]), flush=True)
        last = row
    return last, row[0] in _TERMINAL


def watch(client, name: str, namespace: str, timeout_seconds: int = 600,
          polling_interval: float = 2.0) -> None:
    job_store = getattr(client._backend, "job_store", lambda: None)
    store = job_store()
    if store is None:  # no stream interface on this backend
        return _poll_watch(client, name, namespace, timeout_seconds,
                           polling_interval)

    print(_FMT.format("NAME", "STATE", "TIME"), flush=True)
    events: queue.Queue = queue.Queue()

    def on_event(etype: str, obj: dict) -> None:
        if etype == "GAP":
            events.put(("GAP", None))
            return
        meta = obj.get("metadata") or {}
        if meta.get("name") == name and \
                (meta.get("namespace") or "default") == namespace:
            events.put((etype, obj))

    def deleted() -> None:
        print(_FMT.format(name, "Deleted", ""), flush=True)

    last = None
    seen = False  # has the job ever been observed (get or event)?
    store.add_listener(on_event)
    try:
        deadline = time.monotonic() + timeout_seconds
        # initial state: the listener only sees events from now on
        try:
            job = client.get(name, namespace)
            seen = True
            last, terminal = _emit_row(name, job, last)
            if terminal:
                return
        except NotFoundError:
            pass  # watch opened before create — events will arrive
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                etype, obj = events.get(timeout=min(1.0, remaining))
            except queue.Empty:
                continue
            if etype == "GAP":
                # stream (re)established or errored: events may have
                # been missed — re-read.  A job that was seen before
                # and is gone now lost its DELETED in the gap; one
                # never seen simply hasn't been created yet.
                try:
                    obj = client.get(name, namespace)
                except NotFoundError:
                    if seen:
                        deleted()
                        return
                    continue
            elif etype == "DELETED":
                deleted()
                return
            seen = True
            last, terminal = _emit_row(name, obj, last)
            if terminal:
                return
        raise RuntimeError(
            f"timeout watching PyTorchJob {namespace}/{name}")
    finally:
        store.remove_listener(on_event)


def _poll_watch(client, name: str, namespace: str, timeout_seconds: int,
                polling_interval: float) -> None:
    """GET-poll fallback for backends without a stream interface."""
    print(_FMT.format("NAME", "STATE", "TIME"), flush=True)
    deadline = time.monotonic() + timeout_seconds
    last = None
    while time.monotonic() < deadline:
        last, terminal = _emit_row(name, client.get(name, namespace), last)
        if terminal:
            return
        time.sleep(polling_interval)
    raise RuntimeError(
        f"timeout watching PyTorchJob {namespace}/{name}")
