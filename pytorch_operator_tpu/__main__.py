"""`python -m pytorch_operator_tpu` runs the operator process."""

import sys

from pytorch_operator_tpu.cmd.operator import main

sys.exit(main())
