"""Fused causal flash attention (Pallas TPU kernel).

Forward pass streams K/V blocks through VMEM with an online softmax
(running max + running denominator), so the (T, T) score matrix never
materialises in HBM — the standard flash recipe mapped onto the MXU
with (block_q x d) @ (d x block_k) tiles.  The backward pass is a
rematerialising custom VJP: recompute attention probabilities blockwise
in plain XLA ops (which fuse well) rather than storing them.

Falls back to a dense jnp implementation for shapes that don't tile
(seq not a multiple of the block size) or when Pallas is unavailable;
``interpret=True`` runs the same kernel on CPU test meshes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _dense_reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q, block_k, scale, causal):
    import jax.experimental.pallas as pl

    i = pl.program_id(1)
    q = q_ref[0]                                      # (block_q, d), native dtype
    d = q.shape[-1]
    seq_k = k_ref.shape[1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # blocks strictly above the diagonal contribute nothing
        num_kb = lax.div(i * block_q + block_q + block_k - 1, block_k)
    else:
        num_kb = seq_k // block_k

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        # bf16 x bf16 on the MXU, f32 accumulation
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (block_q, block_k)
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            kpos = j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(qpos >= kpos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    m, l, acc = lax.fori_loop(0, num_kb, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    BH, T, D = q.shape
    grid = (BH, T // block_q)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, T, D), lambda b, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, g):
    # rematerialised dense backward; XLA fuses the softmax chain
    q, k, v = res

    def f(q, k, v):
        return _dense_reference(q, k, v, scale, causal)

    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal attention over (B, T, H, D) inputs (same-H q/k/v; repeat KV
    for GQA before calling).  Dispatches to the Pallas kernel when the
    sequence tiles evenly, dense XLA otherwise."""
    B, T, H, D = q.shape
    scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    def from_bh(x):
        return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    if T % block_q or T % block_k:
        return from_bh(_dense_reference(to_bh(q), to_bh(k), to_bh(v),
                                        scale, causal))
    out = _flash(to_bh(q), to_bh(k), to_bh(v), scale, causal,
                 block_q, block_k, interpret)
    return from_bh(out)
