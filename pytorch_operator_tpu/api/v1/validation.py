"""Validation for PyTorchJob specs.

Behavioral mirror of the reference's
pkg/apis/pytorch/validation/validation.go:23-77:
  * the replica-spec map must be present and non-empty entries valid;
  * only ``Master`` / ``Worker`` replica types are accepted;
  * every replica spec needs at least one container, every container an
    image, and one container must be named ``pytorch``;
  * a Master spec must exist with exactly one replica.
"""

from __future__ import annotations

from . import constants
from .types import PyTorchJobSpec


class ValidationError(ValueError):
    """Raised when a PyTorchJobSpec is invalid."""


def validate_spec(spec: PyTorchJobSpec) -> None:
    if not spec.pytorch_replica_specs or not isinstance(spec.pytorch_replica_specs, dict):
        raise ValidationError("PyTorchJobSpec is not valid")

    master_exists = False
    for rtype, replica in spec.pytorch_replica_specs.items():
        if replica is None or not replica.template.spec.containers:
            raise ValidationError(
                f"PyTorchJobSpec is not valid: containers definition expected in {rtype}"
            )
        if rtype not in constants.VALID_REPLICA_TYPES:
            raise ValidationError(
                f"PyTorchReplicaType is {rtype} but must be one of "
                f"{list(constants.VALID_REPLICA_TYPES)}"
            )
        default_container_present = False
        for container in replica.template.spec.containers:
            if not container.image:
                raise ValidationError(
                    f"PyTorchJobSpec is not valid: Image is undefined in the container of {rtype}"
                )
            if container.name == constants.DEFAULT_CONTAINER_NAME:
                default_container_present = True
        if not default_container_present:
            raise ValidationError(
                "PyTorchJobSpec is not valid: There is no container named "
                f"{constants.DEFAULT_CONTAINER_NAME} in {rtype}"
            )
        if rtype == constants.REPLICA_TYPE_MASTER:
            master_exists = True
            if replica.replicas is not None and replica.replicas != 1:
                raise ValidationError(
                    "PyTorchJobSpec is not valid: There must be only 1 master replica"
                )

    if not master_exists:
        raise ValidationError(
            "PyTorchJobSpec is not valid: Master ReplicaSpec must be present"
        )
