"""Ring attention: causal self-attention over a sequence-sharded mesh axis.

Long-context support the reference lacks entirely (SURVEY.md §2.4: "TP /
PP / SP / EP / CP / ring-attention — ABSENT").  Each device holds a
contiguous chunk of the sequence; K/V chunks rotate around the ring via
`lax.ppermute` while a flash-style online softmax (running max + running
denominator) accumulates exact attention output.  Communication is
neighbour-to-neighbour, so on TPU it rides ICI links and overlaps with
the per-chunk matmuls.

Layout: q/k/v are the *local* (B, T_local, H, Dh) chunks inside a
`jax.shard_map` over ``axis_name``; global position of local row i on
ring rank r is r*T_local + i.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorch_operator_tpu.utils.jax_compat import pvary, shard_map

NEG_INF = -1e30


def _chunk_attn(q, k, v, q_off, k_off, scale, causal):
    """Scores + masked row-stats for one (q-chunk, kv-chunk) pair.

    Returns (o_part, row_max, row_sum) with shapes
    (B,H,Tq,Dh), (B,H,Tq), (B,H,Tq) — all f32.  Grouped (GQA) K/V is
    repeated locally here — the repeat never rides the ring.
    """
    if k.shape[2] != q.shape[2]:
        group = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, group, axis=2)
        v = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bthd,bshd->bhts", q, k).astype(jnp.float32) * scale
    if causal:
        Tq, Tk = q.shape[1], k.shape[1]
        qpos = q_off + jnp.arange(Tq)
        kpos = k_off + jnp.arange(Tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # (B,H,Tq)
    p = jnp.exp(s - m[..., None])
    # rows that are fully masked: zero them out rather than exp(-inf - -inf)
    p = jnp.where(m[..., None] <= NEG_INF / 2, 0.0, p)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhts,bshd->bhtd", p, v.astype(jnp.float32))
    return o, m, l


def _chunk_attn_flash(q, k, v, scale, causal, block, interpret):
    """One (q-chunk, kv-chunk) pair through the Pallas flash kernel.

    Returns the same (o_part, row_max, row_sum) contract as _chunk_attn
    by mapping the kernel's normalized (out, lse) to the accumulator
    basis m := lse, l := 1 (then o_unnormalized(m) == out exactly) — so
    flash- and dense-computed chunks combine interchangeably.  Uses the
    differentiable flash_with_lse pair, so jax.grad flows through the
    ring merge (both out and lse carry cotangents).
    """
    from pytorch_operator_tpu.ops.flash_attention import flash_with_lse

    B, Tq, H, Dh = q.shape

    def bh(x):  # each tensor's own head count (k/v may be grouped)
        return x.transpose(0, 2, 1, 3).reshape(B * x.shape[2], -1, Dh)

    out, lse = flash_with_lse(bh(q), bh(k), bh(v), scale, causal,
                              block, block, interpret)
    o = out.reshape(B, H, Tq, Dh).astype(jnp.float32)
    m = lse.reshape(B, H, Tq)
    return o, m, jnp.ones_like(m)


def _ring_body(q, k, v, axis_name, causal, scale, block, interpret):
    """Runs on each device inside shard_map.

    Causal chunk scheduling: a kv chunk entirely *after* the local q
    chunk is fully masked — its compute is skipped outright via
    lax.cond (the naive ring does the matmuls and masks everything,
    wasting ~half the FLOPs).  The diagonal chunk runs causal, earlier
    chunks run unmasked; both go through the Pallas flash kernel when
    the local chunk tiles (``block``), dense XLA otherwise.
    """
    B, Tl, H, Dh = q.shape
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        o, m, l, kc, vc = carry
        src = (rank - s) % n  # which global chunk kc currently holds

        def merge(parts):
            return _acc_merge((o, m, l), parts)

        def chunk(causal_chunk):
            if block is not None:
                return _chunk_attn_flash(q, kc, vc, scale, causal_chunk,
                                         block, interpret)
            # offsets only matter for the diagonal (causal) chunk, where
            # q and kv offsets are equal — 0/0 yields the same mask
            return _chunk_attn(q, kc, vc, 0, 0, scale, causal_chunk)

        if causal:
            o2, m2, l2 = lax.cond(
                src > rank,
                lambda _: (o, m, l),  # fully masked: skip the compute
                lambda _: lax.cond(
                    src == rank,
                    lambda _: merge(chunk(True)),    # diagonal: causal
                    lambda _: merge(chunk(False)),   # earlier: unmasked
                    None),
                None)
        else:
            o2, m2, l2 = merge(chunk(False))

        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o2, m2, l2, kc, vc

    o, m, l, _, _ = lax.fori_loop(
        0, n, body, (*_acc_zero(B, H, Tl, Dh, axis_name), k, v))
    out = _acc_finish((o, m, l))  # (B,Tl,H,Dh)
    return out.astype(q.dtype)


def _acc_merge(acc, parts):
    """Online-softmax combine of one chunk's (o, m, l) partials into the
    running accumulator — the single numerically delicate merge shared
    by the contiguous and zigzag ring bodies."""
    o, m, l = acc
    o_p, m_p, l_p = parts
    m_new = jnp.maximum(m, m_p)
    a = jnp.exp(m - m_new)
    b = jnp.exp(m_p - m_new)
    return (o * a[..., None] + o_p * b[..., None], m_new, l * a + l_p * b)


def _acc_zero(B, H, T, Dh, axis_name):
    """Fresh (o, m, l) accumulator; pvary marks the constants
    device-varying so shard_map fori_loop carry types match."""
    o = pvary(jnp.zeros((B, H, T, Dh), jnp.float32), axis_name)
    m = pvary(jnp.full((B, H, T), NEG_INF, jnp.float32), axis_name)
    l = pvary(jnp.zeros((B, H, T), jnp.float32), axis_name)
    return o, m, l


def _acc_finish(acc):
    """Normalize and return (B, T, H, Dh); fully-masked rows (l == 0)
    divide by 1 and stay zero."""
    o, m, l = acc
    l = jnp.where(l == 0.0, 1.0, l)
    return (o / l[..., None]).transpose(0, 2, 1, 3)


def _ring_body_zigzag(q, k, v, axis_name, scale, block, interpret):
    """Causal ring body for the ZIGZAG layout: device i holds global
    chunks (i, 2S-1-i) of 2S, so per-rotation causal work is balanced
    instead of rank r doing r+1 chunks while rank 0 idles — the
    standard fix for the contiguous causal ring's tail-heavy load.

    Local arrays are (B, 2C, H, Dh); the two halves' global chunk ids
    are (rank, 2S-1-rank) for q and (src, 2S-1-src) for the rotating
    K/V.  Of the four (q-half, kv-half) pairs, two are statically
    decided — the front q half (id < S) never attends the back kv half
    (id >= S), and the back q half always fully attends the front kv
    half — leaving exactly two data-dependent diagonals, resolved with
    the same flash-or-dense chunk kernels and online-softmax merge as
    the contiguous body (_acc_merge/_acc_zero/_acc_finish).
    """
    B, Tl, H, Dh = q.shape
    C = Tl // 2
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    halves = lambda x: (x[:, :C], x[:, C:])  # noqa: E731
    q_front, q_back = halves(q)

    def chunk(qh, kh, vh, causal_chunk):
        if block is not None:
            return _chunk_attn_flash(qh, kh, vh, scale, causal_chunk,
                                     block, interpret)
        return _chunk_attn(qh, kh, vh, 0, 0, scale, causal_chunk)

    def diagonal(acc, qh, kh, vh, kv_id, q_id):
        # NOTE: both ids are traced (rank/src-derived) — only WHICH half
        # (front/back) is static — so the three-way decision is conds
        return lax.cond(
            kv_id < q_id,
            lambda a: _acc_merge(a, chunk(qh, kh, vh, False)),
            lambda a: lax.cond(
                kv_id == q_id,
                lambda b: _acc_merge(b, chunk(qh, kh, vh, True)),
                lambda b: b,  # future chunk: fully masked, skip
                a),
            acc)

    def body(s, carry):
        acc_f, acc_b, kc, vc = carry
        src = (rank - s) % n
        (k_f, k_b), (v_f, v_b) = halves(kc), halves(vc)
        # front q (id rank < S) vs front kv (id src): data-dependent
        acc_f = diagonal(acc_f, q_front, k_f, v_f, src, rank)
        # front q vs back kv (id >= S): ALWAYS future — statically skipped
        # back q (id 2S-1-rank >= S) vs front kv (id src < S): ALWAYS past
        acc_b = _acc_merge(acc_b, chunk(q_back, k_f, v_f, False))
        # back q vs back kv: kv_id < q_id iff src > rank — data-dependent
        acc_b = diagonal(acc_b, q_back, k_b, v_b,
                         2 * n - 1 - src, 2 * n - 1 - rank)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return acc_f, acc_b, kc, vc

    acc_f, acc_b, _, _ = lax.fori_loop(
        0, n, body,
        (_acc_zero(B, H, C, Dh, axis_name),
         _acc_zero(B, H, C, Dh, axis_name), k, v))
    out = jnp.concatenate([_acc_finish(acc_f), _acc_finish(acc_b)], axis=1)
    return out.astype(q.dtype)


def zigzag_layout(T: int, sp: int, axis_name: str = "sp"):
    """Validated global row permutation for the zigzag layout.

    Device i's slice holds chunks (i, 2*sp-1-i) of 2*sp, so sharding
    the PERMUTED array over sp lands each pair on its device.  Returns
    (perm, inverse); the single owner of the layout contract — both
    ring_attention's internal-permute path and llama.forward_sp's
    once-per-forward zigzag-space pipeline call this.
    """
    import numpy as np

    if T % (2 * sp):
        raise ValueError(
            f"seq len {T} not divisible by 2*{axis_name}={2 * sp} "
            f"(zigzag splits each device's slice into front/back "
            f"half-chunks)")
    C = T // (2 * sp)
    order = []
    for i in range(sp):
        order += [i, 2 * sp - 1 - i]
    perm = np.concatenate([np.arange(c * C, (c + 1) * C) for c in order])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(T)
    return perm, inv


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    batch_axes: tuple[str, ...] = (),
    head_axes: tuple[str, ...] = (),
    layout: str = "contiguous",
) -> jax.Array:
    """Exact causal attention with sequence sharded over ``axis_name``.

    q: global-view (B, T, H, Dh); T must divide evenly by the mesh's
    ``axis_name`` size.  GQA-native: k/v may carry fewer heads (H_kv
    dividing H) — the ring then rotates the UNREPEATED K/V chunks, so
    ICI traffic drops by the group factor; the flash chunk kernel
    streams grouped K/V directly and the dense fallback repeats only
    device-locally.  Returns (B, T, H, Dh).

    Per-chunk compute routes through the Pallas flash kernel when the
    local chunk length tiles (ops.flash_attention._exact_block), dense
    XLA otherwise; fully-masked chunks are skipped either way.

    ``layout="zigzag"`` (causal only) balances the causal ring's load:
    the contiguous layout leaves rank 0 computing 1 chunk while rank
    S-1 computes S, so the step critical path is the last rank; zigzag
    gives device i global chunks (i, 2S-1-i), evening live work.  With
    ``"zigzag"`` inputs/outputs keep the natural sequence order (the
    permutation is applied internally, 4 gathers per call);
    ``"zigzag_pre"`` expects q/k/v ALREADY in zigzag row order
    (``zigzag_layout(T, sp)``) and returns the output in that same
    order with no gathers — the production form, used by
    llama.forward_sp which permutes once per forward and runs the
    whole stack in zigzag space.  There is no runtime check that
    pre-permuted inputs really are permuted; get the order wrong and
    the causal mask is silently wrong.
    """
    from pytorch_operator_tpu.ops.flash_attention import _exact_block

    Dh = q.shape[-1]
    T = q.shape[1]
    H, Hk = q.shape[2], k.shape[2]
    if v.shape[2] != Hk or H % Hk:
        # must reject here: the flash chunk path's kv block index map
        # would silently clamp out-of-bounds groups into garbage
        raise ValueError(
            f"kv heads must divide q heads: q has {H}, k/v have "
            f"{k.shape[2]}/{v.shape[2]}")
    sp = mesh.shape[axis_name]
    t_local = T // sp
    interpret = jax.default_backend() != "tpu"
    # batch_axes: data-parallel mesh axes (dp/fsdp) the batch dim is
    # sharded over — the SP×FSDP composition (llama.forward_sp passes
    # parallel.mesh.data_axes); head_axes: tensor-parallel axes the
    # HEAD dim is sharded over (SP×TP — attention is embarrassingly
    # parallel per head, so the ring only ever rotates over
    # ``axis_name`` while each tp shard works its own head slice).
    from pytorch_operator_tpu.parallel.mesh import head_shard_degree

    head_shard_degree(mesh, head_axes, H, Hk)
    spec = P(batch_axes or None, axis_name, head_axes or None, None)
    shard_kw = dict(
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call outputs carry no vma metadata; the varying-axes
        # checker rejects them outright (same workaround as the remat
        # bodies in models/llama.py)
        check_vma=False,
    )
    if layout in ("zigzag", "zigzag_pre"):
        if not causal:
            raise ValueError(f"layout={layout!r} exists to balance "
                             f"CAUSAL ring load; use the default layout "
                             f"for non-causal attention")
        fn = shard_map(
            partial(_ring_body_zigzag, axis_name=axis_name,
                    scale=Dh ** -0.5,
                    block=_exact_block(t_local // 2, Dh),
                    interpret=interpret),
            **shard_kw)
        if layout == "zigzag_pre":
            # caller already laid q/k/v out in zigzag order (the
            # production path: llama.forward_sp permutes ONCE per
            # forward and runs the whole stack in zigzag space) —
            # outputs come back in the same zigzag order.  Validate the
            # divisibility even though no permutation is applied here.
            zigzag_layout(T, sp, axis_name)
            return fn(q, k, v)
        perm, inv = zigzag_layout(T, sp, axis_name)
        out = fn(q[:, perm], k[:, perm], v[:, perm])
        return out[:, inv]
    if layout != "contiguous":
        raise ValueError(f"unknown ring layout {layout!r}")
    fn = shard_map(
        partial(
            _ring_body, axis_name=axis_name, causal=causal,
            scale=Dh ** -0.5, block=_exact_block(t_local, Dh),
            interpret=interpret
        ),
        **shard_kw)
    return fn(q, k, v)
