"""Fixture factories shared across the test suite.

Equivalent of the reference's pkg/common/util/v1/testutil/job.go:28-145
(NewPyTorchJobWithMaster, NewPyTorchJobWithCleanPolicy, ...).
"""

from __future__ import annotations

from typing import Optional

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.api.v1.types import PyTorchJob, PyTorchJobSpec, ReplicaSpec
from pytorch_operator_tpu.k8s.objects import (
    Container,
    ContainerPort,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ResourceRequirements,
)

TEST_IMAGE = "test-image-for-pytorch-operator:latest"
TEST_JOB_NAME = "test-pytorchjob"
TEST_NAMESPACE = "default"


def new_pod_template(tpu_chips: int = 0) -> PodTemplateSpec:
    resources = None
    if tpu_chips:
        resources = ResourceRequirements(
            limits={constants.TPU_RESOURCE: str(tpu_chips)})
    return PodTemplateSpec(
        spec=PodSpec(
            containers=[
                Container(
                    name=constants.DEFAULT_CONTAINER_NAME,
                    image=TEST_IMAGE,
                    ports=[
                        ContainerPort(
                            name=constants.DEFAULT_PORT_NAME,
                            container_port=constants.DEFAULT_PORT,
                        )
                    ],
                    resources=resources,
                )
            ]
        )
    )


def new_replica_spec(replicas: Optional[int] = None,
                     tpu_chips: int = 0) -> ReplicaSpec:
    return ReplicaSpec(replicas=replicas,
                       template=new_pod_template(tpu_chips=tpu_chips))


def new_job(
    workers: int = 0,
    with_master: bool = True,
    name: str = TEST_JOB_NAME,
    namespace: str = TEST_NAMESPACE,
    tpu_chips: int = 0,
) -> PyTorchJob:
    """NewPyTorchJobWithMaster equivalent (testutil/job.go)."""
    specs = {}
    if with_master:
        specs[constants.REPLICA_TYPE_MASTER] = new_replica_spec(
            1, tpu_chips=tpu_chips)
    if workers > 0 or not with_master:
        specs[constants.REPLICA_TYPE_WORKER] = new_replica_spec(
            workers, tpu_chips=tpu_chips)
    return PyTorchJob(
        metadata=ObjectMeta(name=name, namespace=namespace, uid="test-uid-" + name),
        spec=PyTorchJobSpec(pytorch_replica_specs=specs),
    )


def wait_for(predicate, timeout: float = 15.0, interval: float = 0.02) -> bool:
    """Poll ``predicate`` until true or ``timeout`` elapses."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def job_condition(cluster, ns: str, name: str, cond_type: str) -> bool:
    """True when the job has ``cond_type`` with status "True"."""
    from pytorch_operator_tpu.k8s.errors import NotFoundError

    try:
        job = cluster.jobs.get(ns, name)
    except NotFoundError:
        return False
    for c in (job.get("status") or {}).get("conditions") or []:
        if c["type"] == cond_type and c["status"] == "True":
            return True
    return False
