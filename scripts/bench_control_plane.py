"""Control-plane latency bench: PyTorchJob create -> first step.

The second driver-defined metric (BASELINE.md): the reference's only
anchor is its README sample run — job create -> training start 5m34s on
GKE including scheduling and image pull (reference README.md:56-119) and
the 10-minute create->Succeeded e2e envelope (defaults.go:33,132).
Cluster-side costs (node scheduling, image pull) belong to the cluster,
not the operator, so this bench isolates what the framework controls:
**controller reaction latency** from job creation to pods existing /
status transitions, measured on two tiers:

  * ``sim``  — controller against the in-memory fake cluster + fake
    kubelet (pure reconcile-path latency, no serialization);
  * ``http`` — controller against the stub API server over real
    sockets with the production REST client and watch streams (adds
    JSON serde + HTTP round-trips, the operator's real deployment path).

Per tier, J jobs (1 Master + 3 Workers each) are created back-to-back
and each job reports create->first-pod, create->all-pods,
create->Running and create->Succeeded; the summary prints medians and
p95s.  One JSON line per tier goes to stdout; --out writes the
committed markdown artifact.

Run:  python scripts/bench_control_plane.py --out BENCH_CONTROL_PLANE.md
"""

from __future__ import annotations

import argparse
import datetime
import json
import math
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.k8s.errors import NotFoundError
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.k8s.stub_server import StubApiServer
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import JobControllerConfig


def new_job(name: str, workers: int = 3) -> dict:
    tmpl = {"spec": {"containers": [{"name": "pytorch", "image": "img:1"}]}}
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "PyTorchJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"pytorchReplicaSpecs": {
            "Master": {"replicas": 1, "restartPolicy": "OnFailure",
                       "template": tmpl},
            "Worker": {"replicas": workers, "restartPolicy": "OnFailure",
                       "template": tmpl},
        }},
    }


def _condition_true(job: dict, cond_type: str) -> bool:
    for c in (job.get("status") or {}).get("conditions") or []:
        if c["type"] == cond_type and c["status"] == "True":
            return True
    return False


def bench_tier(observe_cluster, client_cluster, jobs: int, workers: int,
               timeout: float = 60.0) -> dict:
    """Create `jobs` jobs through ``client_cluster`` and watch convergence
    through ``observe_cluster`` (same underlying state)."""
    per_job = []
    expected = workers + 1
    for j in range(jobs):
        name = f"bench-job-{j}"
        lat: dict = {}
        t0 = time.perf_counter()
        client_cluster.jobs.create("default", new_job(name, workers))
        deadline = t0 + timeout
        while time.perf_counter() < deadline:
            now = time.perf_counter()
            try:
                pods = [p for p in observe_cluster.pods.list("default")
                        if p["metadata"]["name"].startswith(name + "-")]
            except NotFoundError:
                pods = []
            if pods and "first_pod" not in lat:
                lat["first_pod"] = now - t0
            if len(pods) >= expected and "all_pods" not in lat:
                lat["all_pods"] = now - t0
            try:
                job = observe_cluster.jobs.get("default", name)
            except NotFoundError:
                job = {}
            if _condition_true(job, "Running") and "running" not in lat:
                lat["running"] = now - t0
            if _condition_true(job, "Succeeded"):
                lat["succeeded"] = now - t0
                break
            time.sleep(0.002)
        per_job.append(lat)

    def stats(key):
        vals = sorted(l[key] for l in per_job if key in l)
        if not vals:
            return {"median_ms": None, "p95_ms": None, "n": 0}
        # nearest-rank p95: ceil(0.95 n) - 1 (int(n*0.95) selects the
        # MAXIMUM for n <= 20, overstating the tail)
        idx = max(0, math.ceil(0.95 * len(vals)) - 1)
        return {
            "median_ms": round(statistics.median(vals) * 1e3, 1),
            "p95_ms": round(vals[idx] * 1e3, 1),
            "n": len(vals),
        }

    return {k: stats(k) for k in ("first_pod", "all_pods", "running",
                                  "succeeded")}


def run_sim(jobs: int, workers: int) -> dict:
    cluster = FakeCluster()
    kubelet = FakeKubelet(cluster)
    kubelet.start()
    ctl = PyTorchController(cluster, config=JobControllerConfig(),
                            registry=Registry())
    stop = threading.Event()
    ctl.run(threadiness=4, stop_event=stop)
    try:
        return bench_tier(cluster, cluster, jobs, workers)
    finally:
        stop.set()
        ctl.work_queue.shutdown()
        kubelet.stop()


def run_http(jobs: int, workers: int) -> dict:
    from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster

    srv = StubApiServer().start()
    kubelet = FakeKubelet(srv.cluster)
    kubelet.start()
    rest = RestCluster(KubeConfig.from_url(f"http://127.0.0.1:{srv.port}"),
                       namespace="default")
    ctl = PyTorchController(rest, config=JobControllerConfig(),
                            registry=Registry())
    stop = threading.Event()
    ctl.run(threadiness=4, stop_event=stop)
    try:
        # create and observe through the REST client: latencies include
        # the same HTTP path the deployed operator uses
        return bench_tier(rest, rest, jobs, workers)
    finally:
        stop.set()
        ctl.work_queue.shutdown()
        kubelet.stop()
        srv.stop()


def render_md(sim: dict, http: dict, jobs: int, workers: int) -> str:
    now = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M UTC")

    def row(tier, res):
        cells = []
        for k in ("first_pod", "all_pods", "running", "succeeded"):
            s = res[k]
            cells.append(f"{s['median_ms']} / {s['p95_ms']}"
                         if s["n"] else "—")
        return f"| {tier} | " + " | ".join(cells) + " |"

    return "\n".join([
        "# BENCH_CONTROL_PLANE — PyTorchJob create→first-step latency",
        "",
        f"Generated {now} by `python scripts/bench_control_plane.py` "
        f"({jobs} jobs x (1 Master + {workers} Workers) per tier, "
        "sequential).  Median / p95 in milliseconds.",
        "",
        "| tier | first pod | all pods | Running | Succeeded |",
        "|---|---|---|---|---|",
        row("sim (in-memory)", sim),
        row("http (REST + watch)", http),
        "",
        "`sim` is the controller against the in-memory fake cluster "
        "(pure reconcile latency); `http` runs the production REST "
        "client and watch streams against the stub API server over real "
        "sockets.  The fake kubelet adds its fixed schedule->Running "
        "(20ms) and Running->Succeeded (50ms) delays to the Running/"
        "Succeeded columns.  Reference anchors (BASELINE.md): the "
        "operator-independent create->start sample on GKE is 5m34s "
        "(image pull + scheduling dominated) with a 10-minute "
        "create->Succeeded e2e envelope; the controller-side reaction "
        "measured here is the part this framework controls.",
        "",
        "## Raw JSON",
        "",
        "```json",
        json.dumps({"sim": sim, "http": http}, indent=2),
        "```",
        "",
    ])


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--jobs", type=int, default=20)
    ap.add_argument("--workers", type=int, default=3)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    print(f"[bench_cp] sim tier ({args.jobs} jobs)...", file=sys.stderr)
    sim = run_sim(args.jobs, args.workers)
    print(json.dumps({"tier": "sim", **sim}))
    print(f"[bench_cp] http tier ({args.jobs} jobs)...", file=sys.stderr)
    http = run_http(args.jobs, args.workers)
    print(json.dumps({"tier": "http", **http}))

    if args.out:
        with open(args.out, "w") as f:
            f.write(render_md(sim, http, args.jobs, args.workers))
        print(f"[bench_cp] wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
