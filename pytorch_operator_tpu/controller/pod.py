"""Pod reconciliation for PyTorchJob replicas.

Behavioral mirror of the reference's pkg/controller.v1/pytorch/pod.go with
the TPU-native cluster spec (tpu_env.py) in place of the c10d wiring:
per-index pod slices, missing-index creation with deterministic labels and
owner refs, ExitCode retry handling, restart-policy mapping, the worker
DNS-wait init container, and gang-scheduler annotations.
"""

from __future__ import annotations

import copy
from typing import Dict, List

from ..api.v1 import constants
from ..api.v1.types import PyTorchJob, ReplicaSpec
from ..k8s import serde
from ..runtime.controls import (
    submit_creates_with_expectations,
    submit_deletes_with_expectations,
)
from ..runtime.expectations import expectation_pods_key
from ..runtime.job_controller import gen_general_name, gen_pod_group_name
from ..runtime.logger import logger_for_pod, logger_for_replica
from ..runtime.recorder import EVENT_TYPE_NORMAL, EVENT_TYPE_WARNING
from . import config as initconfig
from . import reconcile_plan
from . import status as status_machine
from .tpu_env import set_cluster_spec

POD_TEMPLATE_RESTART_POLICY_REASON = "SettedPodTemplateRestartPolicy"
EXITED_WITH_CODE_REASON = "ExitedWithCode"
POD_TEMPLATE_SCHEDULER_NAME_REASON = "SettedPodTemplateSchedulerName"


class PodReconcilerMixin:
    def reconcile_pods(
        self,
        job: PyTorchJob,
        job_dict: dict,
        pods: List[dict],
        rtype: str,
        spec: ReplicaSpec,
        gang_enabled: bool | None = None,
        elastic_target: int | None = None,
    ) -> None:
        """pod.go:49-117.  ``gang_enabled`` lets the caller pass the
        per-sync gang decision down; None recomputes (compat for direct
        callers in tests).  ``elastic_target`` below the configured
        count switches this replica set into the shrunken-elastic
        reconcile (drained index holes are not recreated; survivors
        keep their restart-policy semantics)."""
        if gang_enabled is None:
            gang_enabled = self.gang_scheduling_enabled(job)
        rt = rtype.lower()
        log = logger_for_replica(self.logger, job, rt)
        pods = self.filter_pods_for_replica_type(pods, rt)
        replicas = int(spec.replicas or 0)
        exit_code_policy = (
            spec.restart_policy == constants.RESTART_POLICY_EXIT_CODE)

        status_machine.initialize_replica_statuses(job.status, rtype)

        # Encode observed pods into plan rows and hand the decisions to
        # the reconcile kernel (native C++ when available,
        # reconcile_plan.plan_replica_set_py otherwise); this method then
        # performs the I/O the plan dictates, in ascending index order
        # like the reference's inline loop (pod.go:56-92).
        encoded = [_encode_pod(pod) for pod in pods]
        rows = [(index, phase, exit_code)
                for index, phase, exit_code, _ in encoded]

        creates, delete_rows, warns, counts, restart = (
            reconcile_plan.plan_replica_set(replicas, exit_code_policy, rows))

        # Shrunken elastic gang: the surviving slice IS the gang, so
        # index holes left by drained workers are NOT recreated
        # wholesale (the grow path restores the full index space
        # later).  Everything else is the normal reconcile — the spec's
        # restart policy still applies to SURVIVORS (a retryably-failed
        # worker's node outlived it, unlike the drained holes'), and
        # only enough of the LOWEST empty indices are refilled to keep
        # elastic_target workers occupied, so a restarted survivor's
        # replacement appears on the next sync while the remaining
        # holes wait for capacity.
        shrunken = elastic_target is not None and elastic_target < replicas
        allowed_creates = None
        if shrunken:
            occupied = replicas - len(creates)
            need = max(0, elastic_target - occupied)
            allowed_creates = frozenset(creates[:need])

        create_set = frozenset(creates)
        warn_set = frozenset(warns)
        delete_set = frozenset(delete_rows)
        sole_row_by_index = {}
        for r, (index, _, _) in enumerate(rows):
            if 0 <= index < replicas and index not in warn_set:
                sole_row_by_index[index] = r

        # Pipelined create path: build every missing pod first, then
        # submit them as ONE batch through the control's bounded fan-out
        # (create_many) — expectations are raised up-front for the whole
        # batch and decremented per observed failure, so the
        # CreationObserved bookkeeping is identical to N sequential
        # creates while the API round-trips overlap.
        planned: List[dict] = []
        for index in range(replicas):
            if index in create_set:
                if allowed_creates is not None and \
                        index not in allowed_creates:
                    continue  # drained hole: the grow path restores it
                log.info("Need to create new pod: %s-%d", rt, index)
                master_role = rtype == constants.REPLICA_TYPE_MASTER
                planned.append(self.build_new_pod(
                    job, job_dict, rtype, str(index), spec, master_role,
                    gang_enabled))
            elif index in warn_set:
                log.warning("We have too many pods for %s %d", rt, index)
            else:
                r = sole_row_by_index[index]
                pod = pods[r]
                if exit_code_policy:
                    for code in encoded[r][3]:
                        self.recorder.eventf(
                            job_dict,
                            EVENT_TYPE_NORMAL,
                            EXITED_WITH_CODE_REASON,
                            "Pod: %s.%s exited with code %s",
                            pod["metadata"].get("namespace", ""),
                            pod["metadata"].get("name", ""),
                            code,
                        )
                if r in delete_set:
                    logger_for_pod(self.logger, pod, job).info(
                        "Need to restart the pod: %s", pod["metadata"].get("name")
                    )
                    self.pod_control.delete_pod(
                        pod["metadata"].get("namespace", ""),
                        pod["metadata"].get("name", ""),
                        job_dict,
                    )

        if planned:
            self.submit_pod_creates(job, job_dict, rtype, planned)

        status_machine.apply_replica_counts(job.status, rtype, *counts)

        self.update_status_single(
            job, job_dict, rtype,
            elastic_target if shrunken else replicas, restart)

    # ------------------------------------------------------------------
    def create_new_pod(
        self,
        job: PyTorchJob,
        job_dict: dict,
        rtype: str,
        index: str,
        spec: ReplicaSpec,
        master_role: bool,
        gang_enabled: bool | None = None,
    ) -> None:
        """pod.go:140-232 — compat single-pod entry (direct callers and
        tests): a batch of one through the pipelined path."""
        if gang_enabled is None:
            gang_enabled = self.gang_scheduling_enabled(job)
        pod = self.build_new_pod(job, job_dict, rtype, index, spec,
                                 master_role, gang_enabled)
        self.submit_pod_creates(job, job_dict, rtype, [pod])

    def submit_pod_creates(
        self, job: PyTorchJob, job_dict: dict, rtype: str, pods: List[dict]
    ) -> None:
        """Issue one batch of pod creates through the bounded fan-out.

        Expectations are raised up-front for the whole batch (upstream
        kube's ExpectCreations(key, diff) shape) and decremented once per
        failed create — successes are observed by the pod informer,
        failures in the shared protocol helper.  Without the per-failure
        rollback a failed create (e.g. AlreadyExists colliding with a pod
        of the job's previous incarnation that GC hasn't removed yet)
        parks the job unsynced until the 5-minute expectations TTL — the
        deliberate divergence from the reference's pod.go:218-226
        surfaced by the churn bench.
        """
        submit_creates_with_expectations(
            self.expectations, expectation_pods_key(job.key, rtype.lower()),
            self.pod_control.create_many, job.metadata.namespace, pods,
            job_dict, self.gen_owner_reference(job_dict))

    def submit_pod_deletes(
        self, job: PyTorchJob, job_dict: dict, rtype: str, pods: List[dict]
    ) -> None:
        """Issue one batch of pod deletes through the bounded fan-out —
        the delete-side mirror of submit_pod_creates (ROADMAP fan-out
        item): deletion expectations raised up-front for the batch,
        decremented per failed delete, successes observed by the pod
        informer's DELETED callback.  Rides under CleanPodPolicy
        All/Running terminal cleanup and the disruption subsystem's
        proactive gang restart."""
        names = [p.get("metadata", {}).get("name", "") for p in pods]
        submit_deletes_with_expectations(
            self.expectations, expectation_pods_key(job.key, rtype.lower()),
            self.pod_control.delete_many, job.metadata.namespace, names,
            job_dict)

    def build_new_pod(
        self,
        job: PyTorchJob,
        job_dict: dict,
        rtype: str,
        index: str,
        spec: ReplicaSpec,
        master_role: bool,
        gang_enabled: bool,
    ) -> dict:
        """Render one replica's pod template (the pure part of
        pod.go:140-232; no API calls, no expectations)."""
        rt = rtype.lower()
        labels = self.gen_labels(job.metadata.name)
        labels[constants.LABEL_REPLICA_TYPE] = rt
        labels[constants.LABEL_REPLICA_INDEX] = index
        if master_role:
            labels[constants.LABEL_JOB_ROLE] = "master"
        # sharded control plane: children inherit the job's shard label
        # — and its ring-epoch label after a live reshard — so the
        # owning replica's shard-filtered (epoch-fenced) pod informer
        # sees them (absent on unsharded operators — existing pods
        # byte-identical)
        job_labels = ((job_dict.get("metadata") or {}).get("labels")
                      or {})
        for ring_key in (constants.LABEL_SHARD,
                         constants.LABEL_RING_EPOCH):
            if job_labels.get(ring_key) is not None:
                labels[ring_key] = job_labels[ring_key]

        template = serde.to_dict(spec.template)
        pod = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": copy.deepcopy(template.get("metadata") or {}),
            "spec": copy.deepcopy(template.get("spec") or {}),
        }
        pod["metadata"]["name"] = gen_general_name(job.metadata.name, rt, index)
        pod_labels = pod["metadata"].setdefault("labels", {})
        pod_labels.update(labels)

        set_cluster_spec(pod, job, index, rtype)

        # per-job push-identity token: the pod proves its claimed job
        # to the telemetry PushGateway with this env value (derived,
        # never stored — the gateway re-derives from the live job's
        # uid), closing the spoofed-"job"-field hole
        from ..telemetry.push import derive_push_token

        token = derive_push_token(
            job.key, job.metadata.uid or "",
            getattr(self.config, "push_token_secret", "") or "")
        for container in pod["spec"].get("containers") or []:
            if container.get("name") == constants.DEFAULT_CONTAINER_NAME:
                container.setdefault("env", []).append(
                    {"name": constants.ENV_PUSH_TOKEN, "value": token})

        if pod["spec"].get("restartPolicy"):
            msg = (
                "Restart policy in pod template will be overwritten by"
                " restart policy in replica spec"
            )
            logger_for_replica(self.logger, job, rt).warning(msg)
            self.recorder.event(
                job_dict, EVENT_TYPE_WARNING, POD_TEMPLATE_RESTART_POLICY_REASON, msg
            )
        _set_restart_policy(pod, spec)

        if not master_role:
            master_addr = gen_general_name(
                job.metadata.name, constants.REPLICA_TYPE_MASTER.lower(), 0
            )
            init_containers = initconfig.render_init_containers(
                master_addr, self.config.init_container_image
            )
            pod["spec"].setdefault("initContainers", []).extend(init_containers)

        if gang_enabled:
            if self._is_non_gang_scheduler_set(job):
                msg = (
                    "Another scheduler is specified when gang-scheduling is"
                    " enabled and it will not be overwritten"
                )
                logger_for_replica(self.logger, job, rt).warning(msg)
                self.recorder.event(
                    job_dict, EVENT_TYPE_WARNING, POD_TEMPLATE_SCHEDULER_NAME_REASON, msg
                )
            else:
                pod["spec"]["schedulerName"] = self.config.gang_scheduler_name
            pod["metadata"].setdefault("annotations", {})[
                constants.GANG_SCHEDULING_POD_GROUP_ANNOTATION
            ] = gen_pod_group_name(job.metadata.name)

        return pod

    def _is_non_gang_scheduler_set(self, job: PyTorchJob) -> bool:
        for spec in job.spec.pytorch_replica_specs.values():
            name = spec.template.spec.scheduler_name
            if name and name != self.config.gang_scheduler_name:
                return True
        return False


def _encode_pod(pod: dict):
    """One pod -> (index, phase_enum, exit_code, terminated_codes).

    The single place that parses the replica-index label (same
    missing/unparseable -> dropped semantics as
    runtime.job_controller.get_pod_slices) and scans containerStatuses
    for the framework container's terminated exit codes — used both to
    build the reconcile-plan rows and to emit ExitedWithCode events, so
    the two cannot diverge.  exit_code is the LAST terminated code seen
    (pod.go:74-81 order).
    """
    labels = pod.get("metadata", {}).get("labels") or {}
    try:
        index = int(labels.get(constants.LABEL_REPLICA_INDEX))
    except (TypeError, ValueError):
        index = -1
    status = pod.get("status") or {}
    terminated_codes = [
        (cs.get("state") or {}).get("terminated").get("exitCode", 0)
        for cs in status.get("containerStatuses") or []
        if cs.get("name") == constants.DEFAULT_CONTAINER_NAME
        and (cs.get("state") or {}).get("terminated")
    ]
    exit_code = terminated_codes[-1] if terminated_codes else 0
    return (index, reconcile_plan.encode_phase(status.get("phase")),
            exit_code, terminated_codes)


def _set_restart_policy(pod: dict, spec: ReplicaSpec) -> None:
    """pod.go:283-297: ExitCode maps to Never (the controller implements
    the retry itself); other policies pass through to the pod."""
    if spec.restart_policy == constants.RESTART_POLICY_EXIT_CODE:
        pod["spec"]["restartPolicy"] = "Never"
    else:
        pod["spec"]["restartPolicy"] = spec.restart_policy
