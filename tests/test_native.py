"""Native (C++) runtime core: same contract as the Python implementations.

Runs the workqueue/expectations semantics table against BOTH
implementations, then the full e2e simulation with the native core
forced on, proving drop-in equivalence.
"""

from __future__ import annotations

import threading
import time

import pytest

from pytorch_operator_tpu.runtime import ControllerExpectations, WorkQueue

native = pytest.importorskip("pytorch_operator_tpu.native")

if not native.native_available():
    pytest.skip(f"native core unavailable: {native.load_error()}",
                allow_module_level=True)


@pytest.fixture(params=["python", "native"])
def queue(request):
    if request.param == "python":
        return WorkQueue()
    return native.NativeWorkQueue()


@pytest.fixture(params=["python", "native"])
def expectations(request):
    if request.param == "python":
        return ControllerExpectations()
    return native.NativeExpectations()


class TestWorkQueueContract:
    def test_dedupe(self, queue):
        queue.add("k")
        queue.add("k")
        assert len(queue) == 1

    def test_fifo(self, queue):
        for k in ("a", "b", "c"):
            queue.add(k)
        got = [queue.get(1.0)[0] for _ in range(3)]
        assert got == ["a", "b", "c"]

    def test_processing_exclusion(self, queue):
        """An item re-added while processing is deferred until done()."""
        queue.add("k")
        item, _ = queue.get(1.0)
        queue.add("k")
        assert queue.get(0.05) == (None, False)
        queue.done("k")
        assert queue.get(1.0)[0] == "k"

    def test_done_without_reader(self, queue):
        queue.add("k")
        queue.get(1.0)
        queue.done("k")
        assert queue.get(0.05) == (None, False)

    def test_add_after_delays(self, queue):
        queue.add_after("k", 0.15)
        assert queue.get(0.02) == (None, False)
        t0 = time.monotonic()
        item, _ = queue.get(2.0)
        assert item == "k"
        assert time.monotonic() - t0 >= 0.05

    def test_is_dirty(self, queue):
        assert not queue.is_dirty("k")
        queue.add("k")
        assert queue.is_dirty("k")
        queue.get(1.0)
        assert not queue.is_dirty("k")  # processing, not dirty
        queue.add("k")
        assert queue.is_dirty("k")

    def test_forget_cancels_pending_retry(self, queue):
        queue.add_rate_limited("k")
        queue.forget("k")
        assert queue.get(0.2) == (None, False)

    def test_plain_add_after_survives_forget(self, queue):
        queue.add_after("k", 0.05)
        queue.forget("k")
        assert queue.get(2.0)[0] == "k"

    def test_retry_deduped_against_queued_key(self, queue):
        """Rate-limited requeue + live watch event must not
        double-process the key after the first done()."""
        queue.add("k")
        assert queue.get(1.0)[0] == "k"
        queue.add("k")               # watch event while processing
        queue.add_rate_limited("k")  # failed sync's retry -> deduped
        queue.done("k")
        assert queue.get(1.0)[0] == "k"  # the single re-process
        queue.done("k")
        assert queue.get(0.2) == (None, False)

    def test_newer_retry_supersedes_pending(self, queue):
        queue.add_rate_limited("k")
        queue.add_rate_limited("k")
        assert queue.get(2.0)[0] == "k"
        queue.done("k")
        assert queue.get(0.3) == (None, False)

    def test_rate_limited_backoff_counts(self, queue):
        queue.add_rate_limited("k")
        queue.add_rate_limited("k")
        queue.add_rate_limited("k")
        assert queue.num_requeues("k") == 3
        queue.forget("k")
        assert queue.num_requeues("k") == 0

    def test_shutdown_unblocks_getters(self, queue):
        results = []

        def getter():
            results.append(queue.get(5.0))

        threads = [threading.Thread(target=getter) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        queue.shutdown()
        for t in threads:
            t.join(timeout=5)
            assert not t.is_alive()
        assert all(sd for (_, sd) in results)

    def test_concurrent_workers_no_duplicates(self, queue):
        """N workers, each item processed exactly once per add round."""
        seen = []
        seen_lock = threading.Lock()
        stop = threading.Event()

        def worker():
            while not stop.is_set():
                item, shutdown = queue.get(0.1)
                if shutdown:
                    return
                if item is None:
                    continue
                with seen_lock:
                    seen.append(item)
                queue.done(item)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(200):
            queue.add(f"item-{i}")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with seen_lock:
                if len(seen) >= 200:
                    break
            time.sleep(0.01)
        stop.set()
        queue.shutdown()
        for t in threads:
            t.join(timeout=5)
        with seen_lock:
            assert sorted(seen) == sorted(f"item-{i}" for i in range(200))


class TestExpectationsContract:
    def test_creations_cycle(self, expectations):
        expectations.expect_creations("k", 2)
        assert not expectations.satisfied("k")
        expectations.creation_observed("k")
        assert not expectations.satisfied("k")
        expectations.creation_observed("k")
        assert expectations.satisfied("k")

    def test_deletions_cycle(self, expectations):
        expectations.expect_deletions("k", 1)
        assert not expectations.satisfied("k")
        expectations.deletion_observed("k")
        assert expectations.satisfied("k")

    def test_never_set_is_satisfied(self, expectations):
        assert expectations.satisfied("unknown")

    def test_delete_expectations(self, expectations):
        expectations.expect_creations("k", 5)
        expectations.delete_expectations("k")
        assert expectations.satisfied("k")

    def test_raise_expectations(self, expectations):
        expectations.expect_creations("k", 1)
        expectations.raise_expectations("k", adds=1)
        expectations.creation_observed("k")
        assert not expectations.satisfied("k")
        expectations.creation_observed("k")
        assert expectations.satisfied("k")

    def test_observe_below_zero_stays_satisfied(self, expectations):
        expectations.expect_creations("k", 1)
        expectations.creation_observed("k")
        expectations.creation_observed("k")
        assert expectations.satisfied("k")


class TestNativeTtl:
    def test_expired_expectation_is_satisfied(self):
        e = native.NativeExpectations(ttl_seconds=0.1)
        e.expect_creations("k", 5)
        assert not e.satisfied("k")
        time.sleep(0.15)
        assert e.satisfied("k")


def test_e2e_sim_with_native_core(monkeypatch):
    """Full controller loop on the C++ queue + expectations."""
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE", "1")

    from pytorch_operator_tpu.api.v1 import constants
    from pytorch_operator_tpu.controller import PyTorchController
    from pytorch_operator_tpu.k8s.fake import FakeCluster
    from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
    from pytorch_operator_tpu.metrics.prometheus import Registry
    from pytorch_operator_tpu.runtime import JobControllerConfig

    from testutil import new_job

    cluster = FakeCluster()
    ctl = PyTorchController(cluster, config=JobControllerConfig(),
                            registry=Registry())
    assert isinstance(ctl.work_queue, native.NativeWorkQueue)
    assert isinstance(ctl.expectations, native.NativeExpectations)
    kubelet = FakeKubelet(cluster)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=3, stop_event=stop)
    try:
        cluster.jobs.create("default", new_job(workers=3, name="nat-job").to_dict())
        deadline = time.monotonic() + 15
        done = False
        while time.monotonic() < deadline and not done:
            job = cluster.jobs.get("default", "nat-job")
            conds = (job.get("status") or {}).get("conditions") or []
            done = any(c["type"] == constants.JOB_SUCCEEDED and c["status"] == "True"
                       for c in conds)
            time.sleep(0.02)
        assert done, "job did not succeed on the native core"
        pods = {p["metadata"]["name"] for p in cluster.pods.list()}
        assert {"nat-job-master-0", "nat-job-worker-0", "nat-job-worker-1",
                "nat-job-worker-2"} <= pods
    finally:
        stop.set()
        ctl.work_queue.shutdown()
        kubelet.stop()


@pytest.fixture(params=["python", "native"])
def store(request):
    from pytorch_operator_tpu.runtime.informer import Store

    if request.param == "python":
        return Store()
    return native.NativeStore()


def _obj(ns, name, rv="1", **extra):
    o = {"metadata": {"namespace": ns, "name": name, "resourceVersion": rv}}
    o.update(extra)
    return o


class TestStoreContract:
    """runtime.informer.Store and native.NativeStore are drop-ins."""

    def test_add_get_roundtrip(self, store):
        store.add(_obj("ns", "a", "5", kind="Pod", spec={"x": [1, 2]}))
        got = store.get_by_key("ns/a")
        assert got["kind"] == "Pod"
        assert got["spec"] == {"x": [1, 2]}
        assert got["metadata"]["resourceVersion"] == "5"

    def test_get_missing(self, store):
        assert store.get_by_key("nope/nothing") is None

    def test_update_replaces(self, store):
        store.add(_obj("ns", "a", "1", phase="Pending"))
        store.update(_obj("ns", "a", "2", phase="Running"))
        got = store.get_by_key("ns/a")
        assert got["phase"] == "Running"
        assert got["metadata"]["resourceVersion"] == "2"

    def test_delete(self, store):
        o = _obj("ns", "a")
        store.add(o)
        store.delete(o)
        assert store.get_by_key("ns/a") is None
        store.delete(o)  # idempotent

    def test_keys_and_list(self, store):
        store.add(_obj("ns", "a"))
        store.add(_obj("other", "b"))
        store.add(_obj(None, "clusterwide"))
        assert sorted(store.keys()) == ["clusterwide", "ns/a", "other/b"]
        assert {o["metadata"]["name"] for o in store.list()} == {
            "a", "b", "clusterwide"}

    def test_cluster_scoped_key(self, store):
        store.add(_obj(None, "n"))
        assert store.get_by_key("n")["metadata"]["name"] == "n"


class TestNativeStoreSemantics:
    """Native-only guarantees beyond the shared contract."""

    def test_deep_copy_on_read(self):
        s = native.NativeStore()
        s.add(_obj("ns", "a", "1", spec={"replicas": 1}))
        got = s.get_by_key("ns/a")
        got["spec"]["replicas"] = 99  # mutate the returned copy
        assert s.get_by_key("ns/a")["spec"]["replicas"] == 1

    def test_resource_version_without_parse(self):
        s = native.NativeStore()
        s.add(_obj("ns", "a", "42"))
        assert s.get_resource_version("ns/a") == "42"
        assert s.get_resource_version("ns/missing") is None

    def test_len(self):
        s = native.NativeStore()
        assert len(s) == 0
        s.add(_obj("ns", "a"))
        s.add(_obj("ns", "b"))
        assert len(s) == 2

    def test_concurrent_readers_writers(self):
        s = native.NativeStore()
        errors = []

        def writer(i):
            try:
                for j in range(200):
                    s.add(_obj("ns", f"obj-{i}-{j % 10}", str(j)))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        def reader():
            try:
                for _ in range(200):
                    for key in s.keys():
                        s.get_by_key(key)  # may be None mid-delete: fine
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(i,)) for i in range(4)]
        threads += [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(s) == 40  # 4 writers x 10 distinct names


def test_informer_uses_native_store(monkeypatch):
    """Default informer cache is the native store when the lib loads."""
    monkeypatch.setenv("PYTORCH_OPERATOR_NATIVE", "1")
    from pytorch_operator_tpu.runtime.informer import Informer, _make_store

    assert type(_make_store()).__name__ == "NativeStore"

    class FakeSource:
        def __init__(self):
            self.listeners = []

        def add_listener(self, fn):
            self.listeners.append(fn)

        def remove_listener(self, fn):
            self.listeners.remove(fn)

        def list(self, namespace=None):
            return [_obj("ns", "seed", "1", kind="PyTorchJob")]

    src = FakeSource()
    inf = Informer(src)
    seen = []
    inf.add_event_handler(on_add=lambda o: seen.append(o["metadata"]["name"]))
    inf.start()
    assert inf.has_synced()
    assert seen == ["seed"]
    assert inf.store.get_by_key("ns/seed")["kind"] == "PyTorchJob"
    # watch events flow through the native cache
    src.listeners[0]("DELETED", _obj("ns", "seed", "1"))
    assert inf.store.get_by_key("ns/seed") is None


class TestReconcilePlanEquivalence:
    """The C++ reconcile kernel must agree with the Python reference
    implementation on every scenario — tested exhaustively over small
    spaces and randomly over large ones."""

    def test_exit_code_table_equivalence(self):
        from pytorch_operator_tpu.controller import train_util

        for code in range(0, 256):
            for tpu_aware in (True, False):
                assert native.native_retryable_exit_code(
                    code, tpu_aware) == train_util.is_retryable_exit_code(
                        code, tpu_aware=tpu_aware), (
                    f"exit code {code} tpu_aware={tpu_aware}")

    def test_known_scenarios(self):
        from pytorch_operator_tpu.controller.reconcile_plan import (
            PHASE_FAILED, PHASE_OTHER, PHASE_RUNNING, PHASE_SUCCEEDED,
            plan_replica_set_py)

        scenarios = [
            # (replicas, exit_code_policy, rows)
            (3, False, []),                                    # all missing
            (1, False, [(0, PHASE_RUNNING, 0)]),               # steady state
            (2, True, [(0, PHASE_FAILED, 137),                 # retryable
                       (1, PHASE_FAILED, 1)]),                 # permanent
            (2, True, [(0, PHASE_FAILED, 134)]),               # TPU retryable
            (2, False, [(0, PHASE_FAILED, 137)]),              # policy off
            (3, True, [(0, PHASE_RUNNING, 0), (0, PHASE_RUNNING, 0),
                       (2, PHASE_SUCCEEDED, 0)]),              # dup slice
            (2, True, [(-1, PHASE_RUNNING, 0), (5, PHASE_FAILED, 137),
                       (1, PHASE_OTHER, 0)]),                  # out of range
            (0, True, [(0, PHASE_RUNNING, 0)]),                # zero replicas
        ]
        for replicas, policy, rows in scenarios:
            expected = plan_replica_set_py(replicas, policy, rows)
            got = native.native_rc_plan(replicas, policy, True, rows)
            assert got == expected, (replicas, policy, rows)

    def test_randomized_equivalence(self):
        import random

        from pytorch_operator_tpu.controller.reconcile_plan import (
            plan_replica_set_py)

        rng = random.Random(20260730)
        codes = [0, 1, 2, 126, 127, 128, 130, 134, 135, 137, 138, 139,
                 143, 42, 255]
        for _ in range(500):
            replicas = rng.randint(0, 8)
            n = rng.randint(0, 12)
            rows = [(rng.randint(-2, replicas + 2), rng.randint(0, 3),
                     rng.choice(codes)) for _ in range(n)]
            policy = rng.random() < 0.5
            tpu_aware = rng.random() < 0.5
            expected = plan_replica_set_py(replicas, policy, rows,
                                           tpu_aware=tpu_aware)
            got = native.native_rc_plan(replicas, policy, tpu_aware, rows)
            assert got == expected, (replicas, policy, tpu_aware, rows)

    def test_oversized_replicas_rejected(self):
        with pytest.raises(ValueError):
            native.native_rc_plan(5000, True, True, [])


def test_plan_large_replicas_falls_back_to_python():
    """replicas > the C kernel's 4096 cap must reconcile via the Python
    planner, not hot-loop on a ValueError."""
    from pytorch_operator_tpu.controller.reconcile_plan import (
        PHASE_RUNNING, plan_replica_set)

    creates, deletes, warns, counts, restart = plan_replica_set(
        5000, True, [(0, PHASE_RUNNING, 0)])
    assert len(creates) == 4999 and counts == (1, 0, 0)


def test_plan_int32_overflow_index_stays_out_of_range():
    """A replica-index label >= 2**32 must not alias to index 0 through
    ctypes truncation — both backends treat it as out-of-range."""
    from pytorch_operator_tpu.controller.reconcile_plan import (
        PHASE_RUNNING, plan_replica_set_py)

    rows = [(2**32, PHASE_RUNNING, 0)]
    expected = plan_replica_set_py(2, False, rows)
    got = native.native_rc_plan(2, False, True, rows)
    assert got == expected
    assert got[0] == [0, 1]  # both indices still need creation
