"""Fused causal flash attention (Pallas TPU kernels, fwd + bwd).

Forward pass streams K/V blocks through VMEM via a third grid dimension
with an online softmax (running max + running denominator), so neither
the (T, T) score matrix nor the full K/V sequence ever sits in VMEM —
usable T is bounded by HBM, not the ~16MB VMEM.  Tiles are
(block_q x d) @ (d x block_k) MXU matmuls with f32 accumulation.

Backward pass is the FlashAttention-2 recipe with O(T) memory and no
(T, T) buffer.  Two strategies, picked by sequence length:

  fused kernel (default) — grid (BH, n_k, n_q): one pass computes
    dk[j]/dv[j] in scratch AND accumulates dq[i] += ds[i,j] @ K[j]
    into a constant-index (1, T, D) f32 output block that stays
    VMEM-resident for the whole (j, i) sweep.  p^T and dp^T are
    recomputed once per tile (5 matmuls/tile, the FA-2 minimum).
  two-kernel fallback (T*D f32 too big for VMEM) — separate dq and
    dkv kernels, each recomputing p^T (7 matmuls/tile):
    dq kernel  — grid (BH, n_q, n_k):  dq[i] = sum_j ds[i,j] @ K[j]
    dkv kernel — grid (BH, n_k, n_q):  dk[j] = sum_i ds[i,j]^T @ Q[i],
                                       dv[j] = sum_i  p[i,j]^T @ dO[i]

where p is recomputed blockwise from the saved per-row logsumexp
(lse = m + log l) and ds = p * (dp - delta) * scale with
delta = rowsum(dO * O) computed once in plain XLA.

Layout note: inside the backward kernels every score-shaped tile is kept
*transposed* — (block_k sublanes, block_q lanes) — so the q-indexed
row vectors (lse, delta, stored as (1, block_q) blocks) broadcast along
lanes without any cross-lane reshape; the only sublane<->lane transpose
in the whole pipeline is the (block_q, 1) -> (1, block_q) lse write at
the end of the forward.

Arbitrary sequence lengths: when T is not a multiple of the block
size, inputs are zero-padded up to the next block multiple and the
kernels mask padded key positions in-register (``kpos < seq_len`` →
NEG_INF, same iota guard the causal mask uses); tiles that lie wholly
in the padded region are skipped by the grid guards.  Padded *query*
rows need no mask: their outputs are sliced away, and in the backward
their cotangents are zero (g rows are zero ⇒ dp = 0 and delta = 0 ⇒
ds = 0), so they contribute nothing to dk/dv.  Every T ≥ 1 therefore
takes the Pallas path; ``_dense_reference`` remains only as a ground
truth for tests.  ``interpret=True`` runs the same kernels on CPU test
meshes.

Reference parity note: the reference operator has no attention kernels
at all (its data plane is examples/mnist/mnist.py); this module is part
of the TPU-native data plane that replaces the reference's CUDA-backed
torch ops.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _dense_reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q, k).astype(jnp.float32) * scale
    if causal:
        T = q.shape[1]
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _live_tile(i, j, block_q, block_k, causal, seq_len):
    """Static-shape guard: does tile (q block i, k block j) contribute?

    Skips blocks strictly above the causal diagonal and (for padded
    tails) blocks whose q rows or k columns lie entirely past the true
    sequence length.  Returns None when every tile is live.
    """
    live = None
    if causal:
        live = j * block_k <= i * block_q + block_q - 1
    if seq_len is not None:
        tail = (i * block_q < seq_len) & (j * block_k < seq_len)
        live = tail if live is None else live & tail
    return live


def _score_mask(s, i, j, bq, bk, transposed, causal, seq_len):
    """Apply causal and/or padded-tail masking to a score tile.

    ``transposed`` selects the (block_k, block_q) layout the backward
    kernels use (k in sublanes, q in lanes).  Padded key positions are
    masked to NEG_INF; padded query rows are deliberately left alone
    (see module docstring — their cotangents are zero).
    """
    if not causal and seq_len is None:
        return s
    shape = s.shape
    q_dim, k_dim = (1, 0) if transposed else (0, 1)
    kpos = j * bk + lax.broadcasted_iota(jnp.int32, shape, k_dim)
    ok = None
    if causal:
        qpos = i * bq + lax.broadcasted_iota(jnp.int32, shape, q_dim)
        ok = qpos >= kpos
    if seq_len is not None:
        valid = kpos < seq_len
        ok = valid if ok is None else ok & valid
    return jnp.where(ok, s, NEG_INF)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, block_q, block_k, scale, causal,
                seq_len):
    import jax.experimental.pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0]                                  # (block_q, d)
        k = k_ref[0]                                  # (block_k, d)
        v = v_ref[0]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (block_q, block_k)
        s = _score_mask(s, i, j, block_q, block_k, False, causal, seq_len)
        m_prev = m_scr[...]                           # (block_q, 1)
        l_prev = l_scr[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    live = _live_tile(i, j, block_q, block_k, causal, seq_len)
    if live is None:
        _compute()
    else:
        pl.when(live)(_compute)

    @pl.when(j == n_k - 1)
    def _finalize():
        l = l_scr[...]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        lse = m_scr[...] + jnp.log(l_safe)            # (block_q, 1)
        lse_ref[0] = jnp.transpose(lse)               # (1, block_q)


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
               seq_len=None):
    """Returns (out (BH,T,D), lse (BH,1,T) f32).

    GQA-native: k/v may carry fewer heads than q — (B*H_kv, T, D) with
    H % H_kv == 0.  With the b = batch*H + head layout, the kv block
    for q row b is simply b // group (group = H // H_kv), so grouped
    queries stream each K/V block from HBM once per group instead of
    materialising repeated K/V (1/group the k/v read traffic).
    Honest perf note (v5e, T4096 H16/kv4, two-point scan timing): the
    kernel is MXU-bound at these shapes and K/V DMA fully overlaps, so
    wall time is at PARITY with repeat-KV (~1.0x, BENCH_DETAIL §2b);
    the wins are HBM capacity (no H-head K/V ever materialised) and
    wire traffic where K/V actually moves (ring SP rotates 1/group the
    bytes over ICI — parallel/ring_attention.py).

    lse is stored (BH, 1, T) — q positions in the *lane* dimension — so
    both the forward write and the backward reads use (1, 1, block_q)
    blocks, which satisfy the mosaic block-shape rule (last two dims
    divisible by (8, 128) or equal to the array's) without replicating
    across 128 lanes the way jax's bundled kernel does.
    """
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    from pytorch_operator_tpu.utils.jax_compat import tpu_compiler_params

    BH, T, D = q.shape
    group = BH // k.shape[0]
    grid = (BH, T // block_q, T // block_k)
    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k,
        scale=scale, causal=causal, seq_len=seq_len)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D),
                         lambda b, i, j: (b // group, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D),
                         lambda b, i, j: (b // group, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
        name="flash_fwd",
    )(q, k, v)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _transposed_probs(q_ref, k_ref, lse_ref, i, j, block_q, block_k, scale,
                      causal, seq_len):
    """Recompute p^T = exp(s^T - lse) for one (i, j) tile.

    Returns (block_k, block_q) f32 with q rows in *lanes* so the
    (1, block_q) lse/delta blocks broadcast without reshapes.

    Padded-tail note: wholly-padded q blocks (lse = NEG_INF, where this
    exp would blow up to +inf) NEVER reach this function — _live_tile's
    tail guard skips their tiles, and that guard is what keeps the
    backward NaN-free.  In a partially padded last q block every valid
    row has finite lse, and the padded *lanes* there carry zero
    cotangents (do = 0, delta = 0 ⇒ ds = 0), so dk/dv stay exact and
    the garbage dq rows are sliced away by the caller.
    """
    q = q_ref[0]                                      # (block_q, d)
    k = k_ref[0]                                      # (block_k, d)
    s_t = lax.dot_general(
        k, q, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale   # (block_k, block_q)
    s_t = _score_mask(s_t, i, j, block_q, block_k, True, causal, seq_len)
    return jnp.exp(s_t - lse_ref[0])                  # (block_k, block_q)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, block_q, block_k, scale, causal, seq_len):
    import jax.experimental.pallas as pl

    i = pl.program_id(1)
    j = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _compute():
        p_t = _transposed_probs(q_ref, k_ref, lse_ref, i, j,
                                block_q, block_k, scale, causal, seq_len)
        v = v_ref[0]
        do = do_ref[0]
        dp_t = lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_k, block_q)
        ds_t = p_t * (dp_t - delta_ref[0]) * scale    # (block_k, block_q)
        # dq[i] += ds[i,j] @ K[j]  ==  ds_t^T @ K  (contract sublanes)
        dq_scr[...] += lax.dot_general(
            ds_t.astype(k_ref.dtype), k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_q, d)

    live = _live_tile(i, j, block_q, block_k, causal, seq_len)
    if live is None:
        _compute()
    else:
        pl.when(live)(_compute)

    @pl.when(j == n_k - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _dkv_tile_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_scr, dv_scr, i, j, block_q, block_k, scale, causal,
                   seq_len):
    """Shared FA-2 tile math: accumulate dv/dk for one (i, j) tile and
    return ds^T for the caller (the fused kernel also needs it for dq).
    """
    p_t = _transposed_probs(q_ref, k_ref, lse_ref, i, j,
                            block_q, block_k, scale, causal, seq_len)
    do = do_ref[0]                                    # (block_q, d)
    # dv[j] += p[i,j]^T @ dO[i]
    dv_scr[...] += lax.dot_general(
        p_t.astype(do.dtype), do, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (block_k, d)
    dp_t = lax.dot_general(
        v_ref[0], do, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)           # (block_k, block_q)
    ds_t = p_t * (dp_t - delta_ref[0]) * scale
    # dk[j] += ds[i,j]^T @ Q[i]
    dk_scr[...] += lax.dot_general(
        ds_t.astype(q_ref.dtype), q_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)           # (block_k, d)
    return ds_t


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr,
                    *, block_q, block_k, scale, causal, seq_len):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)   # k block (outer)
    i = pl.program_id(2)   # q block (inner, accumulated)
    n_q = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        _dkv_tile_step(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dk_scr, dv_scr, i, j, block_q, block_k, scale,
                       causal, seq_len)

    live = _live_tile(i, j, block_q, block_k, causal, seq_len)
    if live is None:
        _compute()
    else:
        pl.when(live)(_compute)

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                      *, block_q, block_k, scale, causal, seq_len):
    """One-pass backward: dk/dv via scratch accumulation over i, dq via
    in-place accumulation into the whole-sequence f32 output block.

    dq's block index map is constant in (j, i), so Pallas keeps one
    (1, T, D) VMEM buffer live across the entire sweep for each
    batch-head — cross-j accumulation costs no HBM round trips, and
    p^T / dp^T are computed once per tile instead of once per kernel.
    """
    import jax.experimental.pallas as pl

    j = pl.program_id(1)   # k block (outer)
    i = pl.program_id(2)   # q block (inner, accumulated for dk/dv)
    n_q = pl.num_programs(2)

    @pl.when((j == 0) & (i == 0))
    def _init_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _compute():
        ds_t = _dkv_tile_step(q_ref, k_ref, v_ref, do_ref, lse_ref,
                              delta_ref, dk_scr, dv_scr, i, j, block_q,
                              block_k, scale, causal, seq_len)
        # dq[i] += ds[i,j] @ K[j]  ==  ds_t^T @ K  (contract sublanes)
        rows = pl.ds(i * block_q, block_q)
        dq_ref[0, rows, :] += lax.dot_general(
            ds_t.astype(k_ref.dtype), k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (block_q, d)

    live = _live_tile(i, j, block_q, block_k, causal, seq_len)
    if live is None:
        _compute()
    else:
        pl.when(live)(_compute)

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# The fused kernel keeps a (T, D) f32 dq buffer plus score-shaped
# (block_k, block_q) f32 tiles in VMEM; past this many bytes of dq the
# dispatcher falls back to the two-kernel path (whose VMEM use is
# O(block^2) only), which covers arbitrarily long sequences.  The dq
# gate alone ignores the block-dependent tile term, so the fused path
# is additionally clamped to tiles no larger than the measured-working
# _auto_block maximum (1024x1024, benched at T=8192/D=128) — explicit
# larger blocks take the two-kernel path instead of risking VMEM
# exhaustion near the dq boundary.
_FUSED_DQ_VMEM_BYTES = 4 * 1024 * 1024
_FUSED_MAX_TILE = 1024 * 1024


def _use_fused_bwd(T, D, block_q, block_k):
    return (T * D * 4 <= _FUSED_DQ_VMEM_BYTES
            and block_q * block_k <= _FUSED_MAX_TILE)


def _reduce_kv_partials(partials, group, out_dtype):
    """Per-q-head dk/dv contributions -> per-kv-head grads.

    GQA backward writes one (T, D) partial per q head (same as the
    repeat-KV formulation would); consecutive q heads in a group share
    a kv head, so the reduction is a contiguous reshape-sum — the same
    math XLA's autodiff of jnp.repeat performs, without the repeated
    K/V ever existing in HBM on the forward/operand side.
    """
    if group == 1:
        return partials.astype(out_dtype)
    BH, T, D = partials.shape
    return (partials.reshape(BH // group, group, T, D)
            .astype(jnp.float32).sum(axis=1).astype(out_dtype))


def _flash_bwd_fused(q, k, v, g, lse, delta, scale, causal,
                     block_q, block_k, interpret, seq_len=None):
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    from pytorch_operator_tpu.utils.jax_compat import tpu_compiler_params

    BH, T, D = q.shape
    group = BH // k.shape[0]
    n_q, n_k = T // block_q, T // block_k
    qT_spec = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kT_spec = pl.BlockSpec((1, block_k, D),
                           lambda b, j, i: (b // group, j, 0),
                           memory_space=pltpu.VMEM)
    rowT_spec = pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i),
                             memory_space=pltpu.VMEM)
    dq32, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, block_q=block_q,
                          block_k=block_k, scale=scale, causal=causal,
                          seq_len=seq_len),
        grid=(BH, n_k, n_q),
        in_specs=[qT_spec, kT_spec, kT_spec, qT_spec, rowT_spec, rowT_spec],
        out_specs=[
            pl.BlockSpec((1, T, D), lambda b, j, i: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), jnp.float32),
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=interpret,
        name="flash_bwd_fused",
    )(q, k, v, g, lse, delta)
    return (dq32.astype(q.dtype),
            _reduce_kv_partials(dk, group, k.dtype),
            _reduce_kv_partials(dv, group, v.dtype))


def _flash_bwd(q, k, v, out, lse, g, scale, causal, block_q, block_k,
               interpret, g_lse=None, seq_len=None):
    """dq/dk/dv for upstream cotangents on out (``g``) and, optionally,
    on lse (``g_lse``, (BH, 1, T) f32).

    The lse cotangent folds into the existing kernels for free:
    ds = p*(dp - delta) picks up +p*g_lse (d lse_i/d s_ij = p_ij), which
    is exactly ds = p*(dp - (delta - g_lse)) — so shifting delta is the
    complete correction and no kernel changes.
    """
    import jax.experimental.pallas as pl
    import jax.experimental.pallas.tpu as pltpu

    from pytorch_operator_tpu.utils.jax_compat import tpu_compiler_params

    BH, T, D = q.shape
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]              # (BH, 1, T) f32
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    if _use_fused_bwd(T, D, block_q, block_k):
        return _flash_bwd_fused(q, k, v, g, lse, delta, scale, causal,
                                block_q, block_k, interpret, seq_len)
    group = BH // k.shape[0]
    n_q, n_k = T // block_q, T // block_k

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                          memory_space=pltpu.VMEM)
    k_spec = pl.BlockSpec((1, block_k, D),
                          lambda b, i, j: (b // group, j, 0),
                          memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i),
                            memory_space=pltpu.VMEM)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal, seq_len=seq_len),
        grid=(BH, n_q, n_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((BH, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
        name="flash_bwd_dq",
    )(q, k, v, g, lse, delta)

    # dkv grid walks (b, k-block, q-block): q is the accumulated inner dim
    qT_spec = pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0),
                           memory_space=pltpu.VMEM)
    kT_spec = pl.BlockSpec((1, block_k, D),
                           lambda b, j, i: (b // group, j, 0),
                           memory_space=pltpu.VMEM)
    rowT_spec = pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i),
                             memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                          scale=scale, causal=causal, seq_len=seq_len),
        grid=(BH, n_k, n_q),
        in_specs=[qT_spec, kT_spec, kT_spec, qT_spec, rowT_spec, rowT_spec],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, T, D), k.dtype),
            jax.ShapeDtypeStruct((BH, T, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
        name="flash_bwd_dkv",
    )(q, k, v, g, lse, delta)
    return (dq, _reduce_kv_partials(dk, group, k.dtype),
            _reduce_kv_partials(dv, group, v.dtype))


# checkpoint_name tags for the attention-preserving remat policy: under
# jax.checkpoint(policy=save_only_these_names(*FLASH_SAVE_NAMES)) the
# saved (out, lse) pair is exactly the flash vjp's kernel-derived
# residuals, so the remat backward recomputes only the cheap q/k/v
# projections while the O(T^2) forward kernel is dead-code-eliminated
# from the recompute.  The names are applied INSIDE the vjp forward and
# the NAMED values are returned as both primal outputs and residuals —
# that identity is what lets partial-eval mark the pallas call dead.
FLASH_SAVE_NAMES = ("flash_attn_out", "flash_attn_lse")


def _named_fwd(q, k, v, scale, causal, block_q, block_k, interpret, seq_len):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k,
                          interpret, seq_len)
    return (checkpoint_name(out, FLASH_SAVE_NAMES[0]),
            checkpoint_name(lse, FLASH_SAVE_NAMES[1]))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret,
           seq_len=None):
    out, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                        seq_len)
    return out


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                   seq_len=None):
    out, lse = _named_fwd(q, k, v, scale, causal, block_q, block_k,
                          interpret, seq_len)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, seq_len,
                   res, g):
    q, k, v, out, lse = res
    return _flash_bwd(q, k, v, out, lse, g, scale, causal,
                      block_q, block_k, interpret, seq_len=seq_len)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_with_lse(q, k, v, scale, causal, block_q, block_k, interpret,
                   seq_len=None):
    """Differentiable (out, lse) pair over (BH, T, D) inputs.

    For consumers that combine partial attention results across chunks
    (ring attention's online-softmax merge): both outputs carry
    cotangents, and the backward routes the lse cotangent through the
    delta shift in _flash_bwd.  ``seq_len`` (static) enables the padded
    -tail mask when the caller padded T up to a block multiple.
    """
    return _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                      seq_len)


def _flash_lse_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret,
                       seq_len=None):
    out, lse = _named_fwd(q, k, v, scale, causal, block_q, block_k,
                          interpret, seq_len)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_vjp_bwd(scale, causal, block_q, block_k, interpret, seq_len,
                       res, gs):
    q, k, v, out, lse = res
    g_out, g_lse = gs
    return _flash_bwd(q, k, v, out, lse, g_out, scale, causal,
                      block_q, block_k, interpret, g_lse=g_lse,
                      seq_len=seq_len)


flash_with_lse.defvjp(_flash_lse_vjp_fwd, _flash_lse_vjp_bwd)


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _auto_block(T: int, D: int) -> int:
    """Preferred block size for sequence length T (any T >= 1).

    Measured on TPU v5e (H16/D128, fwd+bwd with the fused backward,
    scan-chained timing): 1024-blocks are 3-4x faster than the naive
    256x256 tiling at T>=4096 — a small tile is only a few MFLOP, so
    per-grid-cell overhead dominates; at 1024 each cell does ~270
    MFLOP.  At T=1024 the whole grid is tiny and a 512 block wins
    (0.50ms vs 0.73ms fwd+bwd) — enough cells to pipeline beats
    per-cell size.  The cap drops to 512 for D > 128 because the
    backward's three (block_k, block_q) f32 score tiles plus the
    operand tiles approach the ~16MB VMEM at 1024.

    When T is not a block multiple the caller pads the tail (masked
    in-kernel); a short non-multiple T rounds up to a single
    lane-aligned tile so the pad waste stays below one 128-lane row.
    """
    cap = 1024 if D <= 128 else 512
    if T <= 1024:
        cap = min(cap, 512)
    for b in (cap, 512, 256, 128):
        if b <= T and T % b == 0:
            return b
    if T < cap:
        return _round_up(T, 128)
    # Non-multiple T above the cap: the caller pads to the block
    # multiple, and live tail tiles compute at full block size — with
    # the cap block, T just past a multiple (e.g. 1030) would pad to
    # 2048 and run ~2x the useful tokens.  Take the largest preferred
    # block whose pad stays <= T/8; 128 bounds the absolute waste at
    # <128 rows, so relative pad overhead shrinks as T grows.
    for b in (cap, 512, 256, 128):
        if (_round_up(T, b) - T) * 8 <= T:
            return b
    return 128


def _exact_block(T: int, D: int) -> int | None:
    """Largest preferred block that tiles T exactly, or None.

    For callers that cannot pad-and-slice (ring attention's per-device
    chunks, where padding would corrupt the cross-chunk online-softmax
    merge): None means "use a dense chunk path"; flash_attention itself
    never needs this — it pads the tail instead."""
    b = _auto_block(T, D)
    return b if T >= b and T % b == 0 else None


# Forward-only crossover: at T <= 1024 the whole (T, T) score tile fits
# XLA's fused softmax pipeline and dense wins the pure forward (measured
# 0.72x flash/dense at T=1024 — BENCH_DETAIL §2), while flash keeps the
# training (fwd+bwd) edge from T~1024 up.  flash_attention auto-routes
# below this: dense when only the forward runs, flash when the call is
# differentiated (jax.custom_vjp picks the path — no caller knobs).
_DENSE_FWD_MAX_T = 1024


def _dense_path(q, k, v, scale, causal):
    """Dense XLA attention on public-layout (B, T, H, D) tensors with
    local GQA repeat — the short-sequence forward path and the A/B side
    of the perf guards."""
    B, T, H, D = q.shape
    Hk = k.shape[2]
    if Hk != H:
        k = jnp.repeat(k, H // Hk, axis=2)
        v = jnp.repeat(v, H // Hk, axis=2)

    def bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)

    out = _dense_reference(bh(q), bh(k), bh(v), scale, causal)
    return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _route_small_t(q, k, v, scale, causal, block, interpret):
    """Short-T dispatcher: dense forward for inference, Pallas flash
    when the call is differentiated.

    jax.custom_vjp makes the choice structural: an un-differentiated
    trace runs the primal (dense — the measured forward winner below
    _DENSE_FWD_MAX_T), while jax.grad/vjp replaces it with the fwd
    rule, which defers to the full flash path (O(T) memory backward,
    save_attn residual names, GQA streaming — everything the training
    path guarantees).  The rms_norm dispatcher pattern, extended to
    differentiate inference from training (round-5 verdict item 4).
    """

    @jax.custom_vjp
    def route(q, k, v):
        return _dense_path(q, k, v, scale, causal)

    def route_fwd(q, k, v):
        out, vjp_fn = jax.vjp(
            lambda a, b, c: flash_attention(
                a, b, c, causal=causal, block_q=block, block_k=block,
                interpret=interpret),
            q, k, v)
        return out, vjp_fn

    def route_bwd(vjp_fn, g):
        return vjp_fn(g)

    route.defvjp(route_fwd, route_bwd)
    return route(q, k, v)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool | None = None,
) -> jax.Array:
    """Causal attention over (B, T, H, D) queries — any T >= 1.

    GQA-native: k/v may carry H_kv <= H heads (H % H_kv == 0) — the
    kernels stream the shared K/V blocks directly (no repeated K/V is
    ever materialised; dk/dv come back at H_kv heads).  Every length
    takes the Pallas path when training: when T is not a block multiple
    the inputs are zero-padded to the next multiple and the kernels
    mask the padded key positions (see module docstring), so
    long-context training works at arbitrary T, not just block
    multiples.  Block sizes default to the measured-fastest tiling for
    the shape (see _auto_block).

    Short-sequence dispatch: with default blocks and T <=
    _DENSE_FWD_MAX_T, a forward-only (inference) call runs dense XLA —
    the measured winner there — while a differentiated call still runs
    the flash kernels; see _route_small_t.  Explicit block args pin the
    path either way (block 0 = dense)."""
    B, T, H, D = q.shape
    Hk = k.shape[2]
    if v.shape[2] != Hk or H % Hk:
        raise ValueError(
            f"kv heads must divide q heads: q has {H}, k/v have "
            f"{k.shape[2]}/{v.shape[2]}")
    scale = D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_q is None and block_k is None and T <= _DENSE_FWD_MAX_T:
        return _route_small_t(q, k, v, scale, causal,
                              _auto_block(T, D), interpret)
    if block_q is None:
        block_q = _auto_block(T, D)
    if block_k is None:
        block_k = _auto_block(T, D)
    if not block_q or not block_k:
        # explicit dense escape (block 0): the A/B side of the perf
        # guards and a manual pin for callers that want dense always
        return _dense_path(q, k, v, scale, causal)
    T_pad = _round_up(T, math.lcm(block_q, block_k))

    def to_bh(x):
        h = x.shape[2]
        bh = x.transpose(0, 2, 1, 3).reshape(B * h, T, D)
        if T_pad != T:
            bh = jnp.pad(bh, ((0, 0), (0, T_pad - T), (0, 0)))
        return bh

    def from_bh(x):
        return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    out = _flash(to_bh(q), to_bh(k), to_bh(v), scale, causal,
                 block_q, block_k, interpret,
                 T if T_pad != T else None)
    if T_pad != T:
        out = out[:, :T]
    return from_bh(out)
