"""JAX platform-selection plumbing for example workloads.

Some images register a PJRT plugin from sitecustomize and pin
``jax_platforms`` at import time, which silently overrides the
JAX_PLATFORMS environment variable a job manifest sets (e.g. the CPU
variant of the mnist example).  Calling :func:`apply_platform_env` right
after ``import jax`` makes the env var authoritative again.
"""

from __future__ import annotations

import os


def apply_platform_env() -> None:
    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import sys

    import jax

    try:
        jax.config.update("jax_platforms", plat)
    except Exception as e:
        print(f"[jaxenv] could not set jax_platforms={plat!r}: {e}",
              file=sys.stderr)
    backend = jax.default_backend()
    want = plat.split(",")[0]
    if backend == want:
        return
    # A PJRT plugin's canonical backend name can differ from its platform
    # name (e.g. a tunnelled TPU plugin registering as platform "axon"
    # reports backend "tpu").  Mere enumerability of the requested
    # platform is NOT enough (on an image whose sitecustomize already
    # initialised another backend, jax.devices(want) can succeed while
    # computations default elsewhere): the requested platform's devices
    # must BE the default devices.
    try:
        if jax.devices(want) == jax.devices():
            return
    except RuntimeError:
        pass
    raise RuntimeError(
        f"JAX_PLATFORMS={plat!r} requested but backend initialised as "
        f"{backend!r} — the job would silently run on the wrong platform"
    )
