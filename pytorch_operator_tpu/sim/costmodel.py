"""Sim-consumable reconcile-cost model: load the committed cost-profile
artifact and draw latencies from it.

ROADMAP direction 3 wants the simulator's cost model "sampled from the
real per-reconcile histograms" instead of hand-tuned constants.  The
real histograms are exactly what the fleet collector
(runtime/fleetview.py) serializes into the committed JSON artifact
(BENCH_RECONCILE_COST.json, written by the ``--fleetview`` bench tier);
this module is the consuming side:

  * :func:`load_cost_profile` — parse + validate the artifact (schema
    version, family layout, cumulative-bucket sanity) into a
    :class:`CostModel`;
  * :meth:`CostModel.sample` — one latency draw via inverse-CDF over
    the histogram buckets (uniform within the landed bucket), driven
    by a CALLER-SEEDED ``random.Random`` so sim runs stay
    deterministic;
  * :meth:`CostModel.mean` — the closed-form expectation (sum/count),
    for calibration printouts and tests.

The artifact's buckets are Prometheus-cumulative with string ``le``
bounds ("+Inf" included), exactly as scraped — this loader, not the
exporter, owns the conversion to per-bucket mass.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

EXPECTED_VERSION = 1


class CostModel:
    """Histogram-backed latency distributions, keyed by
    (family, labelset)."""

    def __init__(self, profile: dict):
        self.version = profile.get("version")
        self._series: Dict[str, List[dict]] = {}
        for family, body in (profile.get("families") or {}).items():
            self._series[family] = list(body.get("series") or [])

    @property
    def families(self) -> List[str]:
        return sorted(self._series)

    def series(self, family: str, **labels) -> Optional[dict]:
        """The series whose labels are a superset match of ``labels``
        (empty ``labels`` returns the first series of the family)."""
        for series in self._series.get(family, ()):
            if all(series.get("labels", {}).get(k) == v
                   for k, v in labels.items()):
                return series
        return None

    def mean(self, family: str, **labels) -> Optional[float]:
        series = self.series(family, **labels)
        if series is None or not series.get("count"):
            return None
        return series["sum"] / series["count"]

    def sample(self, family: str, rng, **labels) -> Optional[float]:
        """One inverse-CDF draw from the family's histogram: pick the
        bucket a uniform quantile lands in, then interpolate uniformly
        within its bounds.  The +Inf bucket falls back to the series
        mean clamped at the last finite bound (a tail draw must not
        invent an unbounded latency).  ``rng`` is the caller's seeded
        ``random.Random`` — determinism stays with the caller."""
        series = self.series(family, **labels)
        if series is None:
            return None
        masses = _bucket_masses(series)
        total = sum(m for _, _, m in masses)
        if total <= 0:
            return None
        target = rng.random() * total
        acc = 0.0
        last_finite = 0.0
        for lo, hi, mass in masses:
            if hi is not None:
                last_finite = hi
            acc += mass
            if target <= acc and mass > 0:
                if hi is None:  # +Inf bucket
                    mean = self.mean(family, **labels) or last_finite
                    return max(last_finite, mean)
                return lo + rng.random() * (hi - lo)
        return last_finite

    def to_dict(self) -> dict:
        return {"version": self.version,
                "families": {f: {"series": s}
                             for f, s in self._series.items()}}


def _bucket_masses(series: dict):
    """Cumulative wire buckets -> [(lo, hi_or_None, mass)]; hi None is
    the +Inf bucket."""
    out = []
    prev_cum = 0.0
    prev_bound = 0.0
    for le, cum in series.get("buckets") or []:
        bound = None if le in ("+Inf", "inf", "Inf") else float(le)
        mass = max(0.0, float(cum) - prev_cum)
        out.append((prev_bound, bound, mass))
        prev_cum = float(cum)
        if bound is not None:
            prev_bound = bound
    return out


def load_cost_profile(path: str) -> CostModel:
    """Read + validate the committed artifact.  Raises ValueError on a
    schema the sim can't safely consume (wrong version, non-cumulative
    buckets, malformed series) — a silently-misread cost model would
    skew every sim result downstream."""
    with open(path) as f:
        profile = json.load(f)
    if not isinstance(profile, dict):
        raise ValueError("cost profile must be a JSON object")
    if profile.get("version") != EXPECTED_VERSION:
        raise ValueError(
            f"cost profile version {profile.get('version')!r} != "
            f"expected {EXPECTED_VERSION}")
    families = profile.get("families")
    if not isinstance(families, dict) or not families:
        raise ValueError("cost profile needs a non-empty 'families' map")
    for family, body in families.items():
        series_list = (body or {}).get("series")
        if not isinstance(series_list, list):
            raise ValueError(f"family {family!r} needs a 'series' list")
        for series in series_list:
            if not isinstance(series.get("labels"), dict):
                raise ValueError(f"series in {family!r} needs 'labels'")
            buckets = series.get("buckets")
            if not isinstance(buckets, list) or not buckets:
                raise ValueError(f"series in {family!r} needs buckets")
            prev = 0.0
            for item in buckets:
                if (not isinstance(item, (list, tuple))
                        or len(item) != 2):
                    raise ValueError(
                        f"bucket in {family!r} must be [le, count]")
                cum = float(item[1])
                if cum < prev:
                    raise ValueError(
                        f"buckets in {family!r} must be cumulative")
                prev = cum
    return CostModel(profile)
