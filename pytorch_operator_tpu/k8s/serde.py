"""Dataclass <-> Kubernetes-style JSON object conversion.

The reference operator relies on k8s.io/apimachinery generated code
(``zz_generated.deepcopy.go``, swagger models) to move between typed Go
structs and the JSON wire format.  This module is the first-party
equivalent: a small reflection layer that maps ``snake_case`` dataclass
fields to ``camelCase`` JSON keys, recursing through ``Optional``,
``List``, ``Dict`` and nested dataclasses.

Conventions (matching Kubernetes marshalling):
  * ``None`` values and empty containers are omitted on output.
  * Unknown keys on input are ignored (forward compatibility).
  * A field may override its wire name via
    ``field(metadata={"k8s": "wireName"})``.
"""

from __future__ import annotations

import copy
import dataclasses
import typing
from typing import Any, Optional, Type, TypeVar, Union, get_args, get_origin, get_type_hints

T = TypeVar("T")

_HINTS_CACHE: dict[type, dict[str, Any]] = {}


def camel_case(snake: str) -> str:
    parts = snake.split("_")
    return parts[0] + "".join(p.title() for p in parts[1:])


def _wire_name(f: dataclasses.Field) -> str:
    return f.metadata.get("k8s", camel_case(f.name))


def _hints(cls: type) -> dict[str, Any]:
    hints = _HINTS_CACHE.get(cls)
    if hints is None:
        hints = get_type_hints(cls)
        _HINTS_CACHE[cls] = hints
    return hints


def _unwrap_optional(tp: Any) -> Any:
    if get_origin(tp) is Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            return args[0]
    return tp


def _is_optional(tp: Any) -> bool:
    return get_origin(tp) is Union and type(None) in get_args(tp)


def _encode_value(v: Any) -> Any:
    if dataclasses.is_dataclass(v):
        return to_dict(v)
    if isinstance(v, dict):
        return {k: _encode_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_encode_value(x) for x in v]
    return v


def to_dict(obj: Any) -> dict:
    """Serialize a dataclass to a camelCase JSON-ready dict."""
    out: dict[str, Any] = {}
    for f in dataclasses.fields(obj):
        v = getattr(obj, f.name)
        if v is None:
            continue
        encoded = _encode_value(v)
        # Go-style omitempty: drop empty strings/lists/dicts (and nested
        # dataclasses that serialized to nothing); keep 0 and False.
        if encoded == "" or (isinstance(encoded, (list, dict)) and not encoded):
            continue
        out[_wire_name(f)] = encoded
    return out


def _decode_value(tp: Any, v: Any) -> Any:
    tp = _unwrap_optional(tp)
    if v is None:
        return None
    if dataclasses.is_dataclass(tp) and isinstance(tp, type):
        if not isinstance(v, dict):
            return v
        return from_dict(tp, v)
    origin = get_origin(tp)
    if origin in (list, tuple) and isinstance(v, list):
        (elem,) = get_args(tp) or (Any,)
        return [_decode_value(elem, x) for x in v]
    if origin is dict and isinstance(v, dict):
        args = get_args(tp)
        elem = args[1] if len(args) == 2 else Any
        return {k: _decode_value(elem, x) for k, x in v.items()}
    return v


def from_dict(cls: Type[T], data: Optional[dict]) -> T:
    """Deserialize a camelCase dict into dataclass ``cls``.

    Unknown keys are ignored; missing keys fall back to field defaults.
    """
    if data is None:
        data = {}
    hints = _hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        wire = _wire_name(f)
        if wire in data:
            value = data[wire]
            if value is None and not _is_optional(hints[f.name]):
                # Explicit JSON null on a non-Optional field: keep the
                # field default rather than violating the type contract.
                continue
            kwargs[f.name] = _decode_value(hints[f.name], value)
    return cls(**kwargs)


def deep_copy(obj: T) -> T:
    """Equivalent of the generated DeepCopy methods."""
    return copy.deepcopy(obj)
