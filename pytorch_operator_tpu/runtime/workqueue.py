"""Rate-limited delaying workqueue with client-go semantics.

First-party equivalent of k8s.io/client-go/util/workqueue as used by the
reference (vendor/.../jobcontroller/jobcontroller.go:110-131): the queue
guarantees an item is never processed by two workers simultaneously
(dirty/processing sets), supports delayed re-adds (AddAfter) and
per-item exponential backoff (AddRateLimited / Forget / NumRequeues).
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class RateLimiter:
    """Per-item exponential backoff: base * 2^failures, capped.

    Matches client-go's ItemExponentialFailureRateLimiter defaults
    (5ms base, 1000s cap).
    """

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0):
        self.base_delay = base_delay
        self.max_delay = max_delay
        self._failures: Dict[Any, int] = {}
        self._lock = threading.Lock()

    def when(self, item: Any) -> float:
        with self._lock:
            n = self._failures.get(item, 0)
            self._failures[item] = n + 1
        return min(self.base_delay * (2 ** n), self.max_delay)

    def forget(self, item: Any) -> None:
        with self._lock:
            self._failures.pop(item, None)

    def num_requeues(self, item: Any) -> int:
        with self._lock:
            return self._failures.get(item, 0)


class WorkQueue:
    """Deduplicating FIFO queue with processing-exclusion semantics."""

    def __init__(self, rate_limiter: Optional[RateLimiter] = None):
        self._lock = threading.Condition()
        self._queue: List[Any] = []
        self._dirty: set = set()
        self._processing: set = set()
        self._shutdown = False
        # (ready_at, seq, item, is_retry) — is_retry marks entries from
        # add_rate_limited, which are cancellable (see _pending_retry);
        # plain add_after entries (deadline/TTL timers) never are.
        self._waiting: List[Tuple[float, int, Any, bool]] = []
        self._seq = 0
        # item -> seq of its single live retry entry; a heap entry whose
        # seq no longer matches was superseded by a newer retry or
        # cancelled by forget() and is dropped on drain
        self._pending_retry: Dict[Any, int] = {}
        self.rate_limiter = rate_limiter or RateLimiter()

    # -- core queue --------------------------------------------------------
    def add(self, item: Any) -> None:
        with self._lock:
            if self._shutdown or item in self._dirty:
                return
            self._dirty.add(item)
            if item in self._processing:
                return
            self._queue.append(item)
            self._lock.notify()

    def get(self, timeout: Optional[float] = None) -> Tuple[Any, bool]:
        """Pop the next item. Returns (item, shutdown)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                self._drain_ready_locked()
                if self._queue:
                    item = self._queue.pop(0)
                    self._processing.add(item)
                    self._dirty.discard(item)
                    return item, False
                if self._shutdown:
                    return None, True
                wait = self._next_wait_locked(deadline)
                if wait is not None and wait <= 0:
                    if deadline is not None and time.monotonic() >= deadline:
                        return None, False
                    continue
                if not self._lock.wait(timeout=wait):
                    if deadline is not None and time.monotonic() >= deadline:
                        return None, False

    def _next_wait_locked(self, deadline: Optional[float]) -> Optional[float]:
        candidates = []
        if self._waiting:
            candidates.append(self._waiting[0][0] - time.monotonic())
        if deadline is not None:
            candidates.append(deadline - time.monotonic())
        return min(candidates) if candidates else None

    def _drain_ready_locked(self) -> None:
        now = time.monotonic()
        while self._waiting and self._waiting[0][0] <= now:
            _, seq, item, is_retry = heapq.heappop(self._waiting)
            if is_retry:
                if self._pending_retry.get(item) != seq:
                    continue  # superseded by a newer retry or forget()
                del self._pending_retry[item]
            # Same dedupe semantics as add().
            if item in self._dirty:
                continue
            self._dirty.add(item)
            if item not in self._processing:
                self._queue.append(item)

    def done(self, item: Any) -> None:
        with self._lock:
            self._processing.discard(item)
            if item in self._dirty:
                self._queue.append(item)
                self._lock.notify()

    def shutdown(self) -> None:
        with self._lock:
            self._shutdown = True
            self._lock.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._queue)

    def is_dirty(self, item: Any) -> bool:
        """True while the item awaits (re)processing — queued, or re-added
        during processing.  The informer's burst coalescing keys off this:
        a MODIFIED event for a dirty key updates the store but skips the
        redundant handler dispatch (the pending sync reads the fresh
        store anyway)."""
        with self._lock:
            return item in self._dirty

    # -- delayed / rate-limited adds ---------------------------------------
    def add_after(self, item: Any, delay: float) -> None:
        if delay <= 0:
            self.add(item)
            return
        with self._lock:
            if self._shutdown:
                return
            self._seq += 1
            heapq.heappush(
                self._waiting,
                (time.monotonic() + delay, self._seq, item, False))
            self._lock.notify()

    def add_rate_limited(self, item: Any) -> None:
        """Schedule a backoff retry.  At most ONE live retry per item:
        a retry for a key that is already dirty (queued or re-added) is
        dropped — the imminent processing supersedes it, and a failure
        there re-schedules with the next backoff — and a newer retry
        replaces any pending one.  Without this, a rate-limited requeue
        plus a live watch event could double-process one key after the
        first done()."""
        delay = self.rate_limiter.when(item)
        with self._lock:
            if self._shutdown:
                return
            if item in self._dirty:
                return
            self._seq += 1
            self._pending_retry[item] = self._seq
            heapq.heappush(
                self._waiting,
                (time.monotonic() + delay, self._seq, item, True))
            self._lock.notify()

    def forget(self, item: Any) -> None:
        """Reset backoff AND cancel the item's pending retry, if any —
        forget() runs after a successful sync, which makes a scheduled
        retry pure double-processing.  Plain add_after entries (deadline
        timers) are never cancelled."""
        with self._lock:
            self._pending_retry.pop(item, None)
        self.rate_limiter.forget(item)

    def num_requeues(self, item: Any) -> int:
        return self.rate_limiter.num_requeues(item)
