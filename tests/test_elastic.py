"""Elastic gangs: checkpoint-drain-resize instead of delete-recreate
(ISSUE 6).

Acceptance (control plane): an elastic 8-worker job under a
CapacityFlap shrinks to 6 via drain — the doomed pods checkpoint before
deletion — keeps reconciling with its rendezvous re-rendered, grows
back to 8 when the nodes return, and reaches ``Succeeded`` with zero
duplicate creates and exactly one ``Resizing`` transition per capacity
change; non-elastic jobs keep the PR 2 full-restart behavior.

Acceptance (data plane): params checkpointed on a 4-device virtual CPU
mesh restore onto a 2-device mesh (and back) numerically identical, and
the llama example resumes training at the new world size.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import threading

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.api.v1.defaults import set_defaults
from pytorch_operator_tpu.api.v1.types import ElasticPolicy, PyTorchJob
from pytorch_operator_tpu.api.v1.validation import (
    ValidationError,
    validate_spec,
)
from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.controller.tpu_env import (
    elastic_rendezvous_annotations,
)
from pytorch_operator_tpu.disruption import CapacityFlap, CapacityWatcher
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet, new_tpu_node
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import (
    FakePodControl,
    FakeServiceControl,
    Informer,
    JobControllerConfig,
)
from pytorch_operator_tpu.runtime.expectations import (
    expectation_pods_key,
    expectation_services_key,
)

from testutil import job_condition, new_job, wait_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def elastic_job(name="elastic-job", workers=8, min_replicas=4,
                max_replicas=None) -> PyTorchJob:
    job = new_job(workers=workers, name=name, tpu_chips=4)
    job.spec.elastic_policy = ElasticPolicy(
        min_replicas=min_replicas, max_replicas=max_replicas or workers)
    set_defaults(job)
    return job


# ---------------------------------------------------------------------------
# API layer
# ---------------------------------------------------------------------------


class TestElasticPolicyApi:
    def test_valid_policy_passes(self):
        validate_spec(elastic_job().spec)

    def test_policy_requires_workers(self):
        job = new_job(workers=0, name="no-workers", tpu_chips=4)
        job.spec.elastic_policy = ElasticPolicy(min_replicas=1,
                                                max_replicas=2)
        with pytest.raises(ValidationError, match="Worker"):
            validate_spec(job.spec)

    @pytest.mark.parametrize("min_r,max_r,needle", [
        (0, 4, "minReplicas"),
        (-1, 4, "minReplicas"),
        (1, 0, "maxReplicas"),
        (6, 4, "exceeds maxReplicas"),
        # bools pass isinstance(int) — a YAML `minReplicas: true` must
        # not silently become a floor of 1
        (True, 4, "minReplicas"),
        (2, True, "maxReplicas"),
    ])
    def test_bad_bounds_rejected(self, min_r, max_r, needle):
        job = elastic_job(workers=4, min_replicas=4)
        job.spec.elastic_policy = ElasticPolicy(min_replicas=min_r,
                                                max_replicas=max_r)
        with pytest.raises(ValidationError, match=needle):
            validate_spec(job.spec)

    def test_configured_count_must_sit_inside_bounds(self):
        job = elastic_job(workers=2, min_replicas=4, max_replicas=8)
        with pytest.raises(ValidationError, match="below"):
            validate_spec(job.spec)
        job = elastic_job(workers=8, min_replicas=1, max_replicas=4)
        with pytest.raises(ValidationError, match="above"):
            validate_spec(job.spec)

    def test_wire_round_trip(self):
        job = elastic_job(min_replicas=3, max_replicas=8)
        job.status.desired_replicas = 6
        job.status.elastic_resizes = 2
        wire = job.to_dict()
        assert wire["spec"]["elasticPolicy"] == {
            "minReplicas": 3, "maxReplicas": 8}
        assert wire["status"]["desiredReplicas"] == 6
        assert wire["status"]["elasticResizes"] == 2
        back = PyTorchJob.from_dict(wire)
        assert back.spec.elastic_policy.min_replicas == 3
        assert back.status.desired_replicas == 6
        # an untouched non-elastic job serializes no elastic fields
        plain = new_job(workers=2, name="plain").to_dict()
        assert "elasticPolicy" not in plain["spec"]
        assert "desiredReplicas" not in plain.get("status", {})


class TestElasticAnnotations:
    def test_dense_ranks_across_index_holes(self):
        job = elastic_job(name="j", workers=8)
        pods = [_bound_pod("j-master-0", "j", "n0", rtype="master")]
        # survivors at indices 0,1,2,4,5,7 (3 and 6 drained)
        for i in (0, 1, 2, 4, 5, 7):
            pods.append(_bound_pod(f"j-worker-{i}", "j", f"n{i+1}",
                                   index=str(i)))
        anns = elastic_rendezvous_annotations(job, pods)
        ws = constants.ANNOTATION_ELASTIC_WORLD_SIZE
        rank = constants.ANNOTATION_ELASTIC_RANK
        hosts = constants.ANNOTATION_ELASTIC_HOSTNAMES
        assert anns["j-master-0"][rank] == "0"
        assert all(a[ws] == "7" for a in anns.values())
        # dense, index-ordered: worker-4 is rank 4 (after 0,1,2), not 5
        assert anns["j-worker-0"][rank] == "1"
        assert anns["j-worker-4"][rank] == "4"
        assert anns["j-worker-7"][rank] == "6"
        hostnames = anns["j-master-0"][hosts].split(",")
        assert hostnames[0] == "j-master-0"
        assert hostnames[4] == "j-worker-4"
        assert len(hostnames) == 7

    def test_master_absent_keeps_master_slot_in_world_size(self):
        # a master restart racing the render must not shrink WORLD_SIZE
        # to len(workers) while the hostname list still leads with the
        # master — ranks would fall out of range and the rendezvous hang
        job = elastic_job(name="j", workers=8)
        pods = [_bound_pod(f"j-worker-{i}", "j", f"n{i}", index=str(i))
                for i in (0, 1, 2)]
        anns = elastic_rendezvous_annotations(job, pods)
        ws = constants.ANNOTATION_ELASTIC_WORLD_SIZE
        hosts = constants.ANNOTATION_ELASTIC_HOSTNAMES
        assert all(a[ws] == "4" for a in anns.values())
        assert anns["j-worker-2"][constants.ANNOTATION_ELASTIC_RANK] == "3"
        hostnames = anns["j-worker-0"][hosts].split(",")
        assert hostnames[0] == "j-master-0"
        assert len(hostnames) == 4


# ---------------------------------------------------------------------------
# Handler units (drain / grow state machine)
# ---------------------------------------------------------------------------


def _bound_pod(name, job_name, node, rtype="worker", index="0",
               uid="test-uid-elastic-job", phase="Running"):
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {
            "name": name, "namespace": "default",
            "labels": {constants.LABEL_REPLICA_TYPE: rtype,
                       constants.LABEL_REPLICA_INDEX: index},
            "ownerReferences": [{
                "apiVersion": constants.API_VERSION, "kind": constants.KIND,
                "name": job_name, "uid": uid, "controller": True}],
        },
        "spec": {"nodeName": node,
                 "containers": [{"name": "pytorch", "image": "i"}]},
        "status": {"phase": phase},
    }


def _elastic_world(drain_deadline=10.0, max_resizes=3):
    cluster = FakeCluster()
    ctl = PyTorchController(
        cluster,
        config=JobControllerConfig(
            enable_disruption_handling=True,
            drain_deadline_seconds=drain_deadline,
            max_elastic_resizes=max_resizes),
        registry=Registry())
    ctl.pod_control = FakePodControl()
    ctl.service_control = FakeServiceControl()
    return cluster, ctl


def _gang_pods(cluster, job, nodes=None):
    """Create the job's gang in the fake cluster, one worker per node
    (master on its own node), and return the live pod dicts."""
    name = job.metadata.name
    uid = job.metadata.uid
    workers = int(job.spec.pytorch_replica_specs["Worker"].replicas or 0)
    pods = [_bound_pod(f"{name}-master-0", name, "node-m", rtype="master",
                       uid=uid)]
    for i in range(workers):
        node = nodes[i] if nodes else f"node-{i}"
        pods.append(_bound_pod(f"{name}-worker-{i}", name, node,
                               index=str(i), uid=uid))
    for pod in pods:
        cluster.pods.create("default", pod)
    return [cluster.pods.get("default", p["metadata"]["name"])
            for p in pods]


class TestDrainStateMachine:
    def test_shrink_signals_checkpoint_and_waits(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        # phase 1: nothing deleted yet, doomed pod signalled, status moved
        assert ctl.pod_control.delete_pod_names == []
        doomed = cluster.pods.get("default", "elastic-job-worker-3")
        anns = doomed["metadata"]["annotations"]
        assert constants.ANNOTATION_CHECKPOINT_REQUESTED in anns
        assert job.status.desired_replicas == 7
        assert job.status.elastic_resizes == 1
        conds = {c.type: c for c in job.status.conditions}
        assert conds[constants.JOB_RESIZING].status == "True"
        assert conds[constants.JOB_RESIZING].reason == \
            constants.RESIZE_SHRINK_REASON
        assert ctl.elastic_resizes_counter.labels(
            direction="shrink").value == 1
        # survivors untouched; no preemption-restart budget spent
        assert not job.status.preemption_restarts

    def test_ack_completes_drain_early(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        # not acked yet: the sync waits
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert ctl.pod_control.delete_pod_names == []
        # the pod acks -> the next sync deletes ONLY the doomed pod
        cluster.pods.patch("default", "elastic-job-worker-3",
                           {"metadata": {"annotations": {
                               constants.ANNOTATION_CHECKPOINTED: "now"}}})
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert ctl.pod_control.delete_pod_names == ["elastic-job-worker-3"]
        assert ctl.expectations.get(
            expectation_pods_key(job.key, "worker")).dels == 1
        assert ctl.elastic_drain_seconds.count == 1
        assert ctl.elastic_drain_timeouts_counter.value == 0

    def test_drain_reasserts_status_after_failed_write(self):
        # The intake sync's end-of-sync status write can fail AFTER the
        # drain note was armed: the requeued sync rebuilds the job from
        # the store at the PRE-shrink size.  The note must re-assert
        # the shrunken target/budget/condition onto that sync's status
        # — else the drain deletes the doomed pods while the store
        # never learns the target, and the next reconcile recreates
        # the very indices it just drained.
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        # the write failed: a FRESH job object plays the store's stale
        # status (no desiredReplicas, no Resizing condition, no budget)
        retry_job = elastic_job()
        cluster.pods.patch("default", "elastic-job-worker-3",
                           {"metadata": {"annotations": {
                               constants.ANNOTATION_CHECKPOINTED: "now"}}})
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(retry_job, retry_job.to_dict(),
                                          pods) is True
        assert ctl.pod_control.delete_pod_names == ["elastic-job-worker-3"]
        assert retry_job.status.desired_replicas == 7
        assert retry_job.status.elastic_resizes == 1
        conds = {c.type: c for c in retry_job.status.conditions}
        assert conds[constants.JOB_RESIZING].status == "True"
        assert conds[constants.JOB_RESIZING].reason == \
            constants.RESIZE_SHRINK_REASON
        # the shrink was still counted exactly once
        assert ctl.elastic_resizes_counter.labels(
            direction="shrink").value == 1

    def test_drain_deadline_deletes_unacked_pods(self):
        cluster, ctl = _elastic_world(drain_deadline=10.0)
        clock = [100.0]
        ctl._mono = lambda: clock[0]  # fake clock: no real sleeping
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-5",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert ctl.pod_control.delete_pod_names == []
        clock[0] += 10.1  # deadline passes, still no ack
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert ctl.pod_control.delete_pod_names == ["elastic-job-worker-5"]
        assert ctl.elastic_drain_timeouts_counter.value == 1

    def test_already_dead_doomed_pod_counts_as_acked(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        cluster.pods.set_status("default", "elastic-job-worker-2",
                                {"phase": "Failed"})
        ctl._note_node_disruption(job.key, "taint", "node-2",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        pods = cluster.pods.list("default")
        # dead pods can't checkpoint: the drain proceeds immediately
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert ctl.pod_control.delete_pod_names == ["elastic-job-worker-2"]

    def test_second_node_merges_into_inflight_drain(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        assert job.status.desired_replicas == 7
        # a second node dies mid-drain: SAME drain widens, budget and
        # the Resizing transition stay single
        ctl._note_node_disruption(job.key, "taint", "node-6",
                                  uid=job.metadata.uid)
        pods = cluster.pods.list("default")
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        assert job.status.desired_replicas == 6
        assert job.status.elastic_resizes == 1
        anns = cluster.pods.get(
            "default", "elastic-job-worker-6")["metadata"]["annotations"]
        assert constants.ANNOTATION_CHECKPOINT_REQUESTED in anns
        # both acked -> one batched delete of exactly the two doomed pods
        for name in ("elastic-job-worker-3", "elastic-job-worker-6"):
            cluster.pods.patch("default", name,
                               {"metadata": {"annotations": {
                                   constants.ANNOTATION_CHECKPOINTED: "t"}}})
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert sorted(ctl.pod_control.delete_pod_names) == [
            "elastic-job-worker-3", "elastic-job-worker-6"]

    def test_pod_scoped_signal_coalesces_into_pending_note(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        # an eviction marks a pod on ANOTHER node before the sync runs:
        # the coalesced note must doom BOTH, or the marked pod is
        # killed without ever being told to checkpoint
        ctl._note_disruption(job.key, "evict",
                             "pod/elastic-job-worker-5",
                             uid=job.metadata.uid,
                             pod="elastic-job-worker-5")
        assert ctl.preemptions_detected_counter.value == 1  # coalesced
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        assert job.status.desired_replicas == 6
        for name in ("elastic-job-worker-3", "elastic-job-worker-5"):
            anns = cluster.pods.get(
                "default", name)["metadata"]["annotations"]
            assert constants.ANNOTATION_CHECKPOINT_REQUESTED in anns

    def test_merge_extends_deadline_for_late_doomed_pods(self):
        cluster, ctl = _elastic_world(drain_deadline=10.0)
        clock = [0.0]
        ctl._mono = lambda: clock[0]
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        # a second node dies just before the original deadline: its
        # pods must get a FULL checkpoint window, not 0.1s
        clock[0] = 9.9
        ctl._note_node_disruption(job.key, "taint", "node-6",
                                  uid=job.metadata.uid)
        pods = cluster.pods.list("default")
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        clock[0] = 10.1  # past the ORIGINAL deadline
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert ctl.pod_control.delete_pod_names == []  # still draining
        clock[0] = 20.0  # past the extended deadline (9.9 + 10)
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is True
        assert sorted(ctl.pod_control.delete_pod_names) == [
            "elastic-job-worker-3", "elastic-job-worker-6"]

    def test_abandoned_drain_returns_budget_and_clears_condition(self):
        cluster, ctl = _elastic_world()
        job = elastic_job(workers=8, min_replicas=6)
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        assert job.status.elastic_resizes == 1
        # two more nodes die mid-drain: target would be 5 < min 6, the
        # shrink is abandoned for the legacy full restart — which must
        # NOT keep the budget slot or the True Resizing condition
        ctl._note_node_disruption(job.key, "taint", "node-0",
                                  uid=job.metadata.uid)
        ctl._note_node_disruption(job.key, "taint", "node-1",
                                  uid=job.metadata.uid)
        pods = cluster.pods.list("default")
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        assert len(ctl.pod_control.delete_pod_names) == 9  # full gang
        assert job.status.elastic_resizes == 0  # slot returned
        assert job.status.desired_replicas == 8
        from pytorch_operator_tpu.controller import status as sm

        cond = sm.get_condition(job.status, constants.JOB_RESIZING)
        assert cond.status == "False"
        assert cond.reason == constants.RESIZE_ABANDONED_REASON
        assert job.status.preemption_restarts == 1

    def test_intake_coalesces_second_node_into_pending_note(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-1",
                                  uid=job.metadata.uid)
        ctl._note_node_disruption(job.key, "taint", "node-4",
                                  uid=job.metadata.uid)
        assert ctl.preemptions_detected_counter.value == 1  # coalesced
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        # BOTH nodes' workers are in the doomed set of the one drain
        assert job.status.desired_replicas == 6
        for name in ("elastic-job-worker-1", "elastic-job-worker-4"):
            anns = cluster.pods.get(
                "default", name)["metadata"]["annotations"]
            assert constants.ANNOTATION_CHECKPOINT_REQUESTED in anns


class TestElasticFallbacks:
    def test_below_min_replicas_falls_back_to_full_restart(self):
        cluster, ctl = _elastic_world()
        job = elastic_job(workers=4, min_replicas=4)
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-0",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        # the legacy path fired: whole gang deleted, TPUPreempted set
        assert len(ctl.pod_control.delete_pod_names) == 5
        assert job.status.preemption_restarts == 1
        assert not job.status.elastic_resizes
        conds = {c.type: c for c in job.status.conditions}
        assert conds[constants.JOB_RESTARTING].reason == \
            constants.TPU_PREEMPTED_REASON

    def test_master_doomed_falls_back_to_full_restart(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-m",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        assert len(ctl.pod_control.delete_pod_names) == 9
        assert job.status.preemption_restarts == 1

    def test_resize_budget_exhausted_falls_back(self):
        cluster, ctl = _elastic_world(max_resizes=1)
        job = elastic_job()
        job.status.elastic_resizes = 1
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        assert len(ctl.pod_control.delete_pod_names) == 9
        reasons = {e["reason"] for e in cluster.events.list()}
        assert constants.ELASTIC_RESIZES_EXHAUSTED_REASON in reasons

    def test_annotation_overrides_resize_budget(self):
        cluster, ctl = _elastic_world(max_resizes=1)
        job = elastic_job()
        job.metadata.annotations[
            constants.ANNOTATION_MAX_ELASTIC_RESIZES] = "5"
        job.status.elastic_resizes = 3
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods)
        assert job.status.elastic_resizes == 4
        assert ctl.pod_control.delete_pod_names == []  # draining, not killing

    def test_unscoped_note_falls_back(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        pods = _gang_pods(cluster, job)
        ctl._note_disruption(job.key, "taint", "node/n1")  # no node scope
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        assert len(ctl.pod_control.delete_pod_names) == 9

    def test_non_elastic_job_never_enters_elastic_path(self):
        cluster, ctl = _elastic_world()
        job = new_job(workers=8, name="plain-gang", tpu_chips=4)
        set_defaults(job)
        pods = _gang_pods(cluster, job)
        ctl._note_node_disruption(job.key, "taint", "node-3",
                                  uid=job.metadata.uid)
        assert ctl.maybe_handle_disruption(job, job.to_dict(), pods) is True
        assert len(ctl.pod_control.delete_pod_names) == 9
        assert job.status.desired_replicas is None
        assert not job.status.elastic_resizes


class TestGrow:
    def test_grow_restores_target_when_capacity_free(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        job.status.desired_replicas = 6
        # two free schedulable TPU nodes + the note the capacity
        # watcher would have left
        cluster.nodes.create("default", new_tpu_node("free-1"))
        cluster.nodes.create("default", new_tpu_node("free-2"))
        ctl.node_informer.start()  # free_capacity reads the informer store
        ctl._shrunken_jobs[job.key] = job.metadata.uid
        ctl._pending_grows[job.key] = {"node": "free-1",
                                       "uid": job.metadata.uid}
        # grow falls through (False) so the SAME sync reconciles creates
        assert ctl.maybe_continue_elastic(job, job.to_dict(), []) is False
        assert job.status.desired_replicas == 8
        conds = {c.type: c for c in job.status.conditions}
        assert conds[constants.JOB_RESIZING].reason == \
            constants.RESIZE_GROW_REASON
        assert ctl.elastic_resizes_counter.labels(
            direction="grow").value == 1

    def test_grow_waits_for_enough_capacity(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        job.status.desired_replicas = 6
        cluster.nodes.create("default", new_tpu_node("free-1"))  # need 2
        ctl.node_informer.start()
        ctl._pending_grows[job.key] = {"node": "free-1",
                                       "uid": job.metadata.uid}
        ctl.maybe_continue_elastic(job, job.to_dict(), [])
        assert job.status.desired_replicas == 6  # still shrunken

    def test_completion_clears_condition_and_rerenders(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        job.status.desired_replicas = 6
        from pytorch_operator_tpu.controller import status as sm

        sm.update_job_conditions(job.status, constants.JOB_RESIZING,
                                 constants.RESIZE_SHRINK_REASON, "x")
        # gang at exactly the target: master + 6 survivors (3, 6 drained)
        pods = [_bound_pod("elastic-job-master-0", "elastic-job", "node-m",
                           rtype="master")]
        for i in (0, 1, 2, 4, 5, 7):
            pods.append(_bound_pod(f"elastic-job-worker-{i}", "elastic-job",
                                   f"node-{i}", index=str(i)))
        for p in pods:
            cluster.pods.create("default", dict(p))
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is False
        cond = sm.get_condition(job.status, constants.JOB_RESIZING)
        assert cond.status == "False"
        assert cond.reason == constants.RESIZE_COMPLETED_REASON
        survivor = cluster.pods.get("default", "elastic-job-worker-4")
        anns = survivor["metadata"]["annotations"]
        assert anns[constants.ANNOTATION_ELASTIC_WORLD_SIZE] == "7"
        assert anns[constants.ANNOTATION_ELASTIC_RANK] == "4"


    def test_grow_claims_stop_siblings_taking_the_same_nodes(self):
        # one capacity event wakes every shrunken job; only as many may
        # grow as there is UNCLAIMED capacity — the rest stay shrunken
        # until the first grow completes and releases its reservation
        cluster, ctl = _elastic_world()
        job_a = elastic_job(name="job-a")
        job_b = elastic_job(name="job-b")
        job_a.status.desired_replicas = 6
        job_b.status.desired_replicas = 6
        cluster.nodes.create("default", new_tpu_node("free-1"))
        cluster.nodes.create("default", new_tpu_node("free-2"))
        ctl.node_informer.start()
        for job in (job_a, job_b):
            ctl._pending_grows[job.key] = {"node": "free-1",
                                           "uid": job.metadata.uid}
        assert ctl.maybe_continue_elastic(job_a, job_a.to_dict(), []) is False
        assert job_a.status.desired_replicas == 8  # claimed both nodes
        ctl.maybe_continue_elastic(job_b, job_b.to_dict(), [])
        assert job_b.status.desired_replicas == 6  # capacity spoken for
        # job-a's resize completes -> its claim releases -> job-b can grow
        pods = [_bound_pod("job-a-master-0", "job-a", "node-m",
                           rtype="master", uid=job_a.metadata.uid)]
        for i in range(8):
            pods.append(_bound_pod(f"job-a-worker-{i}", "job-a",
                                   f"node-{i}", index=str(i),
                                   uid=job_a.metadata.uid))
        for p in pods:
            cluster.pods.create("default", dict(p))
        assert ctl.maybe_continue_elastic(job_a, job_a.to_dict(),
                                          pods) is False
        # releasing the claim re-woke job-b by itself (no node
        # transition happened, so the CapacityWatcher stayed silent)
        assert job_b.key in ctl._pending_grows
        ctl.maybe_continue_elastic(job_b, job_b.to_dict(), [])
        assert job_b.status.desired_replicas == 8

    def test_replacement_pod_annotated_in_steady_shrunken_state(self):
        # A survivor's replacement created AFTER the shrink completed
        # boots with the CONFIGURED-size env (build_cluster_env can't
        # know the elastic target) and missed the completion-edge
        # render: the steady-state re-render must annotate it, or the
        # replacement waits for a full-size rendezvous its 6 peers'
        # annotations contradict.
        cluster, ctl = _elastic_world()
        job = elastic_job()
        job.status.desired_replicas = 6  # shrink completed: no condition
        pods = [_bound_pod("elastic-job-master-0", "elastic-job", "node-m",
                           rtype="master")]
        for i in (0, 1, 2, 4, 5, 7):
            pods.append(_bound_pod(f"elastic-job-worker-{i}", "elastic-job",
                                   f"node-{i}", index=str(i)))
        for p in pods:
            cluster.pods.create("default", dict(p))
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is False
        anns = cluster.pods.get(
            "default", "elastic-job-worker-4")["metadata"]["annotations"]
        assert anns[constants.ANNOTATION_ELASTIC_WORLD_SIZE] == "7"
        # the replacement scenario proper: worker-4 is recreated bare
        # (a survivor restart refilled the index) — the next sync's
        # steady-state render freshens it
        cluster.pods.delete("default", "elastic-job-worker-4")
        cluster.pods.create("default", _bound_pod(
            "elastic-job-worker-4", "elastic-job", "node-4b", index="4"))
        pods = cluster.pods.list("default")
        assert ctl.maybe_continue_elastic(job, job.to_dict(), pods) is False
        anns = cluster.pods.get(
            "default", "elastic-job-worker-4")["metadata"]["annotations"]
        assert anns[constants.ANNOTATION_ELASTIC_WORLD_SIZE] == "7"
        assert anns[constants.ANNOTATION_ELASTIC_RANK] == "4"

    def test_grow_survives_failed_status_write(self):
        # The end-of-sync status write can fail AFTER _try_grow claimed
        # capacity and the same sync's reconcile created the missing
        # workers: the requeued sync rebuilds the job from the store at
        # the SHRUNKEN size while the full gang is already live.  The
        # grow note is the retry memory (symmetric with the drain
        # note): it must survive an applied grow, and the retry must
        # re-apply desiredReplicas WITHOUT demanding fresh capacity for
        # workers that already exist — else the claim strands forever,
        # deducting nodes from every sibling's free-capacity check.
        cluster, ctl = _elastic_world()
        job = elastic_job()
        job.status.desired_replicas = 6
        cluster.nodes.create("default", new_tpu_node("free-1"))
        cluster.nodes.create("default", new_tpu_node("free-2"))
        ctl.node_informer.start()
        ctl._shrunken_jobs[job.key] = job.metadata.uid
        ctl._pending_grows[job.key] = {"node": "free-1",
                                       "uid": job.metadata.uid}
        assert ctl.maybe_continue_elastic(job, job.to_dict(), []) is False
        assert job.status.desired_replicas == 8
        assert ctl._growing_claims[job.key] == 2
        # applied but not yet durably written: the note must survive
        assert job.key in ctl._pending_grows

        # the write failed; the requeued sync sees the STORE's job
        # (still shrunken) but the creates went through — full gang
        # live and bound on the freed nodes
        retry_job = elastic_job()
        retry_job.status.desired_replicas = 6
        pods = [_bound_pod("elastic-job-master-0", "elastic-job", "node-m",
                           rtype="master")]
        for i in range(8):
            node = ("free-1", "free-2")[i - 6] if i >= 6 else f"node-{i}"
            pods.append(_bound_pod(f"elastic-job-worker-{i}", "elastic-job",
                                   node, index=str(i)))
        for p in pods:
            cluster.pods.create("default", dict(p))
        assert ctl.maybe_continue_elastic(retry_job, retry_job.to_dict(),
                                          pods) is False
        # the retry re-applied the grow and the completed resize
        # released the claim — and ONE real resize stayed one counter
        # increment across the retries (the note remembers the
        # announcement)
        assert retry_job.status.desired_replicas == 8
        assert ctl.elastic_resizes_counter.labels(
            direction="grow").value == 1
        assert job.key not in ctl._growing_claims
        from pytorch_operator_tpu.controller import status as sm

        cond = sm.get_condition(retry_job.status, constants.JOB_RESIZING)
        assert cond.status == "False"
        assert cond.reason == constants.RESIZE_COMPLETED_REASON
        # once the store shows the grown target, the note drains
        grown_job = elastic_job()
        grown_job.status.desired_replicas = 8
        assert ctl.maybe_continue_elastic(grown_job, grown_job.to_dict(),
                                          pods) is False
        assert job.key not in ctl._pending_grows

    def test_terminal_job_releases_claim_and_grow_wakes(self):
        # a job that ends mid-grow must not keep its capacity claim (it
        # would starve every other shrunken job) nor its shrunken
        # registration (pointless grow wakes on each capacity event)
        cluster, ctl = _elastic_world()
        job = elastic_job()
        cluster.jobs.create("default", job.to_dict())
        ctl._growing_claims[job.key] = 2
        ctl._shrunken_jobs[job.key] = job.metadata.uid
        from pytorch_operator_tpu.controller import status as sm

        sm.update_job_conditions(job.status, constants.JOB_SUCCEEDED,
                                 "r", "m")
        ctl.reconcile(job, job.to_dict())
        assert job.key not in ctl._growing_claims
        assert job.key not in ctl._shrunken_jobs


class TestShrunkenReconcile:
    def _shrunken_worker_pods(self, survivors=(0, 1, 2, 4, 5, 7)):
        # survivors of an 8-gang shrunken to 6 (indices 3 and 6 drained)
        return [_bound_pod(f"elastic-job-worker-{i}", "elastic-job",
                           f"node-{i}", index=str(i)) for i in survivors]

    def test_failed_survivor_restarts_instead_of_failing_job(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        job.status.desired_replicas = 6
        spec = job.spec.pytorch_replica_specs[constants.REPLICA_TYPE_WORKER]
        spec.restart_policy = constants.RESTART_POLICY_EXIT_CODE
        pods = self._shrunken_worker_pods()
        pods[3]["status"] = {  # worker-4 dies retryably (SIGKILL)
            "phase": "Failed",
            "containerStatuses": [{
                "name": constants.DEFAULT_CONTAINER_NAME,
                "state": {"terminated": {"exitCode": 137}}}]}
        ctl.reconcile_pods(job, job.to_dict(), pods, "Worker", spec,
                           gang_enabled=False, elastic_target=6)
        # the survivor restarts (its node outlived it, unlike the
        # drained holes') — the job must NOT terminally fail
        assert ctl.pod_control.delete_pod_names == ["elastic-job-worker-4"]
        conds = {c.type: c for c in job.status.conditions}
        assert constants.JOB_FAILED not in conds
        assert conds[constants.JOB_RESTARTING].status == "True"

    def test_replacement_fills_lowest_hole_only_up_to_target(self):
        cluster, ctl = _elastic_world()
        job = elastic_job()
        job.status.desired_replicas = 6
        spec = job.spec.pytorch_replica_specs[constants.REPLICA_TYPE_WORKER]
        # worker-4's restarted pod is gone this sync: occupancy 5 < 6,
        # so exactly ONE replacement fills the lowest empty index; the
        # remaining drained holes are left for the grow path
        pods = self._shrunken_worker_pods(survivors=(0, 1, 2, 5, 7))
        ctl.reconcile_pods(job, job.to_dict(), pods, "Worker", spec,
                           gang_enabled=False, elastic_target=6)
        created = [
            p["metadata"]["labels"][constants.LABEL_REPLICA_INDEX]
            for p in ctl.pod_control.templates]
        assert created == ["3"]
        assert ctl.pod_control.delete_pod_names == []


class TestCapacityWatcher:
    def test_fires_once_per_schedulable_transition(self):
        cluster = FakeCluster()
        cluster.nodes.create("default", new_tpu_node("n1"))
        informer = Informer(cluster.nodes)
        fired = []
        CapacityWatcher(informer, fired.append)
        informer.start()
        assert fired == []  # initial LIST is existing, not returning
        taint = [{"key": constants.IMPENDING_NODE_TERMINATION_TAINT,
                  "effect": "NoSchedule"}]
        cluster.nodes.patch("default", "n1", {"spec": {"taints": taint}})
        assert fired == []
        cluster.nodes.patch("default", "n1", {"spec": {"taints": None}})
        assert fired == ["n1"]
        # churn on an already-schedulable node stays silent
        cluster.nodes.patch("default", "n1",
                            {"metadata": {"labels": {"x": "y"}}})
        assert fired == ["n1"]
        # a fresh node joining AFTER sync is returning capacity
        cluster.nodes.create("default", new_tpu_node("n2"))
        assert fired == ["n1", "n2"]

    def test_free_capacity_counts_empty_schedulable_tpu_nodes(self):
        cluster = FakeCluster()
        cluster.nodes.create("default", new_tpu_node("empty"))
        busy = new_tpu_node("busy")
        cluster.nodes.create("default", busy)
        tainted = new_tpu_node("tainted")
        tainted["spec"]["taints"] = [{
            "key": constants.NODE_UNREACHABLE_TAINT, "effect": "NoExecute"}]
        cluster.nodes.create("default", tainted)
        cluster.pods.create("default",
                            _bound_pod("p1", "j", "busy"))
        informer = Informer(cluster.nodes)
        watcher = CapacityWatcher(informer, lambda n: None, cluster=cluster)
        informer.start()
        assert watcher.free_capacity() == 1


def _unbound_pod(name):
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {"containers": [{"name": "pytorch", "image": "i"}]},
            "status": {}}


class TestCapacityFreeze:
    def test_freeze_queues_pods_and_reuses_freed_nodes(self):
        """CapacityFlap(freeze_capacity=True)'s kubelet side: while
        frozen no fresh node is minted — a pod beyond the freed-node
        pool waits Pending, binds the moment a node frees mid-dip, and
        provisioning resumes at unfreeze.  This is what makes the
        --elastic bench's legacy variant genuinely ride the dip instead
        of escaping onto lazily provisioned nodes."""
        import time as _time

        cluster = FakeCluster()
        kubelet = FakeKubelet(cluster, decide=lambda pod: None)
        kubelet.start()
        try:
            cluster.pods.create("default", _unbound_pod("warm"))
            assert wait_for(lambda: (cluster.pods.get("default", "warm")
                                     .get("spec") or {}).get("nodeName"))
            warm_node = cluster.pods.get(
                "default", "warm")["spec"]["nodeName"]
            kubelet.freeze_capacity()
            cluster.pods.create("default", _unbound_pod("starved"))
            _time.sleep(0.1)
            pod = cluster.pods.get("default", "starved")
            assert not (pod.get("spec") or {}).get("nodeName")
            assert (pod.get("status") or {}).get("phase") == "Pending"
            # a node freed mid-dip goes straight to the waiting pod
            cluster.pods.delete("default", "warm")
            assert wait_for(
                lambda: (cluster.pods.get("default", "starved")
                         .get("spec") or {}).get("nodeName") == warm_node)
            assert wait_for(
                lambda: (cluster.pods.get("default", "starved")
                         .get("status") or {}).get("phase") == "Running")
            # still frozen: the next pod has nothing to bind to...
            cluster.pods.create("default", _unbound_pod("starved-2"))
            _time.sleep(0.1)
            assert not (cluster.pods.get("default", "starved-2")
                        .get("spec") or {}).get("nodeName")
            # ...until the freeze lifts and provisioning resumes
            kubelet.unfreeze_capacity()
            assert wait_for(
                lambda: (cluster.pods.get("default", "starved-2")
                         .get("status") or {}).get("phase") == "Running")
        finally:
            kubelet.stop()


# ---------------------------------------------------------------------------
# Sim e2e: the acceptance CapacityFlap scenario.
# ---------------------------------------------------------------------------


@pytest.fixture
def flap_world():
    cluster = FakeCluster()
    registry = Registry()
    ctl = PyTorchController(
        cluster,
        config=JobControllerConfig(enable_disruption_handling=True,
                                   drain_deadline_seconds=5.0),
        registry=registry)
    # pods run forever until the test flips the decision; drained pods
    # ack their checkpoint after checkpoint_delay
    kubelet = FakeKubelet(cluster, decide=lambda pod: None,
                          checkpoint_delay=0.02)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    yield cluster, ctl, registry, kubelet
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()


def _running_pods(cluster):
    return [p for p in cluster.pods.list()
            if (p.get("status") or {}).get("phase") == "Running"]


def _finish(cluster, kubelet):
    kubelet.decide = lambda pod: ("Succeeded", 0)
    for pod in _running_pods(cluster):
        kubelet.complete_pod_now("default", pod["metadata"]["name"])


def test_capacity_flap_shrink_then_grow(flap_world):
    """ISSUE 6 acceptance: elastic 8-worker job under a CapacityFlap
    shrinks to 6 via drain (doomed pods checkpoint before deletion),
    keeps reconciling with re-rendered WORLD_SIZE, grows back to 8 when
    the nodes return, reaches Succeeded with zero duplicate creates and
    exactly one Resizing transition per capacity change."""
    cluster, ctl, registry, kubelet = flap_world
    job = elastic_job(name="flap-job", workers=8, min_replicas=4)
    cluster.jobs.create("default", job.to_dict())
    assert wait_for(lambda: len(_running_pods(cluster)) == 9), \
        [p.get("status") for p in cluster.pods.list()]
    gen1 = {p["metadata"]["name"]: p["metadata"]["uid"]
            for p in cluster.pods.list()}

    # flight recorder: every job-status write (Resizing transitions) and
    # every pod delete (checkpoint-before-deletion proof)
    seen_conditions = []
    cluster.jobs.add_listener(
        lambda et, obj: seen_conditions.extend(
            (obj.get("status") or {}).get("conditions") or []))
    deleted_pods = []
    cluster.pods.add_listener(
        lambda et, obj: deleted_pods.append(obj) if et == "DELETED" else None)

    victims = ["flap-job-worker-3", "flap-job-worker-6"]
    victim_nodes = [cluster.pods.get("default", v)["spec"]["nodeName"]
                    for v in victims]
    assert all(victim_nodes)
    flap = CapacityFlap(kubelet, victim_nodes, grace=1.0)
    flap.down()

    # shrink: exactly the two doomed pods drained away, 7 keep running
    assert wait_for(lambda: (
        len(_running_pods(cluster)) == 7
        and not any(_pod_exists(cluster, v) for v in victims)), timeout=20), \
        [p["metadata"]["name"] for p in _running_pods(cluster)]
    # the doomed pods checkpointed BEFORE deletion
    drained = [p for p in deleted_pods
               if p["metadata"]["name"] in victims]
    assert len(drained) == 2
    for pod in drained:
        anns = pod["metadata"].get("annotations") or {}
        assert constants.ANNOTATION_CHECKPOINT_REQUESTED in anns
        assert constants.ANNOTATION_CHECKPOINTED in anns
    # survivors are the ORIGINAL pods (no full restart) and keep running
    for p in _running_pods(cluster):
        assert gen1[p["metadata"]["name"]] == p["metadata"]["uid"]
    # the job keeps reconciling at the reduced size: desired persisted,
    # survivors' rendezvous re-rendered to WORLD_SIZE 7
    assert wait_for(lambda: cluster.jobs.get("default", "flap-job")
                    ["status"].get("desiredReplicas") == 6)
    assert wait_for(lambda: all(
        (cluster.pods.get("default", p["metadata"]["name"])["metadata"]
         .get("annotations") or {}).get(
             constants.ANNOTATION_ELASTIC_WORLD_SIZE) == "7"
        for p in _running_pods(cluster)), timeout=20)
    assert ctl.elastic_drain_timeouts_counter.value == 0

    # capacity returns: the gang grows back to 8 workers
    flap.restore()
    assert wait_for(lambda: len(_running_pods(cluster)) == 9, timeout=20), \
        [p["metadata"]["name"] for p in _running_pods(cluster)]
    assert wait_for(lambda: all(
        (cluster.pods.get("default", p["metadata"]["name"])["metadata"]
         .get("annotations") or {}).get(
             constants.ANNOTATION_ELASTIC_WORLD_SIZE) == "9"
        for p in _running_pods(cluster)), timeout=20)

    _finish(cluster, kubelet)
    assert wait_for(lambda: job_condition(
        cluster, "default", "flap-job", constants.JOB_SUCCEEDED)), \
        cluster.jobs.get("default", "flap-job")["status"]

    # zero duplicate creates: 9 initial + exactly the 2 regrown
    events = cluster.events.list()
    creates = [e for e in events if e["reason"] == "SuccessfulCreatePod"]
    assert len(creates) == 11
    deletes = [e for e in events if e["reason"] == "SuccessfulDeletePod"]
    assert len(deletes) == 2
    # never the legacy full restart
    assert not [e for e in events
                if e["reason"] == constants.TPU_PREEMPTED_REASON]
    # exactly one Resizing transition per capacity change: one
    # ShrinkOnPreemption and one GrowOnCapacity True-transition
    transitions = []
    for c in seen_conditions:
        if c.get("type") != constants.JOB_RESIZING:
            continue
        key = (c.get("status"), c.get("reason"),
               c.get("lastTransitionTime"))
        if key not in transitions:
            transitions.append(key)
    shrinks = [t for t in transitions
               if t[0] == "True"
               and t[1] == constants.RESIZE_SHRINK_REASON]
    grows = [t for t in transitions
             if t[0] == "True" and t[1] == constants.RESIZE_GROW_REASON]
    assert len(shrinks) == 1, transitions
    assert len(grows) == 1, transitions
    assert ctl.elastic_resizes_counter.labels(
        direction="shrink").value == 1
    assert ctl.elastic_resizes_counter.labels(direction="grow").value == 1
    # budget persisted; preemption-restart budget untouched
    status = cluster.jobs.get("default", "flap-job")["status"]
    assert status.get("elasticResizes") == 1
    assert not status.get("preemptionRestarts")
    # no expectation leaks
    for rtype in ("master", "worker"):
        assert ctl.expectations.satisfied(
            expectation_pods_key("default/flap-job", rtype))
        assert ctl.expectations.satisfied(
            expectation_services_key("default/flap-job", rtype))


def _pod_exists(cluster, name) -> bool:
    from pytorch_operator_tpu.k8s.errors import NotFoundError

    try:
        cluster.pods.get("default", name)
        return True
    except NotFoundError:
        return False


def test_capacity_flap_non_elastic_keeps_full_restart(flap_world):
    """The same flap against a NON-elastic gang job keeps the PR 2
    behavior byte-identically: one proactive full-gang restart with
    reason TPUPreempted, no Resizing machinery anywhere."""
    cluster, ctl, registry, kubelet = flap_world
    job = new_job(workers=4, name="rigid-job", tpu_chips=4)
    cluster.jobs.create("default", job.to_dict())
    assert wait_for(lambda: len(_running_pods(cluster)) == 5)
    gen1 = {p["metadata"]["uid"] for p in cluster.pods.list()}

    victim = cluster.pods.get("default", "rigid-job-worker-1")
    flap = CapacityFlap(kubelet, [victim["spec"]["nodeName"]], grace=0.5)
    flap.down()

    assert wait_for(
        lambda: ctl.preemption_gang_restarts_counter.value == 1)
    assert wait_for(lambda: (
        len(_running_pods(cluster)) == 5
        and not gen1 & {p["metadata"]["uid"]
                        for p in cluster.pods.list()}), timeout=20)
    flap.restore()
    _finish(cluster, kubelet)
    assert wait_for(lambda: job_condition(
        cluster, "default", "rigid-job", constants.JOB_SUCCEEDED))
    status = cluster.jobs.get("default", "rigid-job")["status"]
    assert status.get("preemptionRestarts") == 1
    assert "desiredReplicas" not in status
    assert "elasticResizes" not in status
    assert not [c for c in status.get("conditions", [])
                if c["type"] == constants.JOB_RESIZING]
    assert ctl.elastic_resizes_counter.labels(
        direction="shrink").value == 0


# ---------------------------------------------------------------------------
# Data plane: mesh-shape-flexible state (the reshard acceptance).
# ---------------------------------------------------------------------------


class TestReshard:
    @pytest.fixture(scope="class")
    def tiny_world(self):
        import jax
        import optax

        from pytorch_operator_tpu.models import llama
        from pytorch_operator_tpu.parallel import make_mesh, sharded_init

        cfg = llama.tiny(max_seq_len=64, use_flash=False,
                         use_fused_norm=False, remat=False)
        opt = optax.adamw(3e-4)
        devs = jax.devices()
        mesh4 = make_mesh(1, 4, 1, devices=devs[:4])
        mesh2 = make_mesh(1, 2, 1, devices=devs[:2])
        state4 = sharded_init(cfg, mesh4, opt)
        return cfg, opt, mesh4, mesh2, state4

    @staticmethod
    def _gathered(tree):
        import jax
        import numpy as np

        return [np.asarray(jax.device_get(leaf))
                for leaf in jax.tree.leaves(tree)]

    def test_params_identical_across_mesh_shapes_and_back(self, tiny_world):
        """The data-plane acceptance: a 4-device state reshards onto a
        2-device mesh (and back) with the gathered param tree
        numerically identical — shrink loses layout, never values."""
        from pytorch_operator_tpu.parallel import reshard_state

        cfg, opt, mesh4, mesh2, state4 = tiny_world
        state2 = reshard_state(state4, cfg, mesh2, opt)
        for a, b in zip(self._gathered(state4), self._gathered(state2)):
            assert (a == b).all()
        back = reshard_state(state2, cfg, mesh4, opt)
        for a, b in zip(self._gathered(state4), self._gathered(back)):
            assert (a == b).all()

    def test_resharded_state_trains_on_the_new_mesh(self, tiny_world):
        import numpy as np

        from pytorch_operator_tpu.parallel import (
            make_train_step,
            reshard_state,
        )

        cfg, opt, mesh4, mesh2, state4 = tiny_world
        state2 = reshard_state(state4, cfg, mesh2, opt)
        step2 = make_train_step(cfg, mesh2, opt)
        batch = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (4, 65)).astype(np.int32)
        state2, metrics = step2(state2, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert int(state2.step) == 1

    def test_sharding_tree_matches_mesh(self, tiny_world):
        from pytorch_operator_tpu.parallel import state_shardings

        cfg, opt, mesh4, mesh2, _ = tiny_world
        import jax

        tree2 = state_shardings(cfg, mesh2, opt)
        for sh in jax.tree.leaves(tree2.params):
            assert sh.mesh.devices.size == 2


def _run_llama(steps: int, device_count: int, extra: list[str]) -> str:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={device_count}")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples/llama/train_llama.py"),
         "--model", "tiny", "--batch-size", "4", "--seq-len", "64",
         "--steps", str(steps), "--no-flash", "--no-fused-norm",
         "--no-remat", *extra],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_llama_resumes_at_new_world_size(tmp_path):
    """Run 1 trains and checkpoints on a 4-device mesh; run 2 restores
    onto a 2-device mesh and continues from the saved step — the
    elastic checkpoint-resume flow a shrunken gang executes."""
    ckpt = ["--checkpoint-dir", str(tmp_path / "ckpt"),
            "--checkpoint-every", "2"]
    out1 = _run_llama(steps=2, device_count=4, extra=ckpt)
    assert "checkpointed step 2" in out1

    out2 = _run_llama(steps=4, device_count=2, extra=ckpt)
    assert "restored checkpoint at step 2 onto 2 device(s)" in out2
    steps_run = [int(m) for m in re.findall(r"^step (\d+):", out2,
                                            re.MULTILINE)]
    assert steps_run and min(steps_run) >= 2, steps_run
    assert "training complete" in out2
