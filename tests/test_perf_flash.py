"""Perf regression guard for the flash-attention headline claim.

BENCH_DETAIL.md §2 reports the Pallas kernel at ~11x (fwd) / ~8-9x
(fwd+bwd) over dense XLA at seq 4096.  This enforces a conservative
floor — flash must stay >=4x dense on fwd+bwd at 4096 — so a kernel or
block-policy regression fails the suite instead of surviving until the
next manual bench run.

Contention robustness (round-3 verdict item 3: the old min-of-3,
interleave-free guard let a 2.3x transient slowdown fail a healthy
kernel): flash and dense now run in INTERLEAVED windows (ABAB...) in
one process, so a load spike on the shared chip inflates both sides
and mostly cancels in the ratio; the verdict uses the median of the
per-window times; and when the floor would fail WITH high dispersion
in either series (the contention signature), the whole measurement
re-runs once before failing.  On failure both raw series are printed.

Sensitivity check (one-off, 2026-07-30, re-runnable via the
_GUARD_DEGRADE=1 env hook): forcing the degraded two-kernel backward
path AND 128-blocks (a real multi-x fwd+bwd regression, per the
block-size sweep in _auto_block's docstring) makes this guard fail at
1.48x < 4.0 with low dispersion (flash 10.85 ms vs the healthy ~1.9) —
the robustness changes did not blunt it.  Subprocess escapes the
suite's CPU pin; skips without hardware (same pattern as
test_perf_fused_norm.py).
"""

import json
import os
import subprocess
import sys

import pytest

_PAYLOAD = r"""
import json, statistics, time
import jax
import jax.numpy as jnp

if jax.default_backend() not in ("tpu", "axon") and \
        jax.devices()[0].platform not in ("tpu", "axon"):
    print(json.dumps({"skip": f"no TPU ({jax.default_backend()})"}))
    raise SystemExit(0)

import os
from pytorch_operator_tpu.ops import flash_attention

# _GUARD_DEGRADE: sensitivity self-test hook — force a known-slow
# configuration (two-kernel backward + 128 blocks) that a healthy guard
# MUST flag.  Never set in the suite.
DEGRADE = bool(os.environ.get("_GUARD_DEGRADE"))
if DEGRADE:
    import pytorch_operator_tpu.ops.flash_attention as _fa
    _fa._FUSED_DQ_VMEM_BYTES = 0

B, T, H, D = 1, 4096, 16, 128
ks = jax.random.split(jax.random.key(0), 3)
q, k, v = (jax.random.normal(kk, (B, T, H, D), jnp.bfloat16) for kk in ks)

def make_runner(kw, iters):
    # Two-point timer: (time of 2N-iter scan) - (time of N-iter scan)
    # cancels the fixed per-launch cost, which through the device tunnel
    # is tens-to-hundreds of ms — at small N that overhead, divided by
    # N, would otherwise swamp a ~2 ms kernel and compress the A/B
    # ratio (the same method scripts/bench_detail.py uses).
    def loss(qq, kk, vv):
        o = flash_attention(qq, kk, vv, causal=True, **kw)
        return jnp.sum(o.astype(jnp.float32) ** 2)
    grad_fn = jax.grad(loss, argnums=(0, 1, 2))

    def make_run(length):
        @jax.jit
        def run(qc):
            def body(c, _):
                dq, dk, dv = grad_fn(c, k, v)
                g = (dq + dk + dv).astype(jnp.float32)
                return (g * jax.lax.rsqrt(jnp.mean(g * g) + 1e-6)
                        ).astype(c.dtype), None
            out = jax.lax.scan(body, qc, None, length=length)[0]
            return jnp.sum(out.astype(jnp.float32))
        return run

    run1, run2 = make_run(iters), make_run(2 * iters)
    float(run1(q))  # compile + warmup
    float(run2(q))

    def timed():
        t0 = time.perf_counter()
        float(run1(q))
        t1 = time.perf_counter()
        float(run2(q))
        t2 = time.perf_counter()
        return ((t2 - t1) - (t1 - t0)) / iters
    return timed

flash_kw = ({"block_q": 128, "block_k": 128} if DEGRADE else {})
runners = {"flash": make_runner(flash_kw, 40),
           "dense": make_runner({"block_q": 0, "block_k": 0}, 10)}

def measure(rounds=5):
    series = {"flash": [], "dense": []}
    for _ in range(rounds):
        for name, timed in runners.items():  # interleaved ABAB windows
            series[name].append(timed())
    med = {n: statistics.median(s) for n, s in series.items()}
    disp = {n: (max(s) - min(s)) / med[n] for n, s in series.items()}
    return {"speedup": med["dense"] / med["flash"],
            "flash_ms": med["flash"] * 1e3,
            "dense_ms": med["dense"] * 1e3,
            "dispersion": disp,
            "series_ms": {n: [round(t * 1e3, 3) for t in s]
                          for n, s in series.items()}}

result = measure()
if result["speedup"] < 4.0 and max(result["dispersion"].values()) > 0.4:
    # contention signature: noisy windows AND a failing ratio — one
    # full re-measure before letting the failure stand
    retry = measure()
    retry["retried_after"] = result
    result = retry
print(json.dumps(result))
"""


@pytest.mark.perf
def test_flash_fwdbwd_keeps_headline_speedup():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    if flags:
        env["XLA_FLAGS"] = " ".join(flags)
    else:
        env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", _PAYLOAD], env=env,
                          capture_output=True, text=True, timeout=900,
                          cwd=repo)
    assert proc.returncode == 0, f"payload failed:\n{proc.stderr[-2000:]}"
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    if "skip" in result:
        pytest.skip(result["skip"])
    assert result["speedup"] >= 4.0, (
        f"flash fwd+bwd regressed to {result['speedup']:.2f}x dense at "
        f"seq 4096 (median flash {result['flash_ms']:.2f}ms, dense "
        f"{result['dense_ms']:.2f}ms; headline ~9x).  Raw interleaved "
        f"series (ms): {json.dumps(result['series_ms'])}; dispersion "
        f"{result['dispersion']}"
        + (f"; first attempt (re-measured due to contention): "
           f"{json.dumps(result['retried_after']['series_ms'])}"
           if "retried_after" in result else ""))
