"""TPU-native PyTorchJob controller (reference: pkg/controller.v1/pytorch/)."""

from .pytorch_controller import PyTorchController
from .tpu_env import build_cluster_env, replica_hostnames, set_cluster_spec
from .train_util import is_retryable_exit_code

__all__ = [
    "PyTorchController",
    "build_cluster_env",
    "replica_hostnames",
    "set_cluster_spec",
    "is_retryable_exit_code",
]
