"""Kubernetes Event recorder.

Equivalent of the client-go record.EventRecorder wired in the reference at
jobcontroller.go:160-163 — emits v1.Event objects attached to the involved
object for every notable transition (ExitedWithCode, SuccessfulCreate...).
"""

from __future__ import annotations

import time
import uuid
from typing import Callable, List, Optional

from ..api.v1.constants import LABEL_SHARD as _LABEL_SHARD

EVENT_TYPE_NORMAL = "Normal"
EVENT_TYPE_WARNING = "Warning"


def _now_iso(now: Optional[float] = None) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(now))


class EventRecorder:
    """Writes Events to an ``events`` resource client.

    ``clock`` (epoch-seconds callable, e.g. a VirtualClock's ``now``)
    stamps first/lastTimestamp; None means the real wall clock, so
    events recorded under the simulator carry deterministic times."""

    def __init__(self, events_client, component: str = "pytorch-operator",
                 clock: Optional[Callable[[], float]] = None):
        self._events = events_client
        self.component = component
        self._clock = clock

    def event(self, obj: dict, event_type: str, reason: str, message: str) -> None:
        if not isinstance(obj, dict):
            obj = {}
        meta = obj.get("metadata") or {}
        name = meta.get("name", "unknown")
        namespace = meta.get("namespace", "default")
        ev_meta: dict = {
            "name": f"{name}.{uuid.uuid4().hex[:10]}",
            "namespace": namespace,
        }
        # Events inherit the involved object's shard label: a sharded
        # replica (or dashboard) can then list/watch exactly its own
        # shards' event traffic with a selector instead of receiving
        # the whole fleet's stream.
        shard = (meta.get("labels") or {}).get(_LABEL_SHARD)
        if shard is not None:
            ev_meta["labels"] = {_LABEL_SHARD: shard}
        ev = {
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": ev_meta,
            "involvedObject": {
                "apiVersion": obj.get("apiVersion", ""),
                "kind": obj.get("kind", ""),
                "name": name,
                "namespace": namespace,
                "uid": meta.get("uid", ""),
            },
            "reason": reason,
            "message": message,
            "type": event_type,
            "count": 1,
            "source": {"component": self.component},
            "firstTimestamp": _now_iso(ts := (
                self._clock() if self._clock is not None else None)),
            "lastTimestamp": _now_iso(ts),
        }
        try:
            self._events.create(namespace, ev)
        except Exception:  # lint: swallowed-except-ok event emission is best-effort by design; a failed create must never break the reconcile that raised it
            pass

    def eventf(self, obj: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)


class FakeRecorder:
    """Records events in memory for unit tests."""

    def __init__(self):
        self.events: List[str] = []

    def event(self, obj: dict, event_type: str, reason: str, message: str) -> None:
        self.events.append(f"{event_type} {reason} {message}")

    def eventf(self, obj: dict, event_type: str, reason: str, fmt: str, *args) -> None:
        self.event(obj, event_type, reason, fmt % args if args else fmt)
