"""SDK client tests against the simulated cluster.

Mirrors the reference's SDK e2e flow
(sdk/python/test/test_e2e.py:33-81: create -> wait_for_job -> assert
succeeded -> get logs -> delete) with the fake cluster + controller +
kubelet standing in for GKE.
"""

from __future__ import annotations

import threading

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.k8s.errors import NotFoundError
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import JobControllerConfig
from pytorch_operator_tpu.sdk import PyTorchJobClient
from pytorch_operator_tpu.sdk import utils as sdk_utils

from testutil import new_job


@pytest.fixture
def world():
    cluster = FakeCluster()
    ctl = PyTorchController(
        cluster, config=JobControllerConfig(), registry=Registry())
    kubelet = FakeKubelet(cluster)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    yield cluster
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()


@pytest.fixture
def client(world):
    return PyTorchJobClient(cluster=world)


class TestSdkLifecycle:
    def test_create_wait_logs_delete(self, world, client):
        job = new_job(workers=1, name="sdk-job")
        created = client.create(job.to_dict())
        assert created["metadata"]["name"] == "sdk-job"

        finished = client.wait_for_job(
            "sdk-job", timeout_seconds=15, polling_interval=0.05)
        assert client.is_job_succeeded("sdk-job")
        assert finished["status"]["replicaStatuses"]["Master"]["succeeded"] == 1

        # master-only by default, like the reference get_logs
        logs = client.get_logs("sdk-job")
        assert list(logs) == ["sdk-job-master-0"]
        assert "accuracy=" in logs["sdk-job-master-0"]

        all_pods = client.get_pod_names("sdk-job")
        assert set(all_pods) == {"sdk-job-master-0", "sdk-job-worker-0"}
        workers = client.get_pod_names("sdk-job", replica_type="worker")
        assert workers == ["sdk-job-worker-0"]

        client.delete("sdk-job")
        with pytest.raises(NotFoundError):
            client.get("sdk-job")

    def test_create_dataclass_job(self, client):
        job = new_job(workers=0, name="dc-job")
        client.create(job)  # dataclass, not dict
        got = client.get("dc-job")
        assert got["kind"] == constants.KIND

    def test_get_list(self, client):
        client.create(new_job(workers=0, name="a").to_dict())
        client.create(new_job(workers=0, name="b").to_dict())
        items = client.get()["items"]
        assert {j["metadata"]["name"] for j in items} >= {"a", "b"}

    def test_get_job_status_progression(self, client):
        client.create(new_job(workers=0, name="st-job").to_dict())
        client.wait_for_job("st-job", timeout_seconds=15, polling_interval=0.05)
        assert client.get_job_status("st-job") == constants.JOB_SUCCEEDED
        assert not client.is_job_running("st-job")

    def test_wait_timeout_raises(self, world):
        # no kubelet progress for this job: decide() leaves pods running
        client = PyTorchJobClient(cluster=world)
        job = new_job(workers=0, name="stuck-job")
        # fresh cluster object w/o kubelet interference is complex; instead
        # wait on a nonexistent condition with a tiny timeout
        client.create(job.to_dict())
        with pytest.raises(RuntimeError, match="timeout"):
            client.wait_for_condition(
                "stuck-job", ["NeverHappens"],
                timeout_seconds=0.2, polling_interval=0.05)

    def test_patch(self, client):
        client.create(new_job(workers=1, name="p-job").to_dict())
        client.patch("p-job", {"metadata": {"labels": {"team": "ml"}}})
        assert client.get("p-job")["metadata"]["labels"]["team"] == "ml"


class TestSdkUtils:
    def test_labels_master(self):
        labels = sdk_utils.get_labels("j", master=True)
        assert labels[constants.LABEL_JOB_ROLE] == "master"
        assert labels[constants.LABEL_PYTORCH_JOB_NAME] == "j"

    def test_selector_string(self):
        s = sdk_utils.to_selector({"a": "1", "b": "2"})
        assert s == "a=1,b=2"

    def test_default_namespace(self):
        assert sdk_utils.get_default_target_namespace() == "default"


def _start_watch(client, cluster, name, timeout_seconds=20):
    """Run client.get(watch=True) on a thread; return (thread, result)
    once the watcher's listener is subscribed.  A bare FakeCluster (no
    controller/kubelet) keeps the job's state under the test's
    control."""
    done: dict = {}

    def run():
        try:
            client.get(name, watch=True, timeout_seconds=timeout_seconds)
            done["ok"] = True
        except Exception as e:  # pragma: no cover - surfaced by callers
            done["error"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    pause = threading.Event()
    for _ in range(200):
        if cluster.jobs._listeners:
            return t, done
        pause.wait(0.05)
    pytest.fail("watcher never subscribed")


def test_watch_gap_with_deleted_job_reports_deleted(capsys):
    """A job deleted during a watch-stream outage must surface as
    Deleted when the GAP re-read finds a previously-seen job gone — not
    hang to timeout (round-4 review finding on sdk/watch.py)."""
    cluster = FakeCluster()
    client = PyTorchJobClient(cluster=cluster)
    client.create(new_job(workers=0, name="gap-job").to_dict())
    t, done = _start_watch(client, cluster, "gap-job")
    # delete bypassing events, then deliver only the GAP (the DELETED
    # event was lost in the outage window)
    with cluster.lock:
        cluster.jobs._objects.pop(("default", "gap-job"), None)
    for fn in list(cluster.jobs._listeners):
        fn("GAP", {})
    t.join(timeout=10)
    assert not t.is_alive(), "watch hung after GAP + deletion"
    assert done.get("ok"), done.get("error")
    out = capsys.readouterr().out
    assert "Deleted" in out


def test_watch_gap_before_create_keeps_waiting(capsys):
    """A GAP before the job has ever been observed (LIST-then-WATCH
    emits one when the stream opens) must NOT report Deleted — the job
    simply doesn't exist yet; creation events still complete the
    watch."""
    cluster = FakeCluster()
    client = PyTorchJobClient(cluster=cluster)
    t, done = _start_watch(client, cluster, "late-job")
    for fn in list(cluster.jobs._listeners):
        fn("GAP", {})  # stream (re)opened before the job exists
    threading.Event().wait(0.2)
    assert t.is_alive(), "GAP before create must not end the watch"
    created = client.create(new_job(workers=0, name="late-job").to_dict())
    created["status"] = {"conditions": [
        {"type": "Succeeded", "status": "True", "lastTransitionTime": "t"}]}
    cluster.jobs.update(created, subresource="status")
    t.join(timeout=10)
    assert not t.is_alive() and done.get("ok"), done.get("error")
    out = capsys.readouterr().out
    assert "Succeeded" in out and "Deleted" not in out


def test_watch_table_output(world, capsys):
    client = PyTorchJobClient(cluster=world)
    client.create(new_job(workers=0, name="w-job").to_dict())
    client.wait_for_job("w-job", namespace="default", timeout_seconds=15,
                        polling_interval=0.05)
    client.get("w-job", watch=True, timeout_seconds=5)
    out = capsys.readouterr().out
    assert "NAME" in out and "STATE" in out
    assert "w-job" in out and "Succeeded" in out
