#!/usr/bin/env bash
# Build (and optionally push) the operator image — the reference's
# build.sh / build_image.sh equivalent (gcloud builds submit there).
#
#   IMAGE=gcr.io/my-project/pytorch-operator-tpu:v1 scripts/build-image.sh
#   PUSH=1 ... pushes after building; BUILDER=gcloud uses Cloud Build.
set -euo pipefail
cd "$(dirname "$0")/.."

IMAGE="${IMAGE:-pytorch-operator-tpu:latest}"
BUILDER="${BUILDER:-docker}"

case "$BUILDER" in
  docker)
    docker build -t "$IMAGE" .
    if [ "${PUSH:-0}" = "1" ]; then
      docker push "$IMAGE"
    fi
    ;;
  gcloud)
    # reference scripts/build.sh path: server-side build, implies push
    gcloud builds submit --tag "$IMAGE" .
    ;;
  *)
    echo "unknown BUILDER=$BUILDER (docker|gcloud)" >&2
    exit 1
    ;;
esac
echo "built $IMAGE"
