"""TPU cluster-spec injection tests.

Mirrors the reference's pod_test.go:100 TestClusterSpec (exact env-map
assertions) translated to the TPU/PJRT environment.
"""

import pytest

from pytorch_operator_tpu.api.v1 import constants, set_defaults
from pytorch_operator_tpu.controller.tpu_env import (
    InvalidClusterSpecError,
    build_cluster_env,
    get_port_from_job,
    replica_hostnames,
    set_cluster_spec,
)

from testutil import new_job


def env_map(env_list):
    return {e["name"]: e["value"] for e in env_list}


def test_worker_env_exact():
    """Worker index 1 of a 2-worker job: rank 2, world 3 — the same
    scenario the reference asserts (RANK=2, WORLD_SIZE=3)."""
    job = new_job(workers=2)
    set_defaults(job)
    env = env_map(build_cluster_env(job, "Worker", "1"))
    assert env == {
        "MASTER_PORT": "23456",
        "MASTER_ADDR": "test-pytorchjob-master-0",
        "WORLD_SIZE": "3",
        "RANK": "2",
        "PYTHONUNBUFFERED": "1",
        "PJRT_DEVICE": "TPU",
        "TPU_WORKER_ID": "2",
        "TPU_WORKER_HOSTNAMES": (
            "test-pytorchjob-master-0,test-pytorchjob-worker-0,test-pytorchjob-worker-1"
        ),
        "XRT_TPU_CONFIG": (
            "tpu_worker;2;test-pytorchjob-master-0:8470,"
            "test-pytorchjob-worker-0:8470,test-pytorchjob-worker-1:8470"
        ),
        "COORDINATOR_ADDRESS": "test-pytorchjob-master-0:23456",
        "NUM_PROCESSES": "3",
        "PROCESS_ID": "2",
    }


def test_master_env():
    job = new_job(workers=2)
    set_defaults(job)
    env = env_map(build_cluster_env(job, "Master", "0"))
    assert env["MASTER_ADDR"] == "localhost"  # reference pod.go:246-249 parity
    assert env["RANK"] == "0"
    assert env["TPU_WORKER_ID"] == "0"
    assert env["WORLD_SIZE"] == "3"


def test_hostnames_ordered_by_rank():
    job = new_job(workers=3)
    set_defaults(job)
    assert replica_hostnames(job) == [
        "test-pytorchjob-master-0",
        "test-pytorchjob-worker-0",
        "test-pytorchjob-worker-1",
        "test-pytorchjob-worker-2",
    ]


def test_master_nonzero_index_rejected():
    job = new_job(workers=1)
    set_defaults(job)
    with pytest.raises(InvalidClusterSpecError, match="single master"):
        build_cluster_env(job, "Master", "1")


def test_missing_port_rejected():
    job = new_job(workers=0)
    job.spec.pytorch_replica_specs["Master"].template.spec.containers[0].ports = []
    with pytest.raises(InvalidClusterSpecError, match="port"):
        get_port_from_job(job, "Master")


def test_set_cluster_spec_appends_to_all_containers():
    job = new_job(workers=1)
    set_defaults(job)
    pod = {
        "spec": {
            "containers": [
                {"name": "pytorch", "env": [{"name": "KEEP", "value": "1"}]},
                {"name": "sidecar"},
            ]
        }
    }
    set_cluster_spec(pod, job, "0", "Worker")
    for c in pod["spec"]["containers"]:
        names = [e["name"] for e in c["env"]]
        assert "TPU_WORKER_ID" in names
        assert "MASTER_ADDR" in names
    assert pod["spec"]["containers"][0]["env"][0] == {"name": "KEEP", "value": "1"}
