// Internal TLS interface between http.cc (transport framing) and
// tls.cc (dlopen'd OpenSSL 3).  Not part of the public C API.
//
// The image ships libssl.so.3/libcrypto.so.3 but no OpenSSL headers, so
// tls.cc resolves the dozen functions it needs through dlsym against
// hand-written prototypes (the OpenSSL 1.1+/3.x ABI for these entry
// points is stable).  When the libraries are absent, every entry point
// degrades gracefully and the Python layer keeps its ssl fallback.

#ifndef TPU_OPERATOR_TLS_INTERNAL_H_
#define TPU_OPERATOR_TLS_INTERNAL_H_

#include <string>

namespace tpuop {

// True when libssl/libcrypto resolved (lazily dlopen'd on first call).
bool tls_runtime_available();

// One TLS client configuration: the OpenSSL context plus the insecure
// flag it was built with (kept together so callers can't toggle
// hostname verification out of sync with peer verification).
struct TlsConfig {
  void* ssl_ctx = nullptr;  // SSL_CTX*
  bool insecure = false;
};

// Build a client TLS config.  ca_file/cert_file/key_file may be
// null/empty; verification is ON unless `insecure` (no CA file ->
// system default verify paths).  Returns null and fills *err on failure.
TlsConfig* tls_ctx_create(const char* ca_file, const char* cert_file,
                          const char* key_file, int insecure,
                          std::string* err);
void tls_ctx_destroy(TlsConfig* cfg);

// TLS handshake over a connected blocking fd (with SO_RCVTIMEO/SNDTIMEO
// bounding every step).  server_name drives SNI + hostname/IP
// verification (skipped when the config is insecure).  Returns an
// opaque connection (SSL*) or null with *err filled.  Does NOT take
// ownership of fd.
void* tls_conn_open(TlsConfig* cfg, int fd, const char* server_name,
                    std::string* err);
void tls_conn_close(void* conn);

// tls_recv return convention (richer than recv(2) so the framing layer
// can act on HOW a stream ended — see ADVICE round-3 items on ragged
// EOF and watch timeouts):
//   >0  bytes read
//    0  clean EOF: the peer sent close_notify
//   -1  hard error
//   -2  ragged EOF: TCP FIN with no close_notify.  Indistinguishable
//       from truncation by an on-path attacker, so the read-to-EOF
//       framing in read_body treats it as an error; length-checked
//       framings (Content-Length, chunked) already detect truncation
//       themselves and treat it like EOF.
//   -3  timeout: SO_RCVTIMEO expired inside SSL_read (a partial TLS
//       record can arrive after poll(2) reported readable), or
//       WANT_READ/WANT_WRITE.  ws_next maps this to WS_TIMEOUT so a
//       slow network doesn't tear down a healthy watch stream.
constexpr long kTlsRecvCleanEof = 0;
constexpr long kTlsRecvError = -1;
constexpr long kTlsRecvRaggedEof = -2;
constexpr long kTlsRecvTimeout = -3;

long tls_recv(void* conn, char* buf, unsigned long len);

// Write everything; false on error/timeout.
bool tls_send_all(void* conn, const char* data, unsigned long len);

// Bytes already decrypted and buffered inside the TLS layer — must be
// drained before poll(2)ing the fd (poll cannot see them).
int tls_pending(void* conn);

}  // namespace tpuop

#endif  // TPU_OPERATOR_TLS_INTERNAL_H_
