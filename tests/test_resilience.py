"""Apiserver-resilience layer (ISSUE 5): transient-error
classification, retry backoff/deadline, token-bucket flow control, the
circuit-breaker state machine, verb-aware retry semantics over the
stub server's fault injection, and the http-tier sim e2e — a job
reaching Succeeded through an apiserver injecting 5xx, a 429 burst and
a mid-watch reset, with zero duplicate pods and the retry counters
visible on /metrics."""

from __future__ import annotations

import json
import re
import threading
import urllib.request

import pytest

from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.k8s import errors as k8s_errors
from pytorch_operator_tpu.k8s.errors import (
    AlreadyExistsError,
    ApiError,
    CircuitOpenError,
    ConflictError,
    InternalServerError,
    InvalidError,
    NotFoundError,
    ServerTimeoutError,
    ServiceUnavailableError,
    TooManyRequestsError,
    error_for_status,
    is_transient,
    transient_reason,
)
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.k8s.faults import FaultPlan
from pytorch_operator_tpu.k8s.resilience import (
    CircuitBreaker,
    ResilienceConfig,
    ResilienceMetrics,
    RetryPolicy,
    TokenBucket,
)
from pytorch_operator_tpu.k8s.rest import KubeConfig, RestCluster
from pytorch_operator_tpu.k8s.stub_server import StubApiServer
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.metrics.server import start_metrics_server
from pytorch_operator_tpu.runtime import JobControllerConfig
from testutil import new_job, wait_for


# ---------------------------------------------------------------------------
# Classification
# ---------------------------------------------------------------------------


class TestClassification:
    def test_transient_statuses(self):
        for err in (TooManyRequestsError("429"),
                    InternalServerError("500"),
                    ServiceUnavailableError("503"),
                    ServerTimeoutError("504"),
                    error_for_status(502, "bad gateway")):
            assert is_transient(err), err

    def test_connection_failures_are_transient(self):
        from http.client import IncompleteRead

        assert is_transient(ConnectionResetError("reset"))
        assert is_transient(TimeoutError("timed out"))
        assert is_transient(IncompleteRead(b""))

    def test_definitive_answers_are_not_transient(self):
        for err in (NotFoundError("404"), AlreadyExistsError("409"),
                    ConflictError("409"), InvalidError("422"),
                    error_for_status(418, "teapot"),
                    ValueError("not an api error")):
            assert not is_transient(err), err

    def test_circuit_open_is_never_retried(self):
        assert not is_transient(CircuitOpenError("open"))

    def test_status_mapping(self):
        assert isinstance(error_for_status(404, "x"), NotFoundError)
        assert isinstance(error_for_status(409, "already exists"),
                          AlreadyExistsError)
        assert isinstance(error_for_status(409, "rv conflict"),
                          ConflictError)
        assert isinstance(error_for_status(422, "x"), InvalidError)
        assert isinstance(error_for_status(429, "x"),
                          TooManyRequestsError)
        assert isinstance(error_for_status(503, "x"),
                          ServiceUnavailableError)
        err = error_for_status(502, "x")
        assert type(err) is ApiError and err.code == 502

    def test_retry_after_carried(self):
        err = error_for_status(429, "slow down", retry_after=3.5)
        assert err.retry_after == 3.5

    def test_reason_labels(self):
        assert transient_reason(TooManyRequestsError("")) == "throttled"
        assert transient_reason(ServiceUnavailableError("")) == \
            "server_error"
        assert transient_reason(ConnectionResetError("")) == "connection"


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------


class TestRetryPolicy:
    def test_backoff_exponential_and_capped(self):
        policy = RetryPolicy(base_backoff=0.1, max_backoff=0.8,
                             jitter=0.0, rand=lambda: 0.0)
        assert [policy.backoff(a) for a in range(5)] == \
            [0.1, 0.2, 0.4, 0.8, 0.8]

    def test_jitter_shrinks_never_grows(self):
        policy = RetryPolicy(base_backoff=0.1, max_backoff=10.0,
                             jitter=0.5, rand=lambda: 1.0)
        # rand=1.0 -> full jitter: half the nominal delay
        assert policy.backoff(0) == pytest.approx(0.05)
        policy_hi = RetryPolicy(base_backoff=0.1, max_backoff=10.0,
                                jitter=0.5, rand=lambda: 0.0)
        assert policy_hi.backoff(0) == pytest.approx(0.1)

    def test_run_retries_then_succeeds(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ServiceUnavailableError("boom")
            return "ok"

        policy = RetryPolicy(max_attempts=4, base_backoff=0.0)
        assert policy.run(flaky, retryable=is_transient) == "ok"
        assert len(calls) == 3

    def test_run_respects_max_attempts(self):
        calls = []

        def always_fails():
            calls.append(1)
            raise ServiceUnavailableError("boom")

        policy = RetryPolicy(max_attempts=3, base_backoff=0.0)
        with pytest.raises(ServiceUnavailableError):
            policy.run(always_fails, retryable=is_transient)
        assert len(calls) == 3

    def test_run_never_retries_non_retryable(self):
        calls = []

        def fails():
            calls.append(1)
            raise NotFoundError("gone")

        policy = RetryPolicy(max_attempts=5, base_backoff=0.0)
        with pytest.raises(NotFoundError):
            policy.run(fails, retryable=is_transient)
        assert len(calls) == 1

    def test_run_on_retry_hook_sees_error_and_attempt(self):
        seen = []

        def fails():
            raise ServiceUnavailableError("boom")

        policy = RetryPolicy(max_attempts=3, base_backoff=0.0)
        with pytest.raises(ServiceUnavailableError):
            policy.run(fails, retryable=is_transient,
                       on_retry=lambda e, a: seen.append((type(e), a)))
        assert seen == [(ServiceUnavailableError, 0),
                        (ServiceUnavailableError, 1)]

    def test_deadline_cuts_retries_short(self):
        # fake clock: each backoff would be 10s against a 5s deadline,
        # so the second attempt is never made
        now = [0.0]
        policy = RetryPolicy(max_attempts=10, base_backoff=10.0,
                             max_backoff=10.0, deadline=5.0, jitter=0.0,
                             rand=lambda: 0.0,
                             sleep=lambda s: now.__setitem__(0, now[0] + s),
                             clock=lambda: now[0])
        calls = []

        def fails():
            calls.append(1)
            raise ServiceUnavailableError("boom")

        with pytest.raises(ServiceUnavailableError):
            policy.run(fails, retryable=is_transient)
        assert len(calls) == 1

    def test_sleep_before_retry_honors_at_least(self):
        slept = []
        policy = RetryPolicy(base_backoff=0.01, max_backoff=0.01,
                             deadline=60.0, jitter=0.0, rand=lambda: 0.0,
                             sleep=slept.append, clock=lambda: 0.0)
        assert policy.sleep_before_retry(0, 60.0, at_least=0.7)
        assert slept == [0.7]  # the Retry-After hint wins over backoff


# ---------------------------------------------------------------------------
# TokenBucket
# ---------------------------------------------------------------------------


def _fake_timeline():
    """(clock, sleep) over a virtual timeline."""
    now = [0.0]
    return (lambda: now[0]), (lambda s: now.__setitem__(0, now[0] + s))


class TestTokenBucket:
    def test_burst_then_qps_pacing(self):
        clock, sleep = _fake_timeline()
        bucket = TokenBucket(qps=10.0, burst=3, clock=clock, sleep=sleep)
        # the burst drains for free
        assert [bucket.acquire() for _ in range(3)] == [0.0, 0.0, 0.0]
        # then one token per 1/qps
        waited = bucket.acquire()
        assert waited == pytest.approx(0.1)
        waited = bucket.acquire()
        assert waited == pytest.approx(0.1)

    def test_refill_caps_at_burst(self):
        clock, sleep = _fake_timeline()
        bucket = TokenBucket(qps=10.0, burst=2, clock=clock, sleep=sleep)
        bucket.acquire()
        bucket.acquire()
        sleep(100.0)  # a long idle refills at most `burst` tokens
        assert bucket.acquire() == 0.0
        assert bucket.acquire() == 0.0
        assert bucket.acquire() == pytest.approx(0.1)

    def test_pause_for_delays_everyone(self):
        clock, sleep = _fake_timeline()
        bucket = TokenBucket(qps=1000.0, burst=100, clock=clock,
                             sleep=sleep)
        bucket.pause_for(2.0)  # the 429 Retry-After hook
        assert bucket.acquire() == pytest.approx(2.0)
        # after the pause the bucket flows again
        assert bucket.acquire() == 0.0

    def test_qps_zero_is_unlimited(self):
        clock, sleep = _fake_timeline()
        bucket = TokenBucket(qps=0.0, clock=clock, sleep=sleep)
        assert all(bucket.acquire() == 0.0 for _ in range(100))


# ---------------------------------------------------------------------------
# CircuitBreaker state machine
# ---------------------------------------------------------------------------


class TestCircuitBreaker:
    def _breaker(self, **kw):
        now = [0.0]
        breaker = CircuitBreaker(clock=lambda: now[0], **kw)
        return breaker, now

    def test_opens_after_threshold_consecutive_failures(self):
        breaker, _ = self._breaker(threshold=3, reset_timeout=5.0)
        for _ in range(2):
            breaker.on_failure()
        assert breaker.state == "closed" and breaker.allow()
        breaker.on_failure()
        assert breaker.state == "open" and not breaker.allow()

    def test_success_resets_the_count(self):
        breaker, _ = self._breaker(threshold=3, reset_timeout=5.0)
        breaker.on_failure()
        breaker.on_failure()
        breaker.on_success()  # any definitive answer: server is alive
        breaker.on_failure()
        breaker.on_failure()
        assert breaker.state == "closed"

    def test_half_open_admits_exactly_one_probe(self):
        breaker, now = self._breaker(threshold=1, reset_timeout=5.0)
        breaker.on_failure()
        assert not breaker.allow()
        now[0] += 5.0
        assert breaker.state == "half-open"
        assert breaker.allow()       # the probe
        assert not breaker.allow()   # everyone else keeps failing fast

    def test_probe_success_closes(self):
        breaker, now = self._breaker(threshold=1, reset_timeout=5.0)
        breaker.on_failure()
        now[0] += 5.0
        assert breaker.allow()
        breaker.on_success()
        assert breaker.state == "closed"
        assert breaker.allow() and breaker.allow()

    def test_probe_failure_reopens_and_restarts_clock(self):
        breaker, now = self._breaker(threshold=1, reset_timeout=5.0)
        breaker.on_failure()
        now[0] += 5.0
        assert breaker.allow()
        breaker.on_failure()
        assert breaker.state == "open" and not breaker.allow()
        now[0] += 4.9
        assert not breaker.allow()  # clock restarted at the reopen
        now[0] += 0.2
        assert breaker.allow()

    def test_remaining_open_counts_down(self):
        breaker, now = self._breaker(threshold=1, reset_timeout=5.0)
        breaker.on_failure()
        assert breaker.remaining_open() == pytest.approx(5.0)
        now[0] += 3.0
        assert breaker.remaining_open() == pytest.approx(2.0)
        breaker.on_success()
        assert breaker.remaining_open() == 0.0

    def test_transitions_feed_the_metric(self):
        registry = Registry()
        breaker, now = self._breaker(threshold=1, reset_timeout=5.0)
        ResilienceMetrics(registry, breaker)
        breaker.on_failure()
        now[0] += 5.0
        breaker.allow()
        breaker.on_success()
        text = registry.expose()
        assert ('pytorch_operator_circuit_breaker_transitions_total'
                '{to="open"} 1') in text
        assert ('pytorch_operator_circuit_breaker_transitions_total'
                '{to="closed"} 1') in text
        assert 'pytorch_operator_circuit_breaker_state 0' in text


# ---------------------------------------------------------------------------
# Verb-aware retry semantics over real HTTP (stub server + FaultPlan)
# ---------------------------------------------------------------------------


def _pod(name: str) -> dict:
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default"},
            "spec": {}}


def _cluster_for(srv, **resilience_kw):
    defaults = dict(max_attempts=4, base_backoff=0.01, max_backoff=0.05,
                    breaker_threshold=0)
    defaults.update(resilience_kw)
    return RestCluster(KubeConfig("127.0.0.1", srv.port),
                       registry=Registry(),
                       resilience=ResilienceConfig(**defaults))


class TestRestRetrySemantics:
    def test_429_burst_retried_with_retry_after_pause(self):
        plan = FaultPlan(throttle_after=0, throttle_burst=2,
                         retry_after_s=0.02)
        srv = StubApiServer(fault_plan=plan).start()
        cluster = _cluster_for(srv, qps=100.0)
        try:
            created = cluster.pods.create("default", _pod("p429"))
            assert created["metadata"]["name"] == "p429"
            assert plan.snapshot()["throttled"] == 2
        finally:
            cluster.close()
            srv.stop()

    def test_5xx_exhausts_attempts_then_raises(self):
        plan = FaultPlan(error_rate=1.0, error_verbs=("patch",),
                         error_code=503)
        srv = StubApiServer(fault_plan=plan).start()
        cluster = _cluster_for(srv)
        try:
            cluster.pods.create("default", _pod("p5xx"))
            with pytest.raises(ServiceUnavailableError):
                cluster.pods.patch("default", "p5xx",
                                   {"metadata": {"labels": {"x": "1"}}})
            # all 4 attempts were spent on the patch
            assert plan.snapshot()["errors"] == 4
        finally:
            cluster.close()
            srv.stop()

    def test_torn_create_resolves_already_exists_as_success(self):
        """The POST ambiguity: the create COMMITS but its 201 is lost
        (injected 503 after commit).  The retry hits AlreadyExists and
        must resolve to the existing object — expectations semantics:
        the pod exists exactly once, the caller sees success."""
        plan = FaultPlan(error_rate=1.0, error_verbs=("create",),
                         error_code=503, error_when="after")
        srv = StubApiServer(fault_plan=plan).start()
        cluster = _cluster_for(srv)
        try:
            created = cluster.pods.create("default", _pod("torn"))
            assert created["metadata"]["name"] == "torn"
            assert created["metadata"]["uid"]
            # exactly one pod exists server-side
            assert len(srv.cluster.pods.list("default")) == 1
        finally:
            cluster.close()
            srv.stop()

    def test_torn_delete_resolves_not_found_as_success(self):
        """The DELETE ambiguity: the delete commits, the response is
        lost, the retry 404s — resolved as success (no lost deletes)."""
        plan = FaultPlan(error_rate=1.0, error_verbs=("delete",),
                         error_code=503, error_when="after")
        srv = StubApiServer(fault_plan=plan).start()
        cluster = _cluster_for(srv)
        try:
            cluster.pods.create("default", _pod("doomed"))
            cluster.pods.delete("default", "doomed")  # must not raise
            assert srv.cluster.pods.list("default") == []
        finally:
            cluster.close()
            srv.stop()

    def test_first_attempt_already_exists_still_raises(self):
        """AlreadyExists on a FIRST attempt is a real duplicate create
        (someone else made the object) and must propagate — only the
        retry path may resolve it."""
        srv = StubApiServer().start()
        cluster = _cluster_for(srv)
        try:
            cluster.pods.create("default", _pod("dup"))
            with pytest.raises(AlreadyExistsError):
                cluster.pods.create("default", _pod("dup"))
        finally:
            cluster.close()
            srv.stop()

    def test_breaker_opens_fails_fast_and_recovers(self):
        plan = FaultPlan(error_rate=1.0, error_verbs=("create",),
                         error_code=503)
        srv = StubApiServer(fault_plan=plan).start()
        cluster = _cluster_for(srv, max_attempts=1, breaker_threshold=2,
                               breaker_reset=0.2)
        try:
            for _ in range(2):
                with pytest.raises(ServiceUnavailableError):
                    cluster.pods.create("default", _pod("pb"))
            before = plan.snapshot()["requests"]
            with pytest.raises(CircuitOpenError) as exc:
                cluster.pods.create("default", _pod("pb"))
            # failed fast: no request reached the server, and the error
            # carries the requeue hint
            assert plan.snapshot()["requests"] == before
            assert 0 < exc.value.retry_in <= 0.2
            assert cluster.resilience_snapshot()["state"] == "open"
            # server heals; the half-open probe closes the breaker
            plan.error_rate = 0.0
            assert wait_for(lambda: cluster.breaker.allow(), timeout=2)
            cluster.breaker.on_success()  # hand the probe slot back
            created = cluster.pods.create("default", _pod("pb"))
            assert created["metadata"]["name"] == "pb"
            assert cluster.resilience_snapshot()["state"] == "closed"
        finally:
            cluster.close()
            srv.stop()

    def test_429_answered_to_half_open_probe_closes_not_wedges(self):
        """A 429 is a LIVE answer: answered to the half-open probe it
        must release the probe slot and close the breaker — excluding
        429 from on_failure without the on_success path would latch
        _probing and wedge the client open forever."""
        plan = FaultPlan(error_rate=1.0, error_verbs=("create",),
                         error_code=503)
        srv = StubApiServer(fault_plan=plan).start()
        cluster = _cluster_for(srv, max_attempts=1, breaker_threshold=1,
                               breaker_reset=0.05)
        try:
            with pytest.raises(ServiceUnavailableError):
                cluster.pods.create("default", _pod("pw"))
            assert cluster.breaker.state == "open"
            # server recovers but sheds the probe with 429
            plan.error_rate = 0.0
            plan.arm_throttle_burst(1, retry_after_s=0.01)
            assert wait_for(lambda: cluster.breaker.state == "half-open",
                            timeout=2)
            with pytest.raises(TooManyRequestsError):
                cluster.pods.create("default", _pod("pw"))
            # the 429 closed the breaker instead of wedging the probe
            assert cluster.breaker.state == "closed"
            created = cluster.pods.create("default", _pod("pw"))
            assert created["metadata"]["name"] == "pw"
        finally:
            cluster.close()
            srv.stop()

    def test_retry_metrics_exported(self):
        plan = FaultPlan(throttle_after=0, throttle_burst=1,
                         retry_after_s=0.01)
        srv = StubApiServer(fault_plan=plan).start()
        registry = Registry()
        cluster = RestCluster(
            KubeConfig("127.0.0.1", srv.port), registry=registry,
            resilience=ResilienceConfig(max_attempts=3,
                                        base_backoff=0.01, qps=50.0))
        try:
            cluster.pods.create("default", _pod("pm"))
            text = registry.expose()
            assert ('pytorch_operator_rest_retries_total'
                    '{verb="create",reason="throttled"} 1') in text
            assert 'pytorch_operator_circuit_breaker_state 0' in text
        finally:
            cluster.close()
            srv.stop()


# ---------------------------------------------------------------------------
# Sim-tier fault injection (FakeCluster consults the same plan)
# ---------------------------------------------------------------------------


def test_fake_cluster_injects_classified_errors():
    plan = FaultPlan(error_rate=1.0, error_verbs=("create",),
                     error_code=503)
    cluster = FakeCluster(fault_plan=plan)
    with pytest.raises(ServiceUnavailableError):
        cluster.pods.create("default", _pod("px"))
    plan.error_rate = 0.0
    cluster.pods.create("default", _pod("px"))
    assert len(cluster.pods.list("default")) == 1


def test_fake_cluster_rejects_after_commit_faults_loudly():
    """error_when='after' (torn response) needs response framing to
    tear — only the stub server models it.  The fake must refuse
    loudly, not silently run a different scenario than the test asked
    for."""
    cluster = FakeCluster(fault_plan=FaultPlan(
        error_rate=1.0, error_when="after"))
    with pytest.raises(ValueError, match="http-tier-only"):
        cluster.pods.create("default", _pod("pa"))


# ---------------------------------------------------------------------------
# http-tier sim e2e: Succeeded through an unreliable apiserver
# ---------------------------------------------------------------------------


@pytest.fixture
def chaos_world(e2e_artifacts):
    """Operator over real HTTP against a stub apiserver executing the
    chaos plan (transient 5xx on every mutating verb + one 429 burst +
    watch resets), with the resilience layer on and /metrics served."""
    plan = FaultPlan(error_rate=0.10, error_code=503,
                     throttle_after=20, throttle_burst=4,
                     retry_after_s=0.05, watch_reset_every=25, seed=5)
    srv = StubApiServer(fault_plan=plan).start()
    kubelet = FakeKubelet(srv.cluster)
    kubelet.start()
    registry = Registry()
    rest = RestCluster(
        KubeConfig("127.0.0.1", srv.port), namespace="default",
        registry=registry,
        resilience=ResilienceConfig(qps=200.0, burst=400, max_attempts=5,
                                    base_backoff=0.02, max_backoff=0.2,
                                    breaker_threshold=5,
                                    breaker_reset=0.3))
    ctl = PyTorchController(rest, config=JobControllerConfig(),
                            registry=registry)
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    server = start_metrics_server(registry, 0, host="127.0.0.1")
    e2e_artifacts["port"] = server.server_address[1]
    # a failing run additionally captures breaker + retry state
    e2e_artifacts["extra"]["resilience.json"] = (
        lambda: json.dumps({"breaker": rest.resilience_snapshot(),
                            "faults": plan.snapshot(),
                            "server_responses": dict(srv.counters)},
                           indent=1))
    yield srv, plan, rest, ctl, registry, server.server_address[1]
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()
    rest.close()
    server.shutdown()
    srv.stop()


def test_e2e_job_succeeds_through_chaotic_apiserver(chaos_world):
    srv, plan, rest, ctl, registry, port = chaos_world
    srv.cluster.jobs.create("default",
                            new_job(workers=3, name="chaos-job").to_dict())

    def succeeded():
        try:
            job = srv.cluster.jobs.get("default", "chaos-job")
        except NotFoundError:
            return False
        return any(c.get("type") == "Succeeded"
                   and c.get("status") == "True"
                   for c in (job.get("status") or {}).get("conditions")
                   or [])

    assert wait_for(succeeded, timeout=60), (
        f"job stuck; faults={plan.snapshot()} "
        f"responses={dict(srv.counters)} "
        f"breaker={rest.resilience_snapshot()}")

    # the plan genuinely fired (the e2e exercised faults, not a
    # fault-free pass) ...
    snapshot = plan.snapshot()
    assert snapshot["errors"] + snapshot["throttled"] > 0
    # ... and the expectations ledger held: exactly the declared gang,
    # every pod name unique, zero duplicate-create conflicts at the
    # server (an AlreadyExists answered to a FIRST attempt would count
    # here; retry-resolved ones cannot occur with error_when=before)
    pods = srv.cluster.pods.list("default")
    assert len(pods) == 4
    assert len({p["metadata"]["name"] for p in pods}) == 4
    assert srv.counters.get("POST 409", 0) == 0

    # retry counters are visible on the operator's /metrics
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
    retried = sum(
        int(v) for v in re.findall(
            r'pytorch_operator_rest_retries_total\{[^}]*\} (\d+)', text))
    assert retried > 0
    assert "pytorch_operator_circuit_breaker_state" in text
    # the breaker ended the run closed (the apiserver was flaky, not
    # down) and the job never lost a delete or duplicated a create
    assert rest.resilience_snapshot()["state"] in ("closed", "disabled")


def test_watch_reset_heals_via_gap_relist():
    """A watch stream torn down mid-event must surface as a GAP (not a
    clean EOF): the informer relists and no event is silently lost."""
    plan = FaultPlan(watch_reset_every=1)  # every event tears the stream
    srv = StubApiServer(fault_plan=plan).start()
    cluster = _cluster_for(srv)
    seen = []
    try:
        cluster.pods.add_listener(lambda et, obj: seen.append(
            (et, (obj.get("metadata") or {}).get("name"))))
        srv.cluster.pods.create("default", _pod("w1"))
        # the event is truncated mid-line; the stream dies; the client
        # must report GAP so the informer's relist can heal the cache
        assert wait_for(lambda: ("GAP", "") in [(e, n or "")
                                                for e, n in seen],
                        timeout=10), seen
        assert plan.snapshot()["watch_resets"] >= 1
    finally:
        cluster.close()
        srv.stop()


# ---------------------------------------------------------------------------
# CLI flag surface
# ---------------------------------------------------------------------------


def test_operator_resilience_flags_parse():
    from pytorch_operator_tpu.cmd.operator import build_parser

    args = build_parser().parse_args(
        ["--kube-api-qps", "20", "--kube-api-burst", "40",
         "--kube-api-retries", "3", "--circuit-breaker-threshold", "7",
         "--circuit-breaker-reset", "2s"])
    assert args.qps == 20.0 and args.burst == 40
    assert args.kube_api_retries == 3
    assert args.circuit_breaker_threshold == 7
    assert args.circuit_breaker_reset == "2s"
    # the historical spellings stay valid
    legacy = build_parser().parse_args(["--qps", "9", "--burst", "18"])
    assert legacy.qps == 9.0 and legacy.burst == 18


# ---------------------------------------------------------------------------
# Closed-client guard on the shared per-endpoint breaker (ISSUE 8
# satellite: the --shards kill round's benign blip)
# ---------------------------------------------------------------------------


class TestClosedClientBreakerGuard:
    @staticmethod
    def _dead_port() -> int:
        """A port nothing listens on: connects are REFUSED instantly
        (a merely-stopped stub server still has a bound socket whose
        backlog accepts and then hangs the request)."""
        import socket

        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()
        return port

    def test_closing_clients_errors_never_strike_the_shared_breaker(self):
        port = self._dead_port()
        cfg = ResilienceConfig(max_attempts=1, breaker_threshold=2,
                               breaker_reset=60.0)
        dying = RestCluster(KubeConfig("127.0.0.1", port),
                            resilience=cfg)
        survivor = RestCluster(KubeConfig("127.0.0.1", port),
                               resilience=cfg)
        # same endpoint + same knobs -> ONE shared breaker
        assert dying.breaker is survivor.breaker

        dying.close()  # teardown begins: its errors are OUR fault
        for _ in range(5):
            with pytest.raises(Exception):
                dying.pods.list("default")
        snap = survivor.breaker.snapshot()
        assert snap["state"] == "closed"
        assert snap["consecutive_failures"] == 0

        # sanity: the NOT-closing client's identical failures do strike
        for _ in range(2):
            with pytest.raises(Exception):
                survivor.pods.list("default")
        assert survivor.breaker.state == "open"
        survivor.close()

    def test_closed_client_does_not_burn_retries_on_teardown(self):
        """A closing client's connection error raises immediately —
        retry sleeps against a dying socket only slow teardown down."""
        cluster = RestCluster(
            KubeConfig("127.0.0.1", self._dead_port()),
            resilience=ResilienceConfig(max_attempts=4,
                                        base_backoff=5.0,
                                        breaker_threshold=0))
        cluster.close()
        import time as _time

        t0 = _time.monotonic()
        with pytest.raises(Exception):
            cluster.pods.list("default")
        # no backoff sleeps were paid (4 attempts x 5s base otherwise)
        assert _time.monotonic() - t0 < 2.0

    def test_closed_client_request_text_spares_shared_breaker(self):
        """ISSUE 12 satellite (f): the closed-client guard extends to
        the raw-text path.  The multicore bench scrapes per-replica
        /metrics through request_text; a replica exiting mid-scrape
        must not fail the scraper's SHARED breaker open against the
        still-healthy stub apiserver."""
        port = self._dead_port()
        cfg = ResilienceConfig(max_attempts=1, breaker_threshold=2,
                               breaker_reset=60.0)
        dying = RestCluster(KubeConfig("127.0.0.1", port),
                            resilience=cfg)
        survivor = RestCluster(KubeConfig("127.0.0.1", port),
                               resilience=cfg)
        assert dying.breaker is survivor.breaker

        dying.close()
        for _ in range(5):
            with pytest.raises(Exception):
                dying.client.request_text("GET", "/metrics")
        snap = survivor.breaker.snapshot()
        assert snap["state"] == "closed"
        assert snap["consecutive_failures"] == 0

        # sanity: the NOT-closed client's scrape failures DO strike
        for _ in range(2):
            with pytest.raises(Exception):
                survivor.client.request_text("GET", "/metrics")
        assert survivor.breaker.state == "open"
        survivor.close()
