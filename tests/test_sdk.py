"""SDK client tests against the simulated cluster.

Mirrors the reference's SDK e2e flow
(sdk/python/test/test_e2e.py:33-81: create -> wait_for_job -> assert
succeeded -> get logs -> delete) with the fake cluster + controller +
kubelet standing in for GKE.
"""

from __future__ import annotations

import threading

import pytest

from pytorch_operator_tpu.api.v1 import constants
from pytorch_operator_tpu.controller import PyTorchController
from pytorch_operator_tpu.k8s.errors import NotFoundError
from pytorch_operator_tpu.k8s.fake import FakeCluster
from pytorch_operator_tpu.k8s.fake_kubelet import FakeKubelet
from pytorch_operator_tpu.metrics.prometheus import Registry
from pytorch_operator_tpu.runtime import JobControllerConfig
from pytorch_operator_tpu.sdk import PyTorchJobClient
from pytorch_operator_tpu.sdk import utils as sdk_utils

from testutil import new_job


@pytest.fixture
def world():
    cluster = FakeCluster()
    ctl = PyTorchController(
        cluster, config=JobControllerConfig(), registry=Registry())
    kubelet = FakeKubelet(cluster)
    kubelet.start()
    stop = threading.Event()
    ctl.run(threadiness=2, stop_event=stop)
    yield cluster
    stop.set()
    ctl.work_queue.shutdown()
    kubelet.stop()


@pytest.fixture
def client(world):
    return PyTorchJobClient(cluster=world)


class TestSdkLifecycle:
    def test_create_wait_logs_delete(self, world, client):
        job = new_job(workers=1, name="sdk-job")
        created = client.create(job.to_dict())
        assert created["metadata"]["name"] == "sdk-job"

        finished = client.wait_for_job(
            "sdk-job", timeout_seconds=15, polling_interval=0.05)
        assert client.is_job_succeeded("sdk-job")
        assert finished["status"]["replicaStatuses"]["Master"]["succeeded"] == 1

        # master-only by default, like the reference get_logs
        logs = client.get_logs("sdk-job")
        assert list(logs) == ["sdk-job-master-0"]
        assert "accuracy=" in logs["sdk-job-master-0"]

        all_pods = client.get_pod_names("sdk-job")
        assert set(all_pods) == {"sdk-job-master-0", "sdk-job-worker-0"}
        workers = client.get_pod_names("sdk-job", replica_type="worker")
        assert workers == ["sdk-job-worker-0"]

        client.delete("sdk-job")
        with pytest.raises(NotFoundError):
            client.get("sdk-job")

    def test_create_dataclass_job(self, client):
        job = new_job(workers=0, name="dc-job")
        client.create(job)  # dataclass, not dict
        got = client.get("dc-job")
        assert got["kind"] == constants.KIND

    def test_get_list(self, client):
        client.create(new_job(workers=0, name="a").to_dict())
        client.create(new_job(workers=0, name="b").to_dict())
        items = client.get()["items"]
        assert {j["metadata"]["name"] for j in items} >= {"a", "b"}

    def test_get_job_status_progression(self, client):
        client.create(new_job(workers=0, name="st-job").to_dict())
        client.wait_for_job("st-job", timeout_seconds=15, polling_interval=0.05)
        assert client.get_job_status("st-job") == constants.JOB_SUCCEEDED
        assert not client.is_job_running("st-job")

    def test_wait_timeout_raises(self, world):
        # no kubelet progress for this job: decide() leaves pods running
        client = PyTorchJobClient(cluster=world)
        job = new_job(workers=0, name="stuck-job")
        # fresh cluster object w/o kubelet interference is complex; instead
        # wait on a nonexistent condition with a tiny timeout
        client.create(job.to_dict())
        with pytest.raises(RuntimeError, match="timeout"):
            client.wait_for_condition(
                "stuck-job", ["NeverHappens"],
                timeout_seconds=0.2, polling_interval=0.05)

    def test_patch(self, client):
        client.create(new_job(workers=1, name="p-job").to_dict())
        client.patch("p-job", {"metadata": {"labels": {"team": "ml"}}})
        assert client.get("p-job")["metadata"]["labels"]["team"] == "ml"


class TestSdkUtils:
    def test_labels_master(self):
        labels = sdk_utils.get_labels("j", master=True)
        assert labels[constants.LABEL_JOB_ROLE] == "master"
        assert labels[constants.LABEL_PYTORCH_JOB_NAME] == "j"

    def test_selector_string(self):
        s = sdk_utils.to_selector({"a": "1", "b": "2"})
        assert s == "a=1,b=2"

    def test_default_namespace(self):
        assert sdk_utils.get_default_target_namespace() == "default"


def test_watch_gap_with_deleted_job_reports_deleted(world, capsys):
    """A job deleted during a watch-stream outage must surface as
    Deleted when the GAP re-read finds it gone — not hang to timeout
    (round-4 review finding on sdk/watch.py)."""
    client = PyTorchJobClient(cluster=world)
    # the job is never created: to the GAP re-read this is exactly the
    # deleted-during-outage state, without racing the fake kubelet
    # driving a real job to Succeeded before the injected deletion

    done = {}

    def run():
        try:
            client.get("gap-job", watch=True, timeout_seconds=20)
            done["ok"] = True
        except Exception as e:  # pragma: no cover - surfaced below
            done["error"] = e

    base_listeners = len(world.jobs._listeners)  # controller's informer
    t = threading.Thread(target=run, daemon=True)
    t.start()
    pause = threading.Event()
    # wait for the WATCHER's listener (beyond the controller's), then
    # delete + inject a GAP the way a stream error would deliver it
    for _ in range(200):
        if len(world.jobs._listeners) > base_listeners:
            break
        pause.wait(0.05)
    else:
        pytest.fail("watcher never subscribed")
    # deliver a GAP (stream error; any DELETED was lost in the outage)
    for fn in list(world.jobs._listeners):
        fn("GAP", {})
    t.join(timeout=10)
    assert not t.is_alive(), "watch hung after GAP + deletion"
    assert done.get("ok"), done.get("error")
    out = capsys.readouterr().out
    assert "Deleted" in out


def test_watch_table_output(world, capsys):
    client = PyTorchJobClient(cluster=world)
    client.create(new_job(workers=0, name="w-job").to_dict())
    client.wait_for_job("w-job", namespace="default", timeout_seconds=15,
                        polling_interval=0.05)
    client.get("w-job", watch=True, timeout_seconds=5)
    out = capsys.readouterr().out
    assert "NAME" in out and "STATE" in out
    assert "w-job" in out and "Succeeded" in out
