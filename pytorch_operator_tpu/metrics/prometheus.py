"""Minimal Prometheus client: counters, gauges, text exposition.

Replaces the reference's promauto/prometheus dependency
(pkg/controller.v1/pytorch/{controller.go:60-70,job.go:26-33,status.go:47-59}
and cmd/.../server.go:58-61).  The exposition format follows
https://prometheus.io/docs/instrumenting/exposition_formats/ (text 0.0.4)
so the scrape annotations in manifests/service.yaml keep working.
"""

from __future__ import annotations

import threading
from typing import Dict, List


class _Metric:
    def __init__(self, name: str, help_text: str, metric_type: str):
        self.name = name
        self.help = help_text
        self.type = metric_type
        self._value = 0.0
        self._lock = threading.Lock()

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def expose(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} {self.type}\n"
            f"{self.name} {self._format(self.value)}\n"
        )

    @staticmethod
    def _format(v: float) -> str:
        return str(int(v)) if float(v).is_integer() else repr(v)


class Counter(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "counter")

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount


class Gauge(_Metric):
    def __init__(self, name: str, help_text: str = ""):
        super().__init__(name, help_text, "gauge")

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount


class Registry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get_or_create(name, help_text, Counter)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get_or_create(name, help_text, Gauge)

    def _get_or_create(self, name, help_text, cls):
        with self._lock:
            metric = self._metrics.get(name)
            if metric is None:
                metric = cls(name, help_text)
                self._metrics[name] = metric
            return metric

    def expose(self) -> str:
        with self._lock:
            metrics: List[_Metric] = sorted(self._metrics.values(), key=lambda m: m.name)
        return "".join(m.expose() for m in metrics)


default_registry = Registry()
