#!/usr/bin/env python
"""Concurrency & determinism lint driver.

Runs the :mod:`pytorch_operator_tpu.analysis` AST rules over the tree
(default: the package + scripts/) and reports findings.  Waived
findings (``# lint: <rule>-ok <reason>``) are listed but do not fail
the gate; every waiver must carry a reason.

Exit codes: 0 clean (possibly with waived findings), 1 unwaived
findings, 2 usage error.

    python scripts/lint.py                 # whole tree
    python scripts/lint.py path/to/file.py # specific files/dirs
    python scripts/lint.py --json          # machine-readable
    python scripts/lint.py --list-rules    # rule catalog + pragmas
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from pytorch_operator_tpu.analysis import engine  # noqa: E402
from pytorch_operator_tpu.analysis.rules import RULES  # noqa: E402


def _list_rules() -> str:
    lines = ["rule catalog (pragma: # lint: <rule>-ok <reason>):", ""]
    for key, (fn, scope) in sorted(RULES.items()):
        doc = (fn.__doc__ or "").strip().splitlines()[0]
        where = {"is_clock_injectable": "clock-injectable modules",
                 "is_reconcile_path": "reconcile-path modules",
                 "is_cache_consumer": "cache-consumer modules",
                 None: "whole tree"}[scope]
        lines.append(f"  {key:18s} [{where}]")
        lines.append(f"    {doc}")
    lines += ["", "engine findings (not waivable):",
              "  parse-error, waiver-missing-reason, unused-waiver, "
              "unknown-pragma",
              "  flag-docs-drift (tree runs: cmd/operator.py flags vs "
              "developer_guide.md)"]
    return "\n".join(lines)


#: repo-local flags look like ``--resync-period``; the pattern excludes
#: underscores on purpose so external XLA/absl-style flags mentioned in
#: prose (``--xla_force_host_platform_device_count``) are never checked
_FLAG_RE = re.compile(r"--[A-Za-z0-9][A-Za-z0-9-]*")

#: where repo flags are DEFINED — the universe a guide-documented flag
#: must exist in (operator argparse, helper scripts, pytest conftest
#: options, the stub apiserver's CLI, run-tests.sh knobs)
_FLAG_UNIVERSE_GLOBS = (
    ("pytorch_operator_tpu/cmd", ".py"),
    ("scripts", ".py"),
    ("scripts", ".sh"),
    ("tests", ".py"),
    ("pytorch_operator_tpu/k8s", ".py"),
)


def _flag_docs_findings(root: str):
    """Flags-vs-docs drift, mirroring the metric doc-drift test: every
    ``cmd/operator.py`` flag must appear in developer_guide.md, and
    every repo-style flag the guide documents must be defined somewhere
    in the tree (a renamed/removed flag leaves the doc stale)."""
    guide_path = os.path.join(root, "developer_guide.md")
    op_rel = "pytorch_operator_tpu/cmd/operator.py"
    op_path = os.path.join(root, op_rel)
    if not (os.path.exists(guide_path) and os.path.exists(op_path)):
        return []
    findings = []

    with open(guide_path) as fh:
        guide_lines = fh.read().splitlines()
    guide_flags = {}
    for lineno, line in enumerate(guide_lines, 1):
        for m in _FLAG_RE.finditer(line):
            guide_flags.setdefault(m.group(0), lineno)

    with open(op_path) as fh:
        tree = ast.parse(fh.read())
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"):
            continue
        spellings = [a.value for a in node.args
                     if isinstance(a, ast.Constant)
                     and isinstance(a.value, str)
                     and a.value.startswith("--")]
        if spellings and not any(s in guide_flags for s in spellings):
            findings.append(engine.Finding(
                rule="flag-docs-drift", path=op_rel, line=node.lineno,
                message=(f"operator flag {spellings[0]} is not documented "
                         f"in developer_guide.md — add it to the flag "
                         f"reference (or drop the flag)"),
                end_line=node.lineno))

    universe = set()
    for rel_dir, suffix in _FLAG_UNIVERSE_GLOBS:
        dir_path = os.path.join(root, rel_dir)
        if not os.path.isdir(dir_path):
            continue
        for name in os.listdir(dir_path):
            if not name.endswith(suffix):
                continue
            try:
                with open(os.path.join(dir_path, name),
                          errors="replace") as fh:
                    universe.update(_FLAG_RE.findall(fh.read()))
            except OSError:
                continue
    for flag, lineno in sorted(guide_flags.items()):
        if flag not in universe:
            findings.append(engine.Finding(
                rule="flag-docs-drift", path="developer_guide.md",
                line=lineno,
                message=(f"documented flag {flag} is not defined anywhere "
                         f"in the tree — stale doc (renamed or removed "
                         f"flag?)"),
                end_line=lineno))
    return findings


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="concurrency & determinism lint")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to scan (default: whole tree)")
    parser.add_argument("--json", action="store_true",
                        help="emit findings as JSON")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress waived findings in the listing")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0

    if args.paths:
        missing = [p for p in args.paths if not os.path.exists(p)]
        if missing:
            print(f"lint: no such path: {', '.join(missing)}",
                  file=sys.stderr)
            return 2
        findings = engine.scan_paths(args.paths, root=os.getcwd())
    else:
        findings = engine.scan_tree(_REPO_ROOT)
        findings.extend(_flag_docs_findings(_REPO_ROOT))

    bad = engine.unwaived(findings)
    waived = [f for f in findings if f.waived]

    if args.json:
        print(json.dumps([f.__dict__ for f in findings], indent=2))
    else:
        for f in bad:
            print(f.format())
        if not args.quiet:
            for f in waived:
                print(f.format())
        print(f"lint: {len(bad)} finding(s), {len(waived)} waived")
    if bad and not args.paths:
        # tree-wide gate failed: archive the machine-readable findings
        # next to the e2e flight-recorder captures so CI keeps evidence
        out_dir = os.environ.get(
            "E2E_ARTIFACTS_DIR",
            os.path.join(_REPO_ROOT, "test-artifacts"))
        try:
            os.makedirs(out_dir, exist_ok=True)
            out_path = os.path.join(out_dir, "lint-findings.json")
            with open(out_path, "w") as fh:
                json.dump([f.__dict__ for f in findings], fh, indent=2)
            print(f"lint: findings archived to {out_path}",
                  file=sys.stderr)
        except OSError as e:
            print(f"lint: could not archive findings: {e}",
                  file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
