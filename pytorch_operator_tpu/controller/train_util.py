"""Exit-code retry classification for RestartPolicy=ExitCode.

Behavioral mirror of the reference's
vendor/github.com/kubeflow/tf-operator/pkg/util/train/train_util.go:18-53,
extended with a TPU-aware set: libtpu initialization races and device
preemptions surface as SIGABRT (134) or SIGBUS (135) on TPU VMs, which are
transient (another worker held the chip lock, or the slice was being
re-gang-scheduled) — so they are classified retryable here.  The
documented user contract is preserved: 1-127 permanent unless listed,
128+n follows the signal semantics, 138 (SIGUSR1) is the user-defined
retryable code.
"""

from __future__ import annotations

# Permanent: general errors, shell misuse, cannot execute, not found,
# invalid exit argument, SIGSEGV.
_PERMANENT = frozenset({1, 2, 126, 127, 128, 139})

# Transient by signal: SIGINT (130), SIGKILL (137), SIGTERM (143) —
# typically VM reschedules or preemptions.
_RETRYABLE_SIGNALS = frozenset({130, 137, 143})

# User-defined retryable (SIGUSR1).
USER_DEFINED_RETRYABLE_EXIT_CODE = 138

# TPU-specific transients: SIGABRT (134, libtpu chip-lock contention /
# coordinator timeouts abort the process) and SIGBUS (135, HBM mapping
# teardown during slice preemption).
_TPU_RETRYABLE = frozenset({134, 135})


def is_retryable_exit_code(exit_code: int, tpu_aware: bool = True) -> bool:
    if exit_code in _PERMANENT:
        return False
    if exit_code in _RETRYABLE_SIGNALS:
        return True
    if exit_code == USER_DEFINED_RETRYABLE_EXIT_CODE:
        return True
    if tpu_aware and exit_code in _TPU_RETRYABLE:
        return True
    # No guarantee for other exit codes: treat as permanent.
    return False
