"""Sharded training-step factory for the Llama flagship model.

This replaces the reference's data-plane recipe (DDP wrap + per-step
allreduce, reference: examples/mnist/mnist.py:135-143) with a single
jitted step over a named mesh: parameters laid out by
`llama.param_specs`, batch split over dp+fsdp, gradients reduced by the
collectives GSPMD inserts.  One function covers dp, fsdp and tp — the
mesh shape is the only knob, which is the TPU analogue of the
reference's WORLD_SIZE env wiring (pod.go:234-281).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_operator_tpu.models import llama
from pytorch_operator_tpu.parallel.mesh import batch_spec
from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jax.Array


def cross_entropy_loss(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """Mean next-token cross entropy; logits (B,T,V), targets (B,T)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def chunked_tied_ce(h: jax.Array, embed: jax.Array, targets: jax.Array,
                    chunk: int = 1024) -> jax.Array:
    """Mean next-token CE with the weight-tied head applied per T-chunk.

    h (B, T, D) final hidden states, embed (V, D), targets (B, T).
    Equivalent to cross_entropy_loss(h @ embed^T, targets) but the
    (T, V) f32 logits — and the two logits-sized scatter-add buffers
    the CE backward materialises — only ever exist chunk rows at a
    time (jax.checkpoint recomputes each chunk's logits in the
    backward).  At T=32k/V=32k that's 260 MB of transient instead of
    3.9 GB x2 resident, which is what lets the 32k single-chip config
    train (the attention-preserving save_attn remat fits; these CE
    buffers were the next OOM).
    """
    B, T, D = h.shape
    chunk = min(chunk, T)
    # a ragged final slice (T % chunk) just becomes a smaller chunk —
    # at most one extra trace; collapsing to a single full-T chunk here
    # would silently reintroduce the resident (T, V) buffers this
    # function exists to avoid

    @jax.checkpoint
    def one(hc, tc):
        logits = jnp.einsum("btd,vd->btv", hc, embed).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return jnp.sum(jnp.take_along_axis(logp, tc[..., None], axis=-1))

    total = jnp.zeros((), jnp.float32)
    for i in range(0, T, chunk):
        total += one(h[:, i:i + chunk], targets[:, i:i + chunk])
    return -total / (B * T)


def state_shardings(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    specs: Any = None,
) -> TrainState:
    """The NamedSharding tree of a TrainState laid out on ``mesh`` —
    ``get_sharding_tree`` (SNIPPETS.md [2]) generalised to the llama
    layouts, and the single source the init, the cross-mesh reshard and
    the checkpoint restore all draw from.

    Optimizer-state leaves that mirror a parameter (adam mu/nu subtrees
    repeat the param pytree, so their key paths end with the param's key
    path) inherit that parameter's sharding; scalars (counts) replicate.
    Matching must be by path, not shape: wq (L,D,nh*hd) and wo
    (L,nh*hd,D) have identical shapes for nh*hd == D but transposed
    specs.  ``specs`` defaults to the (dp, fsdp, tp) layout; pass
    llama.pp_param_specs(cfg) for the pipeline layout.
    """
    if specs is None:
        specs = llama.param_specs(cfg)
    p_shardings = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
    replicated = NamedSharding(mesh, P())

    param_shapes = jax.eval_shape(
        partial(llama.init_params, cfg=cfg), jax.random.key(0)
    )
    param_paths = [
        (tuple(path), leaf.shape)
        for path, leaf in jax.tree_util.tree_flatten_with_path(param_shapes)[0]
    ]
    path_to_sharding = {
        path: sh
        for (path, _), sh in zip(param_paths, jax.tree.leaves(p_shardings))
    }

    def leaf_sharding(path, leaf):
        path = tuple(path)
        for ppath, sh in path_to_sharding.items():
            if path[-len(ppath):] == ppath:
                return sh
        return replicated

    opt_shape = jax.eval_shape(optimizer.init, param_shapes)
    opt_shardings = jax.tree_util.tree_map_with_path(leaf_sharding, opt_shape)
    return TrainState(p_shardings, opt_shardings, replicated)


def sharded_init(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    seed: int = 0,
    specs: Any = None,
) -> TrainState:
    """Initialise params + opt state directly into their shardings.

    jit with out_shardings means each device materialises only its own
    parameter shard — no host-side full copy, which is what lets 7B+
    configs initialise on a v5p slice.  ``specs`` defaults to the
    (dp, fsdp, tp) layout; pass llama.pp_param_specs(cfg) for the
    pipeline layout.
    """
    out_shardings = state_shardings(cfg, mesh, optimizer, specs=specs)

    def init(key):
        params = llama.init_params(key, cfg)
        return TrainState(params, optimizer.init(params), jnp.zeros((), jnp.int32))

    return jax.jit(init, out_shardings=out_shardings)(jax.random.key(seed))


def reshard_state(
    state: TrainState,
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    specs: Any = None,
) -> TrainState:
    """Move a live TrainState onto a different mesh shape.

    The elastic-resize data-plane primitive: a state built on an
    N-device mesh re-lays itself out for an M-device mesh by
    device_put-ing every leaf through the new mesh's sharding tree —
    values are bit-identical, only the device layout changes, so a gang
    that shrank from 8 to 6 workers (or a checkpoint-resume at a new
    world size) keeps training without a numeric discontinuity.
    """
    shardings = state_shardings(cfg, mesh, optimizer, specs=specs)
    # one batched device_put over the whole pytree (not a per-leaf
    # tree.map): the runtime can overlap the cross-mesh transfers,
    # which is the elastic-shrink critical path on a real fleet
    return jax.device_put(state, shardings)


def restore_on_mesh(mngr, step: int, target_state: TrainState) -> TrainState:
    """Orbax restore onto ``target_state``'s own shardings.

    ``target_state`` is a freshly initialised state on the CURRENT mesh
    (any world size); the checkpoint may have been written from a
    different mesh shape — orbax reshards each array onto the abstract
    tree's shardings during restore, which is what lets run 2 of a
    checkpoint-resume legally run at a different world size than the
    run that saved.
    """
    import orbax.checkpoint as ocp

    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
        target_state,
    )
    return mngr.restore(step, args=ocp.args.StandardRestore(abstract))


def _make_step(
    forward_fn: Callable[[Any, jax.Array], jax.Array],
    data_sharding: NamedSharding,
    optimizer: optax.GradientTransformation,
    hidden_fn: Callable[[Any, jax.Array], jax.Array] | None = None,
    ce_chunk: int = 1024,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, dict]]:
    """Shared step builder: grad of next-token loss over ``forward_fn``,
    optimizer update, donated state.  The forward (dense vs pipelined)
    and the batch layout are the only things that vary between the
    parallel strategies.  When ``hidden_fn`` is given the loss runs the
    weight-tied head per sequence chunk (chunked_tied_ce) so the
    (T, vocab) logits never materialise — the long-context path."""

    def loss_fn(params, batch):
        inputs, targets = batch[:, :-1], batch[:, 1:]
        if hidden_fn is not None:
            h = hidden_fn(params, inputs)
            return chunked_tied_ce(h, params["embed"], targets, ce_chunk)
        return cross_entropy_loss(forward_fn(params, inputs), targets)

    def step(state: TrainState, batch: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(params, opt_state, state.step + 1)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    return jax.jit(
        step,
        in_shardings=(None, data_sharding),
        donate_argnums=(0,),
    )


def make_train_step(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    chunked_ce: bool = False,
    ce_chunk: int = 1024,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, dict]]:
    """Build the jitted full training step.

    Batch is an int32 (B, T+1) token array; step returns the new state
    (donated in-place) and a metrics dict.  ``chunked_ce`` applies the
    tied output head per ``ce_chunk`` tokens (see chunked_tied_ce) —
    required for 32k single-chip training, profitable from ~16k.
    """
    return _make_step(
        lambda params, inputs: llama.forward(params, inputs, cfg),
        NamedSharding(mesh, batch_spec()),
        optimizer,
        hidden_fn=(lambda params, inputs: llama.forward_hidden(
            params, inputs, cfg)) if chunked_ce else None,
        ce_chunk=ce_chunk,
    )


def make_sp_train_step(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    axis_name: str = "sp",
    impl: str = "ulysses",
    chunked_ce: bool = False,
    ce_chunk: int = 1024,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, dict]]:
    """Jitted sequence-parallel training step for long contexts.

    Batch is (B, T+1) tokens, replicated — T+1 is ragged against the sp
    axis and token ints are negligible; llama.forward_sp pins the (B, T,
    D) activations to the sequence-sharded layout (batch over the
    mesh's dp/fsdp axes, sequence over sp), which is where the memory
    lives.  Attention runs the chosen strategy (ulysses | ring).

    Parameter layout is the init's choice, not this function's: pair
    with ``sharded_init(..., specs=llama.sp_param_specs(cfg))`` for
    replicated weights, or — the Llama-7B v5p-128 north-star layout —
    ``specs=llama.sp_fsdp_param_specs(cfg)`` on a
    ``make_sp_mesh(dp, sp, fsdp=n)`` mesh for ZeRO-3 weights + SP
    activations + dp×fsdp batch.  Either way gradients come back in the
    params' own sharding via the collectives GSPMD inserts (all-reduce
    for replicated, reduce-scatter for fsdp-sharded).
    ``chunked_ce`` applies the tied head per T-chunk on the (already
    T/n-per-device) hidden states — SP shrinks the resident logits by
    the axis degree, chunking bounds the transient too.
    """
    def fwd(params, inputs, **kw):
        return llama.forward_sp(params, inputs, cfg, mesh,
                                axis_name=axis_name, impl=impl, **kw)

    return _make_step(
        fwd,
        NamedSharding(mesh, P()),
        optimizer,
        hidden_fn=partial(fwd, return_hidden=True) if chunked_ce else None,
        ce_chunk=ce_chunk,
    )


def make_pp_train_step(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    n_microbatches: int,
    axis_name: str = "pp",
    chunked_ce: bool = False,
    ce_chunk: int = 1024,
    schedule: str = "gpipe",
) -> Callable[[TrainState, jax.Array], tuple[TrainState, dict]]:
    """Jitted training step through the microbatch pipeline.

    ``schedule="gpipe"``: the forward runs llama.forward_pipelined
    (decoder stack sharded over the pp axis, microbatches through the
    ppermute ring); reverse mode differentiates through the ppermutes
    so gradients flow stage-to-stage the way the activations came.

    ``schedule="1f1b"``: the step runs
    parallel.pipeline.pipeline_value_and_grad — forwards and backwards
    interleaved, loss computed inside the last stage, per-stage vjp
    with at most S saved stage inputs (GPipe saves M) — same losses,
    O(S) in-flight activation memory.  See _1f1b_body.

    Either way pair with ``sharded_init(..., specs=
    llama.pp_param_specs(cfg))``.
    """
    if schedule == "1f1b":
        return _make_1f1b_step(cfg, mesh, optimizer,
                               n_microbatches=n_microbatches,
                               axis_name=axis_name,
                               chunked_ce=chunked_ce, ce_chunk=ce_chunk)
    if schedule != "gpipe":
        raise ValueError(f"unknown pipeline schedule {schedule!r}")

    def fwd(params, inputs, **kw):
        return llama.forward_pipelined(
            params, inputs, cfg, mesh,
            n_microbatches=n_microbatches, axis_name=axis_name, **kw)

    return _make_step(
        fwd,
        NamedSharding(mesh, P()),  # stage 0 consumes the batch
        optimizer,
        hidden_fn=partial(fwd, return_hidden=True) if chunked_ce else None,
        ce_chunk=ce_chunk,
    )


def _make_1f1b_step(
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    *,
    n_microbatches: int,
    axis_name: str = "pp",
    chunked_ce: bool = False,
    ce_chunk: int = 1024,
) -> Callable[[TrainState, jax.Array], tuple[TrainState, dict]]:
    """1F1B training step: stage fns wrap the SAME llama layer body the
    other drivers use (llama.make_layer_body — remat policies included),
    the loss (optionally chunked tied-head CE) runs inside the last
    stage, and grads come back in the params' own layout."""
    M = n_microbatches

    def first_fn(extra, tokens_mb):
        return jnp.take(extra["embed"], tokens_mb, axis=0)

    def stage_fn(layers_local, x):
        cos, sin = llama.rope_table(cfg, x.shape[1])
        body = llama.make_layer_body(cfg, cos, sin)
        return jax.lax.scan(lambda h, lp: (body(h, lp), None),
                            x, layers_local)[0]

    def last_fn(extra, y, targets_mb):
        h = llama.rms_norm(y, extra["final_norm"], cfg.norm_eps,
                           cfg.use_fused_norm)
        if chunked_ce:
            loss = chunked_tied_ce(h, extra["embed"], targets_mb, ce_chunk)
        else:
            logits = jnp.einsum(
                "btd,vd->btv", h, extra["embed"]).astype(jnp.float32)
            loss = cross_entropy_loss(logits, targets_mb)
        # microbatch losses SUM across the schedule; pre-scaling by 1/M
        # makes that sum the global mean CE (equal microbatch sizes)
        return loss / M

    def step(state: TrainState, batch: jax.Array):
        inputs, targets = batch[:, :-1], batch[:, 1:]
        extra = {"embed": state.params["embed"],
                 "final_norm": state.params["final_norm"]}
        loss, g_layers, g_extra = pipeline_value_and_grad(
            state.params["layers"], extra, inputs, targets,
            first_fn=first_fn, stage_fn=stage_fn, last_fn=last_fn,
            mesh=mesh, n_microbatches=M, axis_name=axis_name)
        grads = {"embed": g_extra["embed"], "layers": g_layers,
                 "final_norm": g_extra["final_norm"]}
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        new_state = TrainState(params, opt_state, state.step + 1)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    return jax.jit(
        step,
        in_shardings=(None, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def with_step_profiler(
    step_fn: Callable,
    cfg: llama.LlamaConfig,
    mesh: Mesh,
    *,
    batch: int,
    seq_len: int,
    job: str = "",
    jsonl_path: str | None = None,
    on_record: Callable | None = None,
    window: int = 32,
):
    """Instrument any ``make_*_train_step`` product with telemetry.

    Returns ``(profiled_step, profiler)``: the wrapped step is a
    drop-in replacement (same signature/return, blocked on
    ``block_until_ready`` so timings cover device execution); the
    profiler exposes compile-vs-steady split, rolling tokens/sec and
    the analytic MFU estimate sized from ``cfg``/``mesh``
    (telemetry/step_timer.py).  ``jsonl_path`` appends one structured
    line per step for ``scripts/bench_trend.py``; ``on_record`` is the
    push hook (``telemetry.PushClient(...).on_record`` sends each step
    to the operator's /push/v1/metrics).
    """
    from pytorch_operator_tpu.telemetry import StepProfiler

    profiler = StepProfiler.for_llama(
        cfg, mesh, batch=batch, seq_len=seq_len, job=job,
        jsonl_path=jsonl_path, on_record=on_record, window=window)
    return profiler.wrap(step_fn), profiler
