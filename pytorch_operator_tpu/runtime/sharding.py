"""Active-active control-plane sharding: consistent-hash job shards
owned through per-shard Leases.

The reference operator scales writes with hot-standby leader election —
one replica reconciles everything, the rest idle (server.go:146-171).
This module replaces that with an active-active scheme:

  * every PyTorchJob hashes to one of N **shards**
    (:func:`shard_of` over ``namespace/uid`` — stable for the job's
    lifetime, recorded as the ``pytorch.kubeflow.org/shard`` label at
    admission);
  * each shard is owned through its own Lease
    (``pytorch-operator-shard-<i>``), acquired/renewed/released with the
    same :class:`~pytorch_operator_tpu.runtime.leader_election.LeaderElector`
    state machine leader election uses;
  * every replica runs a :class:`ShardManager` that advertises itself
    through a heartbeat Lease (``pytorch-operator-replica-<id>``),
    derives the live membership from those heartbeats, and acquires /
    voluntarily releases shard Leases until each live replica owns
    exactly its ranked floor/remainder quota — replicas joining or
    dying rebalance the ring without any central coordinator;
  * a replica's informers for an owned shard list+watch with the shard
    label selector (:class:`LabelFilteredSource` client-side for the
    in-memory fake, server-side ``labelSelector`` for the REST/stub
    tier), so a replica never deserializes another shard's objects.

Handoff safety: shard acquisition starts a FRESH ListWatch for the
shard (expectations are satisfied against live lists before any create
is issued), and pod/service names are deterministic, so a rebalance
mid-churn produces AlreadyExists conflicts at worst — never duplicate
pods.  The ``--shards`` bench tier measures exactly that through a
mid-storm replica kill.
"""

from __future__ import annotations

import hashlib
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..analysis.witness import make_lock
from ..k8s.errors import ApiError
from .leader_election import LeaderElector

#: default Lease-name prefixes (ISSUE 7 vocabulary)
SHARD_LEASE_PREFIX = "pytorch-operator-shard"
REPLICA_LEASE_PREFIX = "pytorch-operator-replica"


def shard_of(namespace: str, uid: str, shard_count: int) -> int:
    """Stable shard index for one job: blake2b of ``namespace/uid``
    modulo the shard count.  Hash-stable across processes and Python
    versions (never ``hash()``: PYTHONHASHSEED would reshard the fleet
    per restart)."""
    if shard_count <= 1:
        return 0
    digest = hashlib.blake2b(
        f"{namespace}/{uid}".encode(), digest_size=8).digest()
    return int.from_bytes(digest, "big") % shard_count


def shard_selector(shard: int) -> Dict[str, str]:
    """The label selector confining a list+watch to one shard."""
    from ..api.v1 import constants

    return {constants.LABEL_SHARD: str(shard)}


def sanitize_identity(identity: str) -> str:
    """A replica identity as a valid Lease name segment (RFC 1123)."""
    cleaned = re.sub(r"[^a-z0-9-]+", "-", identity.lower()).strip("-")
    return cleaned[:40] or "replica"


class LabelFilteredSource:
    """A store view confined to one label selector — the informer-source
    adapter for backends whose watch fan-out is not selector-aware (the
    in-memory FakeResourceStore).  ``list`` passes the selector to the
    underlying store (which filters authoritatively); watch events are
    filtered client-side by the same match; ``GAP`` passes through so
    relist healing still fires.  REST-tier informers should use
    ``RestCluster.filtered`` instead, which pushes the selector into the
    list+watch query string so filtering happens server-side."""

    def __init__(self, store, selector: Dict[str, str]):
        self._store = store
        self.selector = dict(selector)
        self.kind = getattr(store, "kind", "")
        self._wrappers: Dict[Callable, Callable] = {}

    def _matches(self, obj: dict) -> bool:
        labels = (obj.get("metadata") or {}).get("labels") or {}
        return all(labels.get(k) == v for k, v in self.selector.items())

    def list(self, namespace=None, label_selector=None) -> List[dict]:
        selector = dict(self.selector)
        if label_selector:
            selector.update(label_selector)
        return self._store.list(namespace=namespace,
                                label_selector=selector)

    def list_changes(self, since_rv):
        """Selector-filtered delta relist when the underlying store
        supports the watch-cache window (see FakeResourceStore)."""
        inner = getattr(self._store, "list_changes", None)
        if inner is None:
            return None
        changes = inner(since_rv)
        if changes is None:
            return None
        return changes._replace(
            items=[o for o in changes.items if self._matches(o)],
            deleted=[o for o in changes.deleted if self._matches(o)])

    def add_listener(self, fn: Callable[[str, dict], None]) -> None:
        def wrapper(event_type: str, obj: dict) -> None:
            if event_type == "GAP" or self._matches(obj):
                fn(event_type, obj)

        self._wrappers[fn] = wrapper
        self._store.add_listener(wrapper)

    def remove_listener(self, fn: Callable[[str, dict], None]) -> None:
        wrapper = self._wrappers.pop(fn, None)
        if wrapper is not None:
            self._store.remove_listener(wrapper)


def sharded_source(cluster, plural: str, shard: int):
    """A shard-confined informer source for ``plural`` on ``cluster``:
    server-side selector filtering when the backend supports it
    (``RestCluster.filtered`` — a fresh list+watch per acquisition, the
    handoff fencing the expectations machinery assumes), client-side
    :class:`LabelFilteredSource` otherwise (FakeCluster)."""
    selector = shard_selector(shard)
    filtered = getattr(cluster, "filtered", None)
    if filtered is not None:
        return filtered(plural, selector)
    return LabelFilteredSource(cluster.resource(plural), selector)


class ShardManager:
    """Own as many shard Leases as fairness allows; rebalance on
    membership change.

    One background thread ticks every ``renew_interval``:

      1. renew the replica's **heartbeat Lease** (membership signal);
      2. derive live members from all heartbeat Leases (a member is
         live while its record keeps changing within leaseDuration of
         local observation — the LeaderElector expiry rule);
      3. compute this replica's ranked quota (floor/remainder split —
         see :meth:`_quota`) and release the highest-indexed owned
         shards above it (empty-holder release, so the starved replica
         acquires immediately);
      4. observe every un-owned shard Lease (keeps foreign expiry
         clocks running) and acquire acquirable ones while under fair
         share, starting at an identity-dependent offset so contending
         replicas fan out over different shards first.

    ``on_acquired(shard)`` / ``on_released(shard)`` fire from the tick
    thread; the controller builds/tears down the shard's informer+queue
    runtime there.  ``kill()`` simulates a crash: stop ticking WITHOUT
    releasing, so survivors take over only after lease expiry — the
    path the handoff bench measures.
    """

    def __init__(
        self,
        lease_store,
        identity: str,
        shard_count: int,
        *,
        namespace: str = "default",
        lease_prefix: str = SHARD_LEASE_PREFIX,
        replica_prefix: str = REPLICA_LEASE_PREFIX,
        lease_duration: float = 15.0,
        renew_interval: float = 5.0,
        on_acquired: Optional[Callable[[int], None]] = None,
        on_released: Optional[Callable[[int], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.lease_store = lease_store
        self.identity = identity
        self.shard_count = max(1, int(shard_count))
        self.namespace = namespace
        self.lease_prefix = lease_prefix
        self.replica_prefix = replica_prefix
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.on_acquired = on_acquired
        self.on_released = on_released
        self.clock = clock
        from ..api.v1 import constants as _constants

        # role labels on every Lease we mint: membership scans LIST
        # with the heartbeat selector (server-side on the REST tier)
        # instead of deserializing every Lease in the namespace — at
        # fleet scale the namespace also holds one Lease per SHARD
        # plus whatever other controllers keep there
        self._electors: Dict[int, LeaderElector] = {
            i: LeaderElector(
                lease_store, identity, name=f"{lease_prefix}-{i}",
                namespace=namespace, lease_duration=lease_duration,
                renew_interval=renew_interval, clock=clock,
                labels={_constants.LABEL_LEASE_COMPONENT:
                        _constants.LEASE_COMPONENT_SHARD,
                        _constants.LABEL_SHARD: str(i)})
            for i in range(self.shard_count)
        }
        self._heartbeat_name = (
            f"{replica_prefix}-{sanitize_identity(identity)}")
        self._heartbeat = LeaderElector(
            lease_store, identity, name=self._heartbeat_name,
            namespace=namespace, lease_duration=lease_duration,
            renew_interval=renew_interval, clock=clock,
            labels={_constants.LABEL_LEASE_COMPONENT:
                    _constants.LEASE_COMPONENT_HEARTBEAT})
        # replica-lease name -> ((holder, renewTime), locally observed at)
        self._member_obs: Dict[str, Tuple[tuple, float]] = {}
        self._owned: Set[int] = set()
        self._lock = make_lock("shard-manager")
        self._stop = threading.Event()
        self._release_on_stop = True
        self._thread: Optional[threading.Thread] = None
        # deterministic identity-dependent scan offset: contending fresh
        # replicas start their acquisition sweep at different shards
        self._scan_offset = shard_of("", identity, self.shard_count)

    # -- state -------------------------------------------------------------
    def owned_shards(self) -> Set[int]:
        with self._lock:
            return set(self._owned)

    def _fire(self, hook: Optional[Callable[[int], None]],
              shard: int) -> None:
        if hook is None:
            return
        try:
            hook(shard)
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "shard %d ownership callback failed", shard, exc_info=True)

    def _mark_owned(self, shard: int, owned: bool) -> None:
        with self._lock:
            if owned:
                self._owned.add(shard)
            else:
                self._owned.discard(shard)

    # -- membership --------------------------------------------------------
    def live_members(self) -> Set[str]:
        """Identities of live replicas: every heartbeat Lease whose
        record changed within leaseDuration of local observation, plus
        always this replica itself."""
        from ..api.v1 import constants as _constants

        now = self.clock()
        members = {self.identity}
        try:
            # selector-scoped: only heartbeat Leases travel (labeled
            # at creation AND re-stamped on every renewal, so a
            # pre-label heartbeat becomes visible within one renew
            # interval of its replica upgrading).  An unlabeled
            # heartbeat is invisible only while its owner runs an old
            # build — that costs fairness (the unseen member's quota),
            # never safety: shard ownership is still CAS-guarded by
            # the per-shard Leases themselves.
            leases = self.lease_store.list(
                namespace=self.namespace,
                label_selector={_constants.LABEL_LEASE_COMPONENT:
                                _constants.LEASE_COMPONENT_HEARTBEAT})
        except ApiError:
            return members
        prefix = f"{self.replica_prefix}-"
        seen = set()
        for lease in leases:
            meta = lease.get("metadata") or {}
            name = meta.get("name", "")
            if not name.startswith(prefix):
                continue
            spec = lease.get("spec") or {}
            holder = spec.get("holderIdentity") or ""
            if not holder:
                continue
            record = (holder, spec.get("renewTime"))
            obs = self._member_obs.get(name)
            if obs is None or obs[0] != record:
                obs = (record, now)
                self._member_obs[name] = obs
            seen.add(name)
            duration = float(spec.get("leaseDurationSeconds")
                             or self.lease_duration)
            if now - obs[1] < duration:
                members.add(holder)
        for name in list(self._member_obs):
            if name not in seen:
                del self._member_obs[name]
        return members

    # -- the rebalance tick ------------------------------------------------
    def _quota(self, members) -> int:
        """This replica's shard quota under the floor/remainder split:
        members ranked by sorted identity, the first ``shards % members``
        get ``floor + 1``, the rest ``floor``.  A plain ceil-for-everyone
        share lets incumbents sit at ceil and strand a joiner at zero
        forever (4 shards / 3 replicas: ceil = 2, two incumbents hold
        2+2 and never release) — with ranked quotas every replica
        computes the same split from the same membership set, so the
        sum is exactly ``shard_count`` and everyone converges to a
        nonzero share."""
        ranked = sorted(members)
        count = max(1, len(ranked))
        base, remainder = divmod(self.shard_count, count)
        try:
            rank = ranked.index(self.identity)
        except ValueError:
            rank = count - 1
        return base + (1 if rank < remainder else 0)

    def tick(self) -> None:
        """One acquire/renew/release round (public so tests can drive
        the state machine with fake clocks, no thread)."""
        self._heartbeat.try_acquire_or_renew()
        members = self.live_members()
        fair = self._quota(members)
        owned = sorted(self.owned_shards())

        # renew what we own; a lost CAS means another replica took over
        for shard in list(owned):
            elector = self._electors[shard]
            if elector.try_acquire_or_renew():
                elector.is_leader = True
            else:
                elector.is_leader = False
                owned.remove(shard)
                self._mark_owned(shard, False)
                self._fire(self.on_released, shard)

        # release overage so joining replicas can pick shards up
        while len(owned) > fair:
            shard = owned.pop()  # highest index first: deterministic
            self._electors[shard].release()
            self._mark_owned(shard, False)
            self._fire(self.on_released, shard)

        # observe every foreign shard (expiry clocks keep running even
        # when fairness forbids acquiring), acquire while under fair
        for step in range(self.shard_count):
            shard = (self._scan_offset + step) % self.shard_count
            if shard in owned:
                continue
            elector = self._electors[shard]
            _holder, acquirable = elector.observe()
            if not acquirable or len(owned) >= fair:
                continue
            if elector.try_acquire_or_renew():
                elector.is_leader = True
                owned.append(shard)
                self._mark_owned(shard, True)
                self._fire(self.on_acquired, shard)

    # -- lifecycle ---------------------------------------------------------
    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        stop = stop_event or self._stop
        while not stop.is_set() and not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                import logging

                logging.getLogger(__name__).warning(
                    "shard manager tick failed", exc_info=True)
            # wait on OUR stop event (stop()/kill() set it and must wake
            # the thread immediately — a graceful release that dozes a
            # full renew_interval is a takeover delay for the survivors);
            # an external stop_event is noticed within one interval
            self._stop.wait(self.renew_interval)
        self._shutdown_leases()

    def _shutdown_leases(self) -> None:
        owned = sorted(self.owned_shards(), reverse=True)
        for shard in owned:
            if self._release_on_stop:
                self._electors[shard].release()
            else:
                self._electors[shard].is_leader = False
            self._mark_owned(shard, False)
            self._fire(self.on_released, shard)
        if self._release_on_stop:
            try:
                self.lease_store.delete(self.namespace,
                                        self._heartbeat_name)
            except ApiError:
                pass

    def start(self, stop_event: Optional[threading.Event] = None
              ) -> threading.Thread:
        self._thread = threading.Thread(
            target=self.run, args=(stop_event,), daemon=True,
            name=f"shard-manager-{sanitize_identity(self.identity)}")
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        """Graceful stop: release every owned shard Lease (empty
        holder) and delete the heartbeat, so survivors rebalance
        immediately."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        else:
            self._shutdown_leases()

    def kill(self) -> None:
        """Crash simulation: stop ticking WITHOUT releasing anything —
        the shards' Leases and the heartbeat simply stop renewing, and
        survivors take over after lease expiry."""
        self._release_on_stop = False
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


__all__ = [
    "LabelFilteredSource",
    "REPLICA_LEASE_PREFIX",
    "SHARD_LEASE_PREFIX",
    "ShardManager",
    "sanitize_identity",
    "shard_of",
    "shard_selector",
    "sharded_source",
]
