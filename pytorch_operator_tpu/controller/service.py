"""Service reconciliation.

The reference creates a headless Service only for the Master
(pkg/controller.v1/pytorch/service.go + controller.go:474-479).  The
TPU-native build creates one headless Service PER REPLICA — master and
every worker — because the PJRT/XRT rendezvous needs stable DNS for all
hosts in TPU_WORKER_HOSTNAMES before libtpu init (SURVEY.md §5
"distributed communication backend").
"""

from __future__ import annotations

from typing import List

from ..api.v1 import constants
from ..api.v1.types import PyTorchJob, ReplicaSpec
from ..runtime.controls import (
    submit_creates_with_expectations,
    submit_deletes_with_expectations,
)
from ..runtime.expectations import expectation_services_key
from ..runtime.job_controller import gen_general_name
from ..runtime.logger import logger_for_replica
from .tpu_env import get_port_from_job


class ServiceReconcilerMixin:
    def reconcile_services(
        self,
        job: PyTorchJob,
        job_dict: dict,
        services: List[dict],
        rtype: str,
        spec: ReplicaSpec,
    ) -> None:
        """service.go:36-71, generalized to any replica type; missing
        services are collected from the slice scan and submitted as one
        fan-out batch (see submit_service_creates)."""
        rt = rtype.lower()
        log = logger_for_replica(self.logger, job, rt)
        services = self.filter_services_for_replica_type(services, rt)
        replicas = int(spec.replicas or 0)
        service_slices = self.get_service_slices(services, replicas)
        planned: List[dict] = []
        for index, service_slice in enumerate(service_slices):
            if len(service_slice) > 1:
                log.warning("We have too many services for %s %d", rt, index)
            elif len(service_slice) == 0:
                log.info("Need to create new service: %s-%d", rt, index)
                planned.append(self.build_new_service(job, rtype, str(index)))
        if planned:
            self.submit_service_creates(job, job_dict, rtype, planned)

    def create_new_service(
        self, job: PyTorchJob, job_dict: dict, rtype: str, index: str
    ) -> None:
        """service.go:95-159 — compat single-service entry: a batch of
        one through the pipelined path."""
        service = self.build_new_service(job, rtype, index)
        self.submit_service_creates(job, job_dict, rtype, [service])

    def submit_service_creates(
        self, job: PyTorchJob, job_dict: dict, rtype: str, services: List[dict]
    ) -> None:
        """One fan-out batch of service creates; expectations raised
        up-front and rolled back per failed create (the divergence note
        in pod.py submit_pod_creates applies verbatim — a leaked
        expectation parks the job until the 5-minute TTL)."""
        submit_creates_with_expectations(
            self.expectations,
            expectation_services_key(job.key, rtype.lower()),
            self.service_control.create_many, job.metadata.namespace,
            services, job_dict, self.gen_owner_reference(job_dict))

    def submit_service_deletes(
        self, job: PyTorchJob, job_dict: dict, rtype: str,
        services: List[dict]
    ) -> None:
        """Delete-side mirror of submit_service_creates: one bounded
        fan-out batch with deletion expectations raised up-front and
        rolled back per failure (observed deletes decrement via the
        service informer's DELETED callback)."""
        names = [s.get("metadata", {}).get("name", "") for s in services]
        submit_deletes_with_expectations(
            self.expectations,
            expectation_services_key(job.key, rtype.lower()),
            self.service_control.delete_many, job.metadata.namespace,
            names, job_dict)

    def build_new_service(self, job: PyTorchJob, rtype: str, index: str) -> dict:
        """Render one replica's headless Service (pure; no API calls)."""
        rt = rtype.lower()
        labels = self.gen_labels(job.metadata.name)
        labels[constants.LABEL_REPLICA_TYPE] = rt
        labels[constants.LABEL_REPLICA_INDEX] = index

        # sharded control plane: the service's METADATA carries the
        # job's shard label (so shard-filtered informers see it); the
        # pod selector stays shard-free — it already names exactly one
        # replica, and widening it would strand pods created before the
        # job was stamped
        metadata_labels = dict(labels)
        job_labels = job.metadata.labels or {}
        for ring_key in (constants.LABEL_SHARD,
                         constants.LABEL_RING_EPOCH):
            if job_labels.get(ring_key) is not None:
                metadata_labels[ring_key] = job_labels[ring_key]

        port = get_port_from_job(job, constants.REPLICA_TYPE_MASTER)
        return {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {
                "name": gen_general_name(job.metadata.name, rt, index),
                "labels": metadata_labels,
            },
            "spec": {
                "clusterIP": "None",
                "selector": dict(labels),
                "ports": [{"name": constants.DEFAULT_PORT_NAME, "port": port}],
            },
        }
