"""Pipeline (pp) and expert (ep) parallelism tests on the 8-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_operator_tpu.models import llama, moe
from pytorch_operator_tpu.parallel import (
    make_named_mesh,
    make_pp_train_step,
    pipeline_apply,
    sharded_init,
)


def sequential(ws, x):
    h = x
    for i in range(ws.shape[0]):
        h = jnp.tanh(h @ ws[i])
    return h


def stage_fn(w_local, h):
    def body(h, w):
        return jnp.tanh(h @ w), None
    return jax.lax.scan(body, h, w_local)[0]


class TestPipeline:
    @pytest.mark.parametrize("pp,n_mb", [(2, 2), (4, 4), (4, 8), (8, 4)])
    def test_matches_sequential(self, pp, n_mb):
        mesh = make_named_mesh({"pp": pp})
        L, D, B = 2 * pp, 16, n_mb * 2
        ws = jax.random.normal(jax.random.key(0), (L, D, D)) * 0.3
        x = jax.random.normal(jax.random.key(1), (B, D))
        out = pipeline_apply(ws, x, stage_fn, mesh, n_microbatches=n_mb)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(sequential(ws, x)),
            atol=1e-5, rtol=1e-5)

    def test_grads_match_sequential(self):
        mesh = make_named_mesh({"pp": 4})
        L, D, B = 8, 8, 8
        ws = jax.random.normal(jax.random.key(0), (L, D, D)) * 0.3
        x = jax.random.normal(jax.random.key(1), (B, D))

        g1 = jax.grad(lambda w: jnp.sum(
            pipeline_apply(w, x, stage_fn, mesh, n_microbatches=4) ** 2))(ws)
        g2 = jax.grad(lambda w: jnp.sum(sequential(w, x) ** 2))(ws)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=1e-4, rtol=1e-3)

    def test_ragged_microbatch_raises(self):
        mesh = make_named_mesh({"pp": 2})
        ws = jnp.zeros((2, 4, 4))
        x = jnp.zeros((5, 4))
        with pytest.raises(ValueError, match="not divisible"):
            pipeline_apply(ws, x, stage_fn, mesh, n_microbatches=3)


class TestLlamaPipeline:
    """VERDICT r1 weakness 6: pp must run REAL Llama decoder blocks, not a
    toy tanh stage."""

    def test_forward_pipelined_matches_sequential(self):
        mesh = make_named_mesh({"pp": 4})
        cfg = llama.tiny(n_layers=8, max_seq_len=32)
        params = llama.init_params(jax.random.key(0), cfg)
        tokens = jax.random.randint(jax.random.key(1), (8, 32), 0,
                                    cfg.vocab_size)
        ref = llama.forward(params, tokens, cfg)
        out = llama.forward_pipelined(params, tokens, cfg, mesh,
                                      n_microbatches=4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-4, rtol=1e-3)

    def test_pp_train_step_matches_sequential_grads(self):
        mesh = make_named_mesh({"pp": 4})
        cfg = llama.tiny(n_layers=4, max_seq_len=16)
        optimizer = optax.sgd(1e-2)
        state = sharded_init(cfg, mesh, optimizer,
                             specs=llama.pp_param_specs(cfg))
        step = make_pp_train_step(cfg, mesh, optimizer, n_microbatches=2)
        batch = jax.random.randint(jax.random.key(2), (4, 17), 0,
                                   cfg.vocab_size)
        # reference grads through the sequential forward
        from pytorch_operator_tpu.parallel import cross_entropy_loss

        def ref_loss(params):
            logits = llama.forward(params, batch[:, :-1], cfg)
            return cross_entropy_loss(logits, batch[:, 1:])

        ref_grads = jax.grad(ref_loss)(jax.device_get(state.params))

        # pp grads equal sequential grads (GPipe is math-identical);
        # computed before step() because the jitted step donates state
        def pp_loss(params):
            logits = llama.forward_pipelined(params, batch[:, :-1], cfg,
                                             mesh, n_microbatches=2)
            return cross_entropy_loss(logits, batch[:, 1:])

        pp_grads = jax.grad(pp_loss)(state.params)
        for ref_leaf, pp_leaf in zip(jax.tree.leaves(ref_grads),
                                     jax.tree.leaves(pp_grads)):
            np.testing.assert_allclose(
                np.asarray(pp_leaf), np.asarray(ref_leaf),
                atol=5e-4, rtol=5e-3)

        state2, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        assert int(state2.step) == 1

    def test_pp_chunked_ce_matches_unchunked(self):
        """chunked CE composes with the pipeline step: identical loss to
        the unchunked head on the same init."""
        mesh = make_named_mesh({"pp": 4})
        cfg = llama.tiny(n_layers=4, max_seq_len=16)
        optimizer = optax.sgd(1e-2)
        batch = jax.random.randint(jax.random.key(3), (4, 17), 0,
                                   cfg.vocab_size)
        losses = []
        for chunked in (False, True):
            state = sharded_init(cfg, mesh, optimizer,
                                 specs=llama.pp_param_specs(cfg))
            step = make_pp_train_step(cfg, mesh, optimizer,
                                      n_microbatches=2,
                                      chunked_ce=chunked, ce_chunk=8)
            # two steps so the chunked BACKWARD (first update) is
            # checked via the second step's loss, not just the forward
            state, m1 = step(state, batch)
            state, m2 = step(state, batch)
            losses.append((float(m1["loss"]), float(m2["loss"]),
                           float(m1["grad_norm"])))
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-4)


class Test1F1B:
    """1F1B schedule (round-5 verdict item 9): interleaved fwd/bwd with
    per-stage vjp and O(S) saved activations must train identically to
    GPipe (which is math-identical to the sequential model)."""

    def _run(self, cfg, mesh, schedule, tokens, n_mb=4, steps=3, **kw):
        opt = optax.sgd(0.1)
        state = sharded_init(cfg, mesh, opt,
                             specs=llama.pp_param_specs(cfg))
        step = make_pp_train_step(cfg, mesh, opt, n_microbatches=n_mb,
                                  schedule=schedule, **kw)
        out = []
        for _ in range(steps):
            state, m = step(state, tokens)
            out.append((float(m["loss"]), float(m["grad_norm"])))
        return out

    @pytest.mark.parametrize("pp,n_mb", [(4, 4), (2, 6), (8, 8)])
    def test_matches_gpipe_multi_step(self, pp, n_mb):
        mesh = make_named_mesh({"pp": pp})
        cfg = llama.tiny(dim=64, n_layers=pp, n_heads=4, n_kv_heads=4,
                         ffn_dim=128, vocab_size=256, max_seq_len=16)
        tokens = jax.random.randint(jax.random.key(5), (n_mb * 2, 17), 0,
                                    cfg.vocab_size)
        a = self._run(cfg, mesh, "gpipe", tokens, n_mb=n_mb)
        b = self._run(cfg, mesh, "1f1b", tokens, n_mb=n_mb)
        # three steps: step N's loss depends on step N-1's grads, so a
        # wrong hand-scheduled backward diverges the sequences
        np.testing.assert_allclose(b, a, rtol=1e-4)

    def test_gqa_and_chunked_ce(self):
        mesh = make_named_mesh({"pp": 4})
        cfg = llama.tiny(dim=64, n_layers=4, n_heads=8, n_kv_heads=2,
                         ffn_dim=128, vocab_size=256, max_seq_len=16)
        tokens = jax.random.randint(jax.random.key(7), (8, 17), 0,
                                    cfg.vocab_size)
        a = self._run(cfg, mesh, "1f1b", tokens)
        b = self._run(cfg, mesh, "1f1b", tokens, chunked_ce=True,
                      ce_chunk=8)
        c = self._run(cfg, mesh, "gpipe", tokens)
        np.testing.assert_allclose(a, c, rtol=1e-4)
        np.testing.assert_allclose(b, c, rtol=1e-4)

    def test_remat_stage_body(self):
        """The 1F1B stages reuse llama.make_layer_body, so cfg.remat
        applies inside the hand-scheduled vjp too."""
        mesh = make_named_mesh({"pp": 2})
        cfg = llama.tiny(dim=32, n_layers=2, n_heads=4, n_kv_heads=4,
                         ffn_dim=64, vocab_size=128, max_seq_len=16,
                         remat=True)
        tokens = jax.random.randint(jax.random.key(9), (4, 17), 0,
                                    cfg.vocab_size)
        a = self._run(cfg, mesh, "1f1b", tokens, n_mb=2)
        cfg2 = llama.tiny(dim=32, n_layers=2, n_heads=4, n_kv_heads=4,
                          ffn_dim=64, vocab_size=128, max_seq_len=16)
        b = self._run(cfg2, mesh, "1f1b", tokens, n_mb=2)
        np.testing.assert_allclose(a, b, rtol=1e-4)

    def test_unknown_schedule_rejected(self):
        mesh = make_named_mesh({"pp": 2})
        cfg = llama.tiny(n_layers=2, max_seq_len=16)
        with pytest.raises(ValueError, match="unknown pipeline schedule"):
            make_pp_train_step(cfg, mesh, optax.sgd(0.1),
                               n_microbatches=2, schedule="2f2b")

    def test_saved_ring_is_stage_bounded(self):
        """The memory property: the per-stage save ring holds S slots,
        not M — visible in the jaxpr's buffer shapes."""
        from pytorch_operator_tpu.parallel import pipeline_value_and_grad

        mesh = make_named_mesh({"pp": 2})
        # activation width D_act differs from the token/target width so
        # an M-deep ACTIVATION buffer is distinguishable from the
        # (M, mb, D_in) microbatched inputs, which legitimately exist
        S, M, mb, D_in, D_act = 2, 8, 2, 4, 16

        def first_fn(extra, t):
            return t @ extra["w_in"]

        def stage_fn(p, x):
            return jax.lax.scan(
                lambda h, w: (jnp.tanh(h @ w), None), x, p)[0]

        def last_fn(extra, y, t):
            return jnp.sum((y @ extra["w_in"].T - t) ** 2) / M

        params = jax.random.normal(jax.random.key(0),
                                   (2, D_act, D_act)) * 0.3
        extra = {"w_in": jax.random.normal(jax.random.key(2),
                                           (D_in, D_act)) * 0.3}
        x = jax.random.normal(jax.random.key(1), (M * mb, D_in))
        jaxpr = jax.make_jaxpr(
            lambda p, e, a, b: pipeline_value_and_grad(
                p, e, a, b, first_fn=first_fn, stage_fn=stage_fn,
                last_fn=last_fn, mesh=mesh, n_microbatches=M))(
            params, extra, x, x)

        def all_shapes(jxp):
            for eqn in jxp.eqns:
                for v in eqn.outvars:
                    yield getattr(v.aval, "shape", ())
                for param in eqn.params.values():
                    inner = param
                    if hasattr(inner, "jaxpr"):  # ClosedJaxpr
                        inner = inner.jaxpr
                    if hasattr(inner, "eqns"):  # raw Jaxpr (shard_map)
                        yield from all_shapes(inner)

        shapes = list(all_shapes(jaxpr.jaxpr))
        # the save ring exists at S slots...
        assert any(s == (S, mb, D_act) for s in shapes), shapes[:20]
        # ...and no M-deep activation buffer does (GPipe would save M)
        assert not any(s[:1] == (M,) and s[1:] == (mb, D_act)
                       for s in shapes), (
            "found an M-deep activation buffer; 1F1B must save only S")


class TestMoE:
    def test_forward_shapes_and_aux(self):
        cfg = moe.tiny()
        params = moe.init_params(jax.random.key(0), cfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits, aux = moe.forward(params, tokens, cfg)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()
        # balanced routing aux is ~1; wildly unbalanced is ~n_experts
        assert 0.5 < float(aux) < cfg.n_experts + 1

    def test_top1_routing(self):
        cfg = moe.tiny(top_k=1)
        params = moe.init_params(jax.random.key(0), cfg)
        logits, _ = moe.forward(params, jnp.zeros((1, 8), jnp.int32), cfg)
        assert np.isfinite(np.asarray(logits)).all()

    def test_ep_sharded_training_converges(self):
        cfg = moe.tiny(n_experts=4)
        mesh = make_named_mesh({"dp": 2, "fsdp": 1, "tp": 2, "ep": 2})
        params = moe.init_params(jax.random.key(0), cfg)
        shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s), moe.param_specs(cfg))
        params = jax.device_put(params, shardings)
        opt = optax.adam(3e-3)
        opt_state = opt.init(params)
        batch = jax.device_put(
            jax.random.randint(jax.random.key(2), (4, 33), 0, cfg.vocab_size),
            NamedSharding(mesh, P(("dp", "fsdp"))))

        @jax.jit
        def step(params, opt_state):
            def loss_fn(p):
                logits, aux = moe.forward(p, batch[:, :-1], cfg)
                lp = jax.nn.log_softmax(logits)
                ce = -jnp.mean(jnp.take_along_axis(lp, batch[:, 1:, None], -1))
                return ce + 0.01 * aux
            loss, g = jax.value_and_grad(loss_fn)(params)
            u, opt_state = opt.update(g, opt_state)
            return optax.apply_updates(params, u), opt_state, loss

        losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses
        # expert bank is genuinely sharded over ep (and tp)
        wg = params["layers"]["w_gate"]
        assert wg.addressable_shards[0].data.size * 4 == wg.size

    def test_moe_params_superset_of_llama(self):
        cfg = moe.tiny()
        params = moe.init_params(jax.random.key(0), cfg)
        assert "router" in params["layers"]
        assert params["layers"]["w_gate"].shape[1] == cfg.n_experts
        specs = moe.param_specs(cfg)
        assert jax.tree.structure(params).num_leaves == \
            jax.tree.structure(specs, is_leaf=lambda x: x is None or hasattr(x, "index")).num_leaves
