"""Lease-based leader election.

The reference elects a leader with a deprecated Endpoints lock named
``pytorch-operator`` (15s lease / 5s renew / 3s retry,
cmd/pytorch-operator.v1/app/server.go:55-57,146-171); this is the same
state machine over the modern Lease object.  Only the elected replica
runs the controller workers; the ``pytorch_operator_is_leader`` gauge
(server.go:58-61) flips with leadership.

Works against any store with get/create/update (the fake cluster's
``resource("leases")`` or a real REST client).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from pytorch_operator_tpu.k8s.errors import AlreadyExistsError, ConflictError, NotFoundError

LEASE_DURATION = 15.0
RENEW_INTERVAL = 5.0
RETRY_INTERVAL = 3.0


class LeaderElector:
    def __init__(
        self,
        lease_store,
        identity: str,
        *,
        name: str = "pytorch-operator",
        namespace: str = "default",
        lease_duration: float = LEASE_DURATION,
        renew_interval: float = RENEW_INTERVAL,
        retry_interval: float = RETRY_INTERVAL,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.lease_store = lease_store
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        self.retry_interval = retry_interval
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock
        self.is_leader = False
        self._stop = threading.Event()
        self._active_stop = self._stop
        self._thread: Optional[threading.Thread] = None
        # client-go semantics: expiry is judged against the *local*
        # observation time of the last lease change, never by comparing
        # another process's timestamps with our clock (clocks across nodes
        # are not comparable; monotonic clocks especially so).
        self._observed_record: Optional[tuple] = None
        self._observed_at: float = 0.0

    # -- lease record helpers ---------------------------------------------

    def _lease_obj(self) -> dict:
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": {"name": self.name, "namespace": self.namespace},
            "spec": {
                "holderIdentity": self.identity,
                "leaseDurationSeconds": int(self.lease_duration),
                "renewTime": self.clock(),
            },
        }

    def try_acquire_or_renew(self) -> bool:
        """One CAS round: returns True if we hold the lease afterwards."""
        now = self.clock()
        try:
            lease = self.lease_store.get(self.namespace, self.name)
        except NotFoundError:
            try:
                self.lease_store.create(self.namespace, self._lease_obj())
                return True
            except AlreadyExistsError:
                return False
        spec = lease.get("spec") or {}
        holder = spec.get("holderIdentity")
        duration = float(spec.get("leaseDurationSeconds") or self.lease_duration)
        record = (holder, spec.get("renewTime"))
        if record != self._observed_record:
            self._observed_record = record
            self._observed_at = now
        if holder != self.identity and now - self._observed_at < duration:
            return False  # holder's record changed within leaseDuration (locally observed)
        lease["spec"] = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration),
            "renewTime": now,
        }
        try:
            updated = self.lease_store.update(lease)
            spec = updated.get("spec") or {}
            self._observed_record = (spec.get("holderIdentity"), spec.get("renewTime"))
            self._observed_at = now
            return True
        except (ConflictError, NotFoundError):
            return False

    # -- run loop ----------------------------------------------------------

    def run(self, stop_event: Optional[threading.Event] = None) -> None:
        """Block until stopped; invokes callbacks on leadership changes."""
        stop = stop_event or self._stop
        self._active_stop = stop
        while not stop.is_set():
            if self.try_acquire_or_renew():
                if not self.is_leader:
                    self.is_leader = True
                    if self.on_started_leading:
                        self.on_started_leading()
                interval = self.renew_interval
            else:
                if self.is_leader:
                    self.is_leader = False
                    if self.on_stopped_leading:
                        self.on_stopped_leading()
                interval = self.retry_interval
            stop.wait(interval)
        if self.is_leader:
            self.is_leader = False
            if self.on_stopped_leading:
                self.on_stopped_leading()

    def start(self, stop_event: Optional[threading.Event] = None) -> threading.Thread:
        self._thread = threading.Thread(
            target=self.run, args=(stop_event,), daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        self._active_stop.set()
        if self._thread:
            self._thread.join(timeout=5)
